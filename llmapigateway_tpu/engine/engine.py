"""The serving engine: slot-based continuous batching over compiled XLA
programs (chunked prefill + fused decode bursts + optional speculation).

Design (SURVEY.md §2b "Serving scheduler", §7 steps 5-6):

* **Fixed shapes everywhere.** Decode programs are compiled once for the
  full slot batch ``[B]``; inactive slots ride along masked (`active`), so
  admission/retirement never recompiles. Prefill is compiled per power-of-2
  chunk bucket, padded — pad tokens land beyond the true length and are
  masked off by the length-based causal mask, then overwritten by the next
  chunk. The first token is sampled INSIDE the prefill program (one host
  fetch completes the TTFT path).
* **Fused, lag-one-pipelined decode bursts.** A burst of decode steps is
  ONE ``lax.scan`` program (one dispatch, one fetch); burst N+1 dispatches
  before burst N's tokens are fetched, hiding the device→host round trip
  under compute. Two burst depths compile: the deep throughput burst and a
  shallow "busy" burst used while prefill work interleaves. Emission lags
  one burst; slot release/re-admission races are epoch-guarded
  (``_flush_entry``).
* **Deferred-insert decode.** Decode attention reads the STALE cache plus
  a self-column, and every layer's new K/V is inserted once per step
  outside the layer scan (models/llama.py ``insert_kv_stacked``) — the
  per-layer functional insert lowers to serialized TPU scatters.
* **Greedy fast path + speculation.** When every active slot decodes at
  temperature 0, an argmax-only program runs (no full-vocab sort), and
  with ``spec_draft_len`` set, prompt-lookup speculative bursts verify k
  drafted tokens per weight-streaming pass (engine/speculative.py).
* **Continuous batching.** New requests are admitted into free slots
  between bursts; prefill runs chunk-at-a-time so a long prompt never
  blocks decode for more than one chunk (chunked-prefill interleave).
* **The engine is an async service.** Compiled-program calls are offloaded
  to a worker thread (`asyncio.to_thread`) so the gateway's event loop keeps
  serving; results stream back through per-sequence asyncio queues.
* Per-slot sampling params live in device arrays; sampling is part of the
  decode program (no host round-trip per token beyond the sampled ids).

The serving KV layout is the paged pool (ops/paged_attention.py
``PagedKVCache`` + engine/paged.py allocator): admission reserves pages
for a request's whole lifetime — page exhaustion is backpressure at
admission, never a mid-generation failure — and the radix prefix cache
(engine/prefix_cache.py) reuses resident KV across requests: a prompt
whose prefix is resident maps the matched blocks into its page table and
starts prefill at the match boundary, skipping the matched span's FLOPs
outright (insert-on-release / LRU-by-leaf eviction / refcount pinning).
``kv_layout="contiguous"`` keeps the dense per-slot cache
(models/llama.py ``KVCache``) as a test-only numerical reference.

Two independent int8 precision knobs (models/quant.py): ``quant`` stores
every matmul weight as per-channel int8 (W8A8 on the MXU's native int8
path — decode is weight-bandwidth-bound, so ~2× tok/s) and ``kv_quant``
stores K/V as per-token int8 (halves KV bandwidth and capacity; both
layouts). Both are plain ``{"q","s"}`` dict leaves in the params/cache
pytrees, so sharding, scanning, and multihost transport treat them
uniformly.
"""
from __future__ import annotations

import asyncio
import logging
import time
from dataclasses import dataclass, field
from functools import partial
from typing import Any, AsyncIterator

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import NamedSharding, PartitionSpec as P

import os as _os
if _os.environ.get("JAX_PLATFORMS", "").strip().lower() == "cpu":
    # Honor JAX_PLATFORMS=cpu even where a site plugin re-forces the TPU
    # platform after env parsing (the config pin wins over the plugin;
    # the env var alone is overridden) — without this, a gateway started
    # for CPU operation hangs in TPU client init when the tunnel is down.
    jax.config.update("jax_platforms", "cpu")

from ..config.schemas import LocalEngineConfig
from ..models import forward_fn, init_fn, llama
from ..models.config import ModelConfig, get_preset
from ..obs.device import phase as _device_phase
from ..parallel.mesh import MeshSpec, build_mesh
from ..parallel.sharding import cache_sharding, param_shardings
from .sampling import SamplingParams, sample
from .tokenizer import IncrementalDetokenizer, load_tokenizer

logger = logging.getLogger(__name__)


class EngineOverloaded(Exception):
    """Admission failed (queue full) — maps to a provider error so the
    gateway falls back to the next provider in the chain."""


class EngineUnavailable(Exception):
    """Admission refused because the engine is draining, restarting, or
    failed (ISSUE 14). Maps to a retryable 503 in providers/local.py so
    the breaker opens and the router fails over to remote providers
    while the supervisor recovers the engine."""

    def __init__(self, message: str, retry_after_s: float | None = None):
        super().__init__(message)
        self.retry_after_s = retry_after_s


@dataclass
class FaultPlan:
    """Injectable engine faults (SURVEY.md §5 "failure detection / fault
    injection": the reference tested failures by hand-editing code —
    ``chat.py:143-144`` stubs; here they are first-class hooks). Attach via
    ``engine.fault_plan = FaultPlan(...)``; counters track trigger points.
    """
    fail_prefill_after: int = -1    # raise after N prefill chunks (-1 = off)
    fail_decode_after: int = -1     # raise after N decode bursts (-1 = off)
    slow_decode_s: float = 0.0      # added latency per decode burst
    # Supervision chaos hooks (ISSUE 14). fail_step_after raises at the
    # TOP of scheduler iteration N (before any admission/dispatch) with
    # fail_step_msg — put "RESOURCE_EXHAUSTED" in the message to fake an
    # HBM OOM (classified transient), or set fail_step_fatal to force
    # the fatal (no-restart) classification. fail_handoff_after raises
    # inside the disagg prefill→decode KV handoff. stall_step_after
    # freezes iteration N for stall_s WITHOUT raising — the silent-stall
    # shape only the watchdog can catch.
    fail_step_after: int = -1
    fail_step_fatal: bool = False
    fail_step_msg: str = "injected step fault"
    fail_handoff_after: int = -1
    stall_step_after: int = -1
    stall_s: float = 0.0
    prefill_calls: int = 0
    decode_calls: int = 0
    step_calls: int = 0
    handoff_calls: int = 0

    def on_prefill(self) -> None:
        self.prefill_calls += 1
        if 0 <= self.fail_prefill_after < self.prefill_calls:
            raise RuntimeError("injected prefill fault")

    def on_decode(self) -> None:
        self.decode_calls += 1
        if self.slow_decode_s > 0:
            time.sleep(self.slow_decode_s)
        if 0 <= self.fail_decode_after < self.decode_calls:
            raise RuntimeError("injected decode fault")

    def on_step(self) -> float:
        """Called at the top of every scheduler iteration. Returns the
        stall duration to sleep (0 = none); raises for step faults."""
        self.step_calls += 1
        if 0 <= self.fail_step_after < self.step_calls:
            if self.fail_step_fatal:
                raise ValueError(self.fail_step_msg)
            raise RuntimeError(self.fail_step_msg)
        if 0 <= self.stall_step_after < self.step_calls:
            return self.stall_s
        return 0.0

    def on_handoff(self) -> None:
        self.handoff_calls += 1
        if 0 <= self.fail_handoff_after < self.handoff_calls:
            raise RuntimeError("injected handoff fault")


@dataclass
class GenRequest:
    """One sequence's lifecycle inside the engine."""
    prompt_ids: list[int]
    max_tokens: int
    temperature: float = 0.0
    top_p: float = 1.0
    top_k: int = 0
    presence_penalty: float = 0.0     # OpenAI semantics; engine-native
    frequency_penalty: float = 0.0    # (engine/sampling.py apply_penalties)
    stop: list[str] = field(default_factory=list)
    # Gateway request id (providers/local.py sets it from the active
    # trace) — what the flight recorder's lifecycle records carry, so a
    # scheduler timeline row links back to /v1/api/trace/{id}.
    request_id: str = ""
    # Per-request SLO targets in ms (obs/slo.py; None = no target). The
    # outcome is computed at stream end from the timestamps below and
    # attributed against the flight recorder's step records.
    slo_ttft_ms: float | None = None
    slo_tpot_ms: float | None = None

    # Filled by the engine:
    slot: int = -1
    prefill_pos: int = 0
    # Prefix-cache hit accounting (ISSUE 6): tokens whose prefill was
    # skipped because their KV blocks were resident, the radix nodes
    # pinned for this request's lifetime, and the lookup's wall cost
    # (None = the cache was never consulted — disabled or bypassed).
    cached_tokens: int = 0
    prefix_nodes: list = field(default_factory=list)
    prefix_lookup_ms: float | None = None
    generated: list[int] = field(default_factory=list)
    out_queue: asyncio.Queue = field(default_factory=asyncio.Queue)
    detok: IncrementalDetokenizer | None = None
    text: str = ""
    emitted_upto: int = 0          # index into `text` already sent downstream
    cancelled: bool = False        # client gone — stop generating, free slot
    finish_reason: str | None = None
    t_submit: float = field(default_factory=time.monotonic)
    t_admitted: float | None = None   # slot admission (queued-phase end)
    t_first_token: float | None = None
    t_done: float | None = None
    # Flight-recorder cross-links (ISSUE 7): the seq numbers of this
    # request's admit/finish records, surfaced as trace-span attributes.
    flight_admit_seq: int = -1
    flight_done_seq: int = -1
    # Disaggregated serving (ISSUE 13): which pool currently owns the
    # request (obs.flight POOL_* tag; 0 on a unified engine), the decode
    # slot reserved at admission for the prefill→decode handoff (-1 =
    # none; equals `slot` after the handoff or on direct-to-decode
    # admissions), and whether goodput admission flagged this request
    # as TTFT-clamped (burst depth held at the busy/interleave rung
    # until its first token).
    pool: int = 0
    decode_slot: int = -1
    disagg_clamped: bool = False

    @property
    def done(self) -> bool:
        return self.finish_reason is not None


@dataclass
class Delta:
    """One streamed event: text delta and/or terminal state."""
    text: str = ""
    finish_reason: str | None = None
    error: str | None = None


def _kernel_cost_fn(fn, args):
    """AOT ``lower().compile().cost_analysis()`` closure for the kernel
    registry (obs/device.py): capture the call's AVALS now — metadata
    only; holding the real arrays would pin donated buffers — and do the
    lower/compile/analyze later on the registry's resolver thread (an 8B
    lower costs seconds; the persistent compilation cache makes the
    compile itself a lookup)."""
    def aval(x):
        return jax.ShapeDtypeStruct(
            np.shape(x), getattr(x, "dtype", None) or np.asarray(x).dtype,
            sharding=getattr(x, "sharding", None))
    avals = jax.tree.map(aval, args)

    def cost():
        return fn.lower(*avals).compile().cost_analysis()
    return cost


def _start_host_copy(arr) -> None:
    """Kick off an async device→host copy so the transfer overlaps the
    next dispatched burst. Purely an overlap optimization: backends
    without async copies raise assorted exception types here, and the
    later ``np.asarray`` fetch pays the synchronous copy instead —
    correctness is unaffected, so there is nothing useful to log per
    decode step."""
    try:
        arr.copy_to_host_async()
    except Exception:  # graftlint: disable=exception-hygiene — best-effort prefetch, sync fallback is correct
        pass


class InferenceEngine:
    """Owns params, cache, compiled programs, and the batching loop."""

    def __init__(self, engine_cfg: LocalEngineConfig,
                 model_cfg: ModelConfig | None = None,
                 devices: list | None = None):
        self.cfg = engine_cfg
        # Compile monitor FIRST (ISSUE 8): the engine build's own
        # compiles must count under the "startup" phase — installing
        # after init would misattribute nothing-at-all for them and make
        # the recompile telemetry start from a lie.
        from ..obs.device import install_compile_monitor
        install_compile_monitor()
        if model_cfg is None:
            if engine_cfg.preset:
                model_cfg = get_preset(engine_cfg.preset)
            elif engine_cfg.model_path:
                model_cfg = _config_from_checkpoint(engine_cfg.model_path)
            else:
                raise ValueError("local engine needs 'preset' or 'model_path'")
        self.model_cfg = model_cfg
        self.dtype = jnp.bfloat16 if engine_cfg.dtype == "bfloat16" else \
            jnp.dtype(engine_cfg.dtype)

        self.mesh = build_mesh(MeshSpec(sizes=dict(engine_cfg.mesh)), devices)
        self.B = engine_cfg.max_batch_size
        self.S = min(engine_cfg.max_seq_len, model_cfg.max_seq_len)
        self.prefill_chunk = engine_cfg.prefill_chunk
        # Batched-admission K rungs (schemas.LocalEngineConfig
        # .prefill_batch): group sizes the prefill program compiles for,
        # snapped down from the number of same-bucket queued admissions.
        self._prefill_k_rungs = tuple(
            k for k in (8, 4, 2, 1)
            if k <= max(1, min(engine_cfg.prefill_batch, self.B)))
        self.decode_burst = max(1, engine_cfg.decode_burst)
        self.decode_burst_busy = max(1, min(engine_cfg.decode_burst_busy,
                                            self.decode_burst))
        self.ttft_target_ms = max(0.0, engine_cfg.ttft_target_ms)
        # Depths the fused decode scans are compiled for (lazily, on first
        # use). With a TTFT target the 3/4, 1/2 and 1/4 rungs give the
        # adaptive cap real landing spots between deep and busy — the
        # cap snaps DOWN to a compiled depth, so a coarse ladder forfeits
        # throughput (e.g. a 26-step budget truncated to 16 when 24
        # exists ≈ +8% exposure headroom converted to tok/s); each rung
        # costs one lazily-compiled scan program.
        self._burst_depths = {self.decode_burst, self.decode_burst_busy}
        if self.ttft_target_ms > 0:
            for frac in (2, 4):
                self._burst_depths.add(max(1, self.decode_burst // frac))
            self._burst_depths.add(max(1, 3 * self.decode_burst // 4))
        self._burst_depths = tuple(sorted(self._burst_depths))
        if engine_cfg.kv_layout not in ("contiguous", "paged"):
            raise ValueError(f"unknown kv_layout {engine_cfg.kv_layout!r}")
        self.paged = engine_cfg.kv_layout == "paged"
        # Effective page size, clamped to the cache extent: a page larger
        # than S would waste a whole-page tail per slot (with paged now
        # the DEFAULT layout, small test/dev engines would otherwise carry
        # 256-token pages for 64-token contexts).
        self.kv_page = max(1, min(engine_cfg.kv_page_size, self.S))
        self._swa_ring_pages = 0        # set by the paged+SWA init branch
        self._swa_margin = 0            # in-flight burst margin, tokens
        # Sequence parallelism (SURVEY.md §5 long-context): with a `seq`
        # mesh axis, the KV cache's S dim is sharded across chips and
        # prefill runs ONE whole-prompt ring-attention program instead of
        # chunk-at-a-time (a chunk's KV insert would straddle shards; the
        # ring sees every block exactly once with compute/ICI overlap).
        self.seq_n = self.mesh.shape.get("seq", 1)
        self.seq_attention = engine_cfg.seq_attention
        if self.seq_n > 1:
            if self.paged:
                # Paged × seq: the pool's page dim shards over `seq` with
                # POSITION-BANDED allocation (engine/paged.py) so every
                # chip's S-shard of the gathered dense view reads only
                # local pages; band boundaries must fall on pages.
                if self.S % (self.seq_n * self.kv_page):
                    raise ValueError(
                        f"paged × seq needs max_seq_len {self.S} divisible "
                        f"by seq × page = "
                        f"{self.seq_n * self.kv_page}")
                # (SWA × seq — paged or not — is rejected by the
                # sliding-window guardrail below.)
            if self.S % self.seq_n:
                raise ValueError(
                    f"max_seq_len {self.S} must be divisible by the seq "
                    f"axis size {self.seq_n}")
            if self.seq_attention not in ("ring", "ulysses"):
                raise ValueError(
                    f"unknown seq_attention {self.seq_attention!r}; "
                    f"expected ring | ulysses")
            if self.seq_attention == "ulysses" and (
                    model_cfg.n_heads % self.seq_n
                    or model_cfg.n_kv_heads % self.seq_n):
                # Ulysses all-to-alls the head dim across the seq axis —
                # impossible when heads don't divide. Ring is always legal;
                # fall back rather than refuse the whole engine.
                logger.warning(
                    "seq_attention=ulysses needs heads divisible by the "
                    "seq axis (H=%d, KV=%d, seq=%d); falling back to ring",
                    model_cfg.n_heads, model_cfg.n_kv_heads, self.seq_n)
                self.seq_attention = "ring"
            # One prefill program covering the whole prompt: chunking is
            # disabled (TTFT tradeoff: a long prompt occupies the engine
            # for one full-prefill program instead of interleaving).
            self.prefill_chunk = self.S

        # Multi-host: process 0 runs the scheduler and publishes every
        # compiled-program call; followers replay (parallel/multihost.py).
        # Paged layout: the page table rides the command stream (followers
        # have no allocator), sized here so the wire width is fixed.
        from ..parallel.multihost import HostBridge
        page = self.kv_page
        self._bridge = HostBridge(
            self.B, self.prefill_chunk,
            table_slots=(self.S + page - 1) // page if self.paged else 0)
        self._published_table: np.ndarray | None = None
        # Pipeline parallelism: with a `pipe` axis the compiled programs run
        # the GPipe schedule (parallel/pipeline.py) — params and KV cache
        # shard their layer dim per stage, activations hop stage-to-stage
        # via ppermute. Decode splits the slot batch into `pipe`
        # microbatches when divisible (else M=1: correct, bubble-heavy).
        self.pipe_n = self.mesh.shape.get("pipe", 1)
        if self.pipe_n > 1:
            if self.seq_n > 1:
                raise ValueError("mesh axes pipe and seq cannot be "
                                 "combined (pick PP or SP, not both)")
            if model_cfg.n_layers % self.pipe_n:
                raise ValueError(
                    f"n_layers {model_cfg.n_layers} not divisible by "
                    f"pipe={self.pipe_n} stages")

        # Int8 weight quantization (models/quant.py): validated here so a
        # bad config fails at engine build (→ provider error → fallback),
        # not mid-load.
        from ..models.quant import QUANT_MODES
        self.quant = engine_cfg.quant
        if self.quant not in QUANT_MODES:
            raise ValueError(f"unknown quant {self.quant!r}; "
                             f"expected one of {QUANT_MODES}")
        # KV-cache quantization (int8 K/V + per-token scales).
        self.kv_quant = engine_cfg.kv_quant
        if self.kv_quant not in ("", "int8"):
            raise ValueError(f"unknown kv_quant {self.kv_quant!r}; "
                             f"expected '' | 'int8'")
        if self.kv_quant:
            # Composes with seq sharding (ring/ulysses attend fresh q/k/v;
            # the S-sharded insert/decode paths are quantization-aware)
            # AND with pipeline sharding (the staged block tree-maps its
            # batch slicing over {q, s} cache leaves — parallel/
            # pipeline.py, closing VERDICT r3 item 7).
            # Speculative decoding composes since the verify self-block
            # went mixed-precision (models/llama.py dense_verify_attention
            # + the paged deferred verify): drafted tokens at u < t go
            # through the same quantize→dequantize the insert path
            # applies, the diagonal stays full precision like the decode
            # self-column — so greedy output with spec on is exactly the
            # spec-off sequence. Two combos remain unimplemented, both
            # because their verify rides the insert-then-attend chunk
            # path (no ``.verify`` provider), which reads even the draft
            # self token quantized: the seq-sharded PAGED engine, and any
            # pipeline-sharded engine (parallel/pipeline.py stage blocks
            # verify as a chunk by design).
            if (engine_cfg.spec_draft_len and self.paged
                    and self.seq_n > 1):
                raise ValueError(
                    "kv_quant='int8' + spec_draft_len + seq-sharded "
                    "paged cache is not supported: the seq-paged verify "
                    "rides the chunk path, which reads the draft self "
                    "token quantized (breaking exact-greedy parity). "
                    "Use kv_layout='contiguous' with seq sharding, or "
                    "drop seq sharding for the paged layout")
            if engine_cfg.spec_draft_len and self.pipe_n > 1:
                raise ValueError(
                    "kv_quant='int8' + spec_draft_len + pipeline "
                    "sharding is not supported: the staged block "
                    "verifies drafts on the chunk path, which reads the "
                    "draft self token quantized (breaking exact-greedy "
                    "parity). Drop pipe sharding or kv_quant for "
                    "speculative runs")

        # Sliding-window attention (mistral family): the windowed dense
        # paths, the windowed flash kernels, AND the windowed paged
        # kernels all carry the bound — a windowed paged decode reads
        # O(window) *pages* (ops/paged_attention.py), compounding the SWA
        # bandwidth win with paging's capacity win. Full GSPMD DP/TP/PP
        # and speculation compose. Only seq sharding is excluded:
        # ring/ulysses attention is unwindowed (and a 4k-window model
        # has no sequence long enough to need S sharded).
        if model_cfg.sliding_window and self.seq_n > 1:
            raise ValueError(
                "sliding-window models do not compose with seq "
                "sharding (v1: ring/ulysses attention is unwindowed)")

        # Prompt-lookup speculative decoding (engine/speculative.py).
        self.spec_k = max(0, engine_cfg.spec_draft_len)
        if self.spec_k:
            if self.spec_k not in (1, 3, 7):
                raise ValueError(
                    f"spec_draft_len must be one of 1, 3, 7 (verify width "
                    f"k+1 must be a power of two), got {self.spec_k}")
            # Multihost composes: OP_SPEC rides the command stream, every
            # process maintains a bit-identical hist mirror, and the
            # data-dependent advances are derived on each host from its
            # own fetch of the same emitted matrix (parallel/multihost.py
            # wire-format notes).

        self.tokenizer = load_tokenizer(
            engine_cfg.tokenizer_path or engine_cfg.model_path or None,
            vocab_size=model_cfg.vocab_size)

        self.fault_plan: FaultPlan | None = None
        self._prev_debug_nans: bool | None = None
        self._enable_debug_nans()
        _enable_compilation_cache(engine_cfg.compilation_cache_dir)

        t0 = time.monotonic()
        self._init_params()
        t1 = time.monotonic()
        self._init_state()
        self._compile()
        logger.info("engine build: params %.1fs, state+programs %.1fs "
                    "(programs compile lazily on first call)",
                    t1 - t0, time.monotonic() - t1)

        # Scheduler state is event-loop-thread ONLY (asyncio.Queue and the
        # slot maps are not thread-safe; worker-thread calls touch device
        # programs and host numpy mirrors, never these) — the `guarded-by:
        # loop` marks make graftlint's lock-discipline rule enforce that.
        self._queue: asyncio.Queue[GenRequest] = asyncio.Queue(
            maxsize=max(2 * self.B, 16))                # guarded-by: loop
        self._head: GenRequest | None = None            # guarded-by: loop
        # Slot ownership lives in SlotPool objects (engine/disagg.py,
        # ISSUE 13): ONE pool spanning every slot for the unified
        # scheduler, or a prefill + decode pair sharing this mesh and KV
        # pool in disaggregated mode — where admission reserves a decode
        # slot up front and prompt completion hands the KV over by page
        # refcount transfer (_handoff), never by device copy.
        from .disagg import DisaggController, build_pools
        self._disagg: DisaggController | None = None
        if engine_cfg.disaggregation.enabled:
            self._disagg = DisaggController(
                self, engine_cfg.disaggregation)
            self._pools = self._disagg.pools            # guarded-by: loop
        else:
            self._pools = build_pools(self.B)           # guarded-by: loop
        self._pool_by_slot = {s: p for p in self._pools
                              for s in p.slots}
        self._admit_pool = self._pools[0]     # prefill pool when disagg
        self._decode_pool = self._pools[-1]   # same object when unified
        self._running: dict[int, GenRequest] = {}       # guarded-by: loop
        self._prefilling: dict[int, GenRequest] = {}    # guarded-by: loop
        self._loop_task: asyncio.Task | None = None
        self._stopped = False
        self._work_event = asyncio.Event()
        self._loop = None               # the loop _work_event is bound to
        self._warm_thread = None
        # Scheduler flight recorder (ISSUE 7): per-step and lifecycle
        # records in a preallocated ring, appended only from the loop
        # thread (its fields are `guarded-by: loop`; the sanitizer
        # instruments the class). None = disabled (flight_ring_size 0).
        from ..obs.flight import FlightRecorder
        self.flight = (FlightRecorder(engine_cfg.flight_ring_size)
                       if engine_cfg.flight_ring_size > 0 else None)
        # Device observability plane (ISSUE 8): per-kernel cost registry
        # (worker thread records, lock-guarded internally), the HBM
        # memory ledger, and the process-wide XLA compile monitor. The
        # ledger's watermark feeds submit()'s shed path so admission
        # reacts to device memory pressure, not just slots/pages.
        from ..obs.device import HbmLedger, KernelRegistry
        self.profile_annotations = bool(engine_cfg.profile_annotations)
        self.kernels = KernelRegistry()
        self.ledger: HbmLedger = self._build_ledger()
        self._watermark_sheds = 0                       # guarded-by: loop
        # Engine supervision (ISSUE 14): lifecycle state machine +
        # heartbeat/watchdog/backoff bookkeeping. Transitions echo into
        # the flight ring as SUPERVISOR records so an incident reads off
        # the same timeline as the steps it interrupted.
        from ..reliability.supervisor import EngineSupervisor
        sup = engine_cfg.supervisor
        self.supervisor = EngineSupervisor(
            watchdog_ms=sup.watchdog_ms, max_restarts=sup.max_restarts,
            backoff_ms=sup.backoff_ms, backoff_max_ms=sup.backoff_max_ms,
            drain_deadline_ms=sup.drain_deadline_ms,
            on_transition=self._on_lifecycle_transition)
        self._watchdog_task: asyncio.Task | None = None
        self._clean_steps = 0                           # guarded-by: loop

    def _on_lifecycle_transition(self, frm: str, to: str,
                                 reason: str) -> None:
        """Supervisor transition hook: mirror the lifecycle edge into
        the flight ring (kind SUPERVISOR, flag = state entered)."""
        if self.flight is None:
            return
        from ..obs.flight import SUPERVISOR, SUPERVISOR_STATES
        try:
            idx = SUPERVISOR_STATES.index(to)
        except ValueError:
            idx = 0
        self.flight.record(SUPERVISOR, flag=idx, rid=reason or frm)

    # -- initialization ------------------------------------------------------
    def _init_params(self) -> None:
        c = self.model_cfg
        t0 = time.monotonic()
        from ..parallel.multihost import put_global
        if self.cfg.model_path:
            from .checkpoint import _np_dtype, load_checkpoint
            from ..parallel.sharding import spec_for_param
            from ..models.quant import (QUANT_TOP_KEYS, _np_quantize,
                                        quantizes, weight_bits)

            def put(path: str, arr: np.ndarray) -> jax.Array:
                # ".q"/".s" quantized sub-leaves get their own rules.
                return put_global(
                    arr, spec_for_param(path, tuple(arr.shape), self.mesh))

            def preprocess(path: str, arr: np.ndarray):
                # quant="int8": quantize each tensor at the checkpoint's
                # SOURCE precision (not a bf16-rounded copy), per layer,
                # before stacking — the host stacks and transfers the int8
                # copy, halving both footprints.
                if self.quant and quantizes(path):
                    return _np_quantize(
                        arr, 1 if path in QUANT_TOP_KEYS else 0,
                        bits=weight_bits(self.quant, path))
                return arr.astype(_np_dtype(self.dtype))
            self.params = load_checkpoint(self.cfg.model_path, c,
                                          dtype=self.dtype, put=put,
                                          preprocess=preprocess)
            if (self.quant and c.tie_embeddings
                    and "lm_head_q8" not in self.params):
                # Tied checkpoints ship no lm_head tensor, so the preprocess
                # hook never saw one to quantize — build the int8 head copy
                # (models/quant.py quantize_tree rationale) from the placed
                # embed on device; out_shardings keep it in lm_head layout.
                from functools import partial
                from ..models.quant import quantize_array
                emb = self.params["embed"]
                out_sh = {
                    "q": spec_for_param("lm_head_q8.q", tuple(emb.shape),
                                        self.mesh),
                    "s": spec_for_param("lm_head_q8.s", (emb.shape[0],),
                                        self.mesh)}
                self.params["lm_head_q8"] = jax.jit(
                    partial(quantize_array, contract_axis=1),
                    out_shardings=out_sh)(emb)
        else:
            # Random init as ONE jitted program with sharded outputs:
            # params materialize directly in their GSPMD layout (no host
            # copy, no host→device transfer), and the whole init lands in
            # the persistent compilation cache — the eager per-op form
            # compiled ~10 one-off programs on every cold start. Multihost:
            # same program + same key on every process → identical values,
            # each process computing only its addressable shards.
            def build(k):
                p = init_fn(c)(c, k, dtype=self.dtype)
                if self.quant:
                    from ..models.quant import quantize_tree
                    p = quantize_tree(p, c, self.quant)
                return p
            key = jax.random.PRNGKey(0)
            shapes = jax.eval_shape(build, key)
            shardings = param_shardings(shapes, self.mesh)
            self.params = jax.jit(build, out_shardings=shardings)(key)
            jax.block_until_ready(self.params)
        n_params = sum(int(np.prod(p.shape))
                       for p in jax.tree.leaves(self.params))
        logger.info("params ready: %.2fB parameters in %.1fs",
                    n_params / 1e9, time.monotonic() - t0)

    def _init_state(self) -> None:
        c = self.model_cfg
        self.kv_ppb = 1          # multi-page kernel blocking (paged only)
        self._prefix_cache = None       # guarded-by: loop
        if self.paged:
            from ..parallel.sharding import paged_cache_sharding
            from ..ops.paged_attention import PagedKVCache
            from .paged import PageAllocator

            page = self.kv_page
            per_slot = (self.S + page - 1) // page
            n_bands = self.seq_n if self.seq_n > 1 else 1
            # Sliding-window RING reservation (single host/stage/band):
            # the windowed kernels never read below pos − window, so a
            # ring of O(window) physical pages serves ANY context length —
            # ensure_mapped recycles each slot's oldest dead page onto the
            # next logical page (mistral's rolling buffer, at page
            # granularity). Margins: in-flight lag-one bursts may still
            # read one burst below the current floor, and dispatch writes
            # run one burst/chunk ahead.
            if (c.sliding_window and self.mesh.size == 1
                    and self.pipe_n == 1 and n_bands == 1
                    and not self._bridge.enabled):
                # ONE copy of the margin: _swa_rotate's recycle floor
                # must stay in lockstep with the capacity the ring was
                # sized for, or rotation exhausts mid-stream.
                self._swa_margin = self.decode_burst * (self.spec_k + 1)
                span = max(self.prefill_chunk, self._swa_margin)
                ring = -(-(c.sliding_window + self._swa_margin + span)
                         // page) + 2
                if ring < per_slot:
                    self._swa_ring_pages = ring
                    logger.info(
                        "paged SWA ring: %d pages/slot (window %d) instead "
                        "of %d — steady-state KV footprint is O(window)",
                        ring, c.sliding_window, per_slot)
            # Multi-page kernel blocking (kv_pages_per_block): resolve the
            # requested run length against what the pool can actually
            # pack — the allocator's superpage runs are what license the
            # kernels' gather-free index maps, so any geometry the
            # allocator can't pack falls back to per-page blocks instead
            # of serving wrong reads.
            ppb_req = max(1, self.cfg.kv_pages_per_block)
            if ppb_req > 1:
                why = None
                if n_bands > 1:
                    why = "seq-banded pool (positions band per chip)"
                elif self._swa_ring_pages:
                    why = "SWA page ring (mappings rotate per page)"
                elif per_slot % ppb_req:
                    why = (f"pages per slot ({per_slot}) not divisible "
                           f"by {ppb_req}")
                elif (self.cfg.kv_num_pages
                      and self.cfg.kv_num_pages % ppb_req):
                    why = (f"kv_num_pages ({self.cfg.kv_num_pages}) not "
                           f"divisible by {ppb_req}")
                if why is None:
                    self.kv_ppb = ppb_req
                else:
                    logger.warning(
                        "kv_pages_per_block=%d falls back to per-page "
                        "blocks: %s", ppb_req, why)
            # One trash page per band (seq-sharded pools redirect masked
            # writes shard-locally); a PACKED pool reserves the whole
            # trash superpage instead.
            n_trash = self.kv_ppb if self.kv_ppb > 1 else n_bands
            num_pages = self.cfg.kv_num_pages or (
                self.B * per_slot + n_trash)
            min_hold = self._swa_ring_pages or per_slot
            if num_pages - n_trash < min_hold:
                raise ValueError(
                    f"kv_num_pages={num_pages} cannot hold one "
                    f"max-footprint sequence ({min_hold} pages of {page})")
            self.allocator = PageAllocator(num_pages, page, self.B, self.S,
                                           n_bands=n_bands,
                                           pages_per_block=self.kv_ppb)
            # Radix prefix cache (ISSUE 6): cross-request KV reuse over
            # the pool, block = one superpage run so the multi-page
            # kernels apply to shared pages unchanged. Gated to the
            # geometries where page identity is stable for a sequence's
            # lifetime: single-band (a banded pool's pages are
            # chip-local), non-SWA (ring rotation re-targets pages;
            # windowed attention never re-reads old prefixes anyway),
            # single-host (followers replay the broadcast table but hold
            # no allocator/cache state to mirror the index).
            if (self.cfg.prefix_cache and n_bands == 1
                    and not self._swa_ring_pages and not c.sliding_window
                    and not self._bridge.enabled):
                from .prefix_cache import RadixPrefixCache
                self._prefix_cache = RadixPrefixCache(
                    self.allocator, block_tokens=self.kv_ppb * page)
            psh = paged_cache_sharding(
                self.mesh, c.n_kv_heads,
                n_layers=c.n_layers if self.pipe_n > 1 else None,
                num_pages=num_pages if n_bands > 1 else None)
            shape = (c.n_layers, num_pages, c.n_kv_heads, page, c.head_dim)
            # Layout owned by PagedKVCache.create (the one copy of the
            # int8 {q,s} scheme); value leaves shard via psh, the rank-4
            # [.., KV, 1, page] scale planes via the same spec with the
            # page axis moved last (head_dim dropped, None for the unit
            # dim).
            pool = PagedKVCache.create(c, num_pages, page, self.dtype,
                                       kv_quant=self.kv_quant)
            ssh = NamedSharding(
                self.mesh, P(*psh.spec[:-2], None, psh.spec[-2]))

            def put_side(side):
                if isinstance(side, dict):
                    return {"q": jax.device_put(side["q"], psh),
                            "s": jax.device_put(side["s"], ssh)}
                return jax.device_put(side, psh)
            self.cache = PagedKVCache(k=put_side(pool.k),
                                      v=put_side(pool.v))
            self._d_table = None
            self._table_dirty = True
        else:
            from ..parallel.multihost import zeros_global
            csh = cache_sharding(
                self.mesh, c.n_kv_heads, self.B,
                max_seq=self.S if self.seq_n > 1 else None,
                n_layers=c.n_layers if self.pipe_n > 1 else None)
            shape = (c.n_layers, self.B, c.n_kv_heads, self.S, c.head_dim)
            if self.kv_quant == "int8":
                # int8 values + per-token fp32 scales, stored rank-4
                # [L, B, KV, 1, S] (models/llama.py KVCache): the value
                # sharding with the S axis moved last (head_dim dropped,
                # None for the unit dim) — a seq-sharded S stays sharded.
                ssh = NamedSharding(
                    self.mesh, P(*csh.spec[:-2], None, csh.spec[-2]))
                def qz():
                    return {"q": zeros_global(shape, jnp.int8, csh),
                            "s": zeros_global(shape[:-2] + (1, shape[-2]),
                                              jnp.float32, ssh)}
                self.cache = llama.KVCache(k=qz(), v=qz())
            else:
                self.cache = llama.KVCache(
                    k=zeros_global(shape, self.dtype, csh),
                    v=zeros_global(shape, self.dtype, csh))
        # Host-authoritative per-slot state, mirrored to device each step.
        self.lengths = np.zeros((self.B,), np.int32)
        self.active = np.zeros((self.B,), bool)
        self.last_token = np.zeros((self.B,), np.int32)
        self.samp_temperature = np.zeros((self.B,), np.float32)
        self.samp_top_p = np.ones((self.B,), np.float32)
        self.samp_top_k = np.zeros((self.B,), np.int32)
        self.samp_presence = np.zeros((self.B,), np.float32)
        self.samp_frequency = np.zeros((self.B,), np.float32)
        # Token-occurrence state for presence/frequency penalties:
        # [B, V] int32, DEVICE-authoritative (prefill resets a slot's row
        # and counts the prompt; the general decode path counts each
        # step's INPUT token — so the count visible when sampling token
        # t+1 covers prompt + generated through t, and multihost
        # followers stay bit-identical without broadcasting sampled
        # tokens). The greedy fast path passes it through untouched:
        # stale rows are harmless because a row's counts only matter to
        # its OWN request's penalties, and penalty requests are (a)
        # reset at admission and (b) force the general path.
        self._d_counts = jax.device_put(
            np.zeros((self.B, self.model_cfg.vocab_size), np.int32),
            NamedSharding(self.mesh, P()))
        # Typed PRNG key end-to-end (the legacy raw-uint32 path is slated to
        # become an error in future JAX); the multihost broadcast bit-casts
        # via key_data/wrap_key_data at the wire boundary only.
        self._rng = jax.random.key(int(time.time() * 1e3) % (2**31))
        # Device-resident mirrors for the chained decode loop; re-uploaded
        # (once) whenever host slot state changes.
        self._d_tokens = None
        self._d_lengths = None
        self._d_active = None
        self._d_samp = None
        self._d_dirty = True
        # Lag-one burst pipelining: the scan path dispatches burst N+1
        # BEFORE fetching burst N's tokens, so the device→host round trip
        # (~64 ms through a remote tunnel) overlaps the next burst's
        # compute instead of serializing with it. The stash holds
        # (device tokens, n_steps, active snapshot, slot epochs) of the
        # in-flight burst; `_slot_epoch` guards against a slot being
        # released + re-admitted between dispatch and flush (the stale
        # burst's token must not clobber the new request's first token).
        self._pending: tuple | None = None
        self._slot_epoch = np.zeros((self.B,), np.int64)
        # Step-time model for the ttft_target_ms burst-depth cap. A
        # burst's wall time is C + d·step (C = per-burst fixed cost —
        # host scheduling plus, on a tunneled chip, the dispatch round
        # trip), so the naive wall/d estimate overstates the per-step
        # time at shallow depths; feeding it back into the cap shallowed
        # the bursts further — a death spiral to the minimum compiled
        # depth (observed on v5e: 372 tok/s vs 1468 at a fixed burst 16,
        # same TTFT target). Instead, keep an EMA of burst WALL per
        # depth (any steady same-depth pair — busy stretches at
        # decode_burst_busy feed this too, so it never goes stale under
        # load) and fit step = Δwall/Δdepth across the two largest
        # measured depths: C cancels, the estimate is depth-unbiased,
        # and the control loop is self-correcting in both directions
        # (see _step_ms_estimate). No dedicated refresh bursts needed.
        # Entries age: a depth that stopped running (e.g. the cap
        # settled shallower) holds a wall measured under OLD conditions
        # (shorter contexts); fitting against it would bias the slope —
        # _step_ms_estimate ignores entries not refreshed within the
        # last _BURST_WALL_WINDOW samples (falling back to the newest).
        self._burst_walls: dict[int, float] = {}
        self._burst_wall_stamp: dict[int, int] = {}
        self._burst_wall_n = 0
        # Persistent slope fit + exploration (the staleness window alone
        # is a trap: once the cap settles at one depth, every OTHER
        # depth's wall sample ages out, the estimate degrades to the
        # biased one-depth wall/d (per-burst fixed cost folded back in),
        # the cap shrinks, and the controller never runs a deep burst
        # again — a self-reinforcing spiral observed ON CHIP at 345.7
        # tok/s vs 1475 at fixed burst 16, same target. Two repairs:
        # the last two-depth fitted slope PERSISTS (TTL'd) so a depth
        # aging out doesn't un-learn the fixed cost, and every
        # _EXPLORE_EVERY idle bursts the controller runs a steady PAIR
        # at the next-deeper compiled depth, keeping two fresh depths
        # forever (pairs, because a wall sample only records on a
        # steady same-depth burst pair). Exploration is throughput-free
        # (deeper bursts amortize the fixed cost better); it costs a
        # bounded, rare TTFT exposure one rung deeper.
        self._fit_slope: float | None = None
        self._fit_stamp = 0
        self._idle_burst_i = 0
        self._explore_pending = 0
        self._explore_depth = 0
        self._depth_hist: dict[int, int] = {}
        # Prefill-aware clamp + queue-wait telemetry (stats()): how often
        # busy bursts were clamped below decode_burst_busy, the last
        # depth actually dispatched, and how long admissions waited for a
        # slot — the scheduler-side counters of the roofline story.
        self._busy_clamps = 0
        self._last_burst_depth = 0
        self._queue_wait_n = 0                          # guarded-by: loop
        self._queue_wait_ema_ms: float | None = None    # guarded-by: loop
        self._queue_wait_max_ms = 0.0                   # guarded-by: loop
        # Overload sheds (submit() raised EngineOverloaded on a full
        # admission queue) — the gateway maps these to HTTP 429 with a
        # Retry-After from retry_after_hint_s() (reliability, ISSUE 3).
        self._shed_n = 0
        # Operator-facing gauge for /v1/api/engine-stats: EMA over ANY
        # steady same-depth burst (wall/depth, per-burst overhead
        # included) — the number an operator compares to the bench.
        self._ema_step_ms_stats: float | None = None
        # Speculative decoding state: host token-history mirror (device
        # twin rides the dirty upload) + acceptance counters.
        if self.spec_k:
            self.hist = np.zeros((self.B, self.S), np.int32)
            self._d_hist = None
            self._d_hist_fresh = False
            self._spec_pending = None       # lag-one in-flight spec burst
            self._spec_steps_done = 0
            self._spec_tokens_out = 0
            # Adaptive drafting gate (config.spec_min_tokens_per_step):
            # per-slot EMA of accepted tokens/step (1..k+1); NaN = not yet
            # measured (treated optimistically). Reset on slot release.
            self.spec_min_tps = max(
                0.0, self.cfg.spec_min_tokens_per_step)
            self.spec_probe_interval = max(
                1, self.cfg.spec_probe_interval)
            self._spec_ema = np.full((self.B,), np.nan)
            self._spec_probe_ctr = 0
            # PER-SLOT adaptive drafting (config.spec_acceptance_floor):
            # drafting suspends on a slot whose EMA-derived acceptance
            # ratio ((ema - 1) / k) falls below the floor — its drafts
            # are masked on device (deterministic 1 token/step), its EMA
            # freezes at the suspended value, and the batch-mean gate
            # above excludes it. Suspended slots re-probe together every
            # spec_probe_interval spec rounds (the probe bit rides the
            # OP_SPEC command in multihost so every process masks
            # identically). Per-slot proposed/accepted counters feed the
            # /metrics gauges and stats(); lifetime totals survive slot
            # release.
            self.spec_floor = min(1.0, max(
                0.0, self.cfg.spec_acceptance_floor))
            self._spec_suspended = np.zeros((self.B,), bool)
            self._spec_suspend_probe_ctr = 0
            self._spec_slot_proposed = np.zeros((self.B,), np.int64)
            self._spec_slot_accepted = np.zeros((self.B,), np.int64)
            self._spec_proposed_total = 0
            self._spec_accepted_total = 0
            # Wall-clock gate term: EMA of measured ms per emitted token
            # across full spec bursts. Acceptance alone can lie — a
            # random-weight repetition loop accepts 2+ tokens/step while
            # each spec step (host draft + k+1-wide verify + its own
            # dispatch pattern) costs many times a fused decode step
            # (v5e ladder 2026-07-31: spec_mixed 346.9 vs 1475.1 tok/s
            # with the acceptance gate OPEN at ema 2.24). None = not yet
            # measured; _spec_wall_age forces a periodic re-measure so a
            # wall-closed gate isn't pinned shut on stale data.
            self._spec_ms_per_tok: float | None = None
            self._spec_wall_age = 0
            self._spec_wall_gate_on = bool(self.cfg.spec_wall_gate)
            # Baseline probe: spec-open traffic never runs NORMAL decode
            # bursts, so the step-time model the wall gate compares
            # against would never get a sample on an engine that is
            # spec-open from its first request. Every
            # 8*spec_probe_interval spec rounds (or immediately while no
            # baseline exists), run TWO consecutive normal rounds — two,
            # because a steady same-depth PAIR is what lands a wall
            # sample (the first normal burst after a spec burst is a
            # transition and can't be timed).
            self._spec_base_ctr = 0
            self._spec_base_rounds = 0
            # Starvation guard: some workloads can never land a wall
            # sample (every normal burst capped below the smallest
            # compiled rung -> synchronous path -> no steady pair).
            # After this many fruitless baseline attempts, stop forcing
            # normal rounds — the wall gate simply stays inert (no
            # baseline) and the acceptance gate still protects, instead
            # of pinning speculation off forever.
            self._spec_base_fails = 0

    def _compile(self) -> None:
        if self.paged:
            self._compile_paged()
            return
        c = self.model_cfg
        family_forward = forward_fn(c)
        attention_fn = self._pick_attention()
        if attention_fn is None:
            model_forward = family_forward
        else:
            model_forward = partial(family_forward, attention_fn=attention_fn)
        if self.seq_n > 1:
            # Whole-prompt prefill attends via the configured seq pattern —
            # ring (K/V blocks rotate over ICI; any head count) or Ulysses
            # (two all-to-alls reshard heads<->sequence; cheaper when heads
            # divide the axis). Decode keeps the dense path — GSPMD
            # partitions its S-reductions over the sharded cache.
            # model_forward above stays the DECODE forward.
            prefill_forward = partial(
                family_forward,
                attention_fn=_seq_prefill_attention_fn(
                    self.mesh, self.seq_attention))
        elif self.pipe_n > 1:
            # Both compiled programs run the GPipe schedule: decode splits
            # the B slots into `pipe` microbatches (when divisible);
            # prefill's single-slot row degrades to M=1 (correct,
            # bubble-heavy — prefill cost is dominated by FLOPs anyway).
            model_forward = _pipelined_family_forward(self.mesh, self.pipe_n)
            prefill_forward = model_forward
        else:
            prefill_forward = model_forward

        replicated = NamedSharding(self.mesh, P())

        @partial(jax.jit, donate_argnums=(1, 2))
        def prefill_step(params, cache: llama.KVCache, counts: jax.Array,
                         tokens: jax.Array,
                         start_len: jax.Array, slots: jax.Array,
                         last_idx: jax.Array, samp_t: jax.Array,
                         samp_p: jax.Array, samp_k: jax.Array,
                         samp_pp: jax.Array, samp_fp: jax.Array,
                         key: jax.Array
                         ) -> tuple[jax.Array, jax.Array, llama.KVCache]:
            """Run one prompt chunk for each of K slots. tokens [K, C],
            start_len/slots/last_idx/samp_* [K]. Returns (first_tokens
            [K, replicated], cache). K=1 is the single-request path;
            K>1 is BATCHED admission: on a tunneled chip one dispatch
            costs ~50-75 ms while a 1.1B chunk computes in ~3 ms
            (BENCH_SELF_r5b: 40 slots filled at 77 ms/chunk), so K
            queued prefills in one program cut fill time ~K-fold. The
            first token is sampled INSIDE this program from each row's
            last REAL position — prefill→row-fetch→sample-one folded
            into one dispatch, as before. Per-k cache rows move via
            unrolled dynamic slices (NOT a gather: the B axis may be
            sharded over `data`, and dynamic_slice is the op GSPMD
            already partitions correctly for the K=1 path).
            Multihost followers always run K=1 (see _step): batched
            grouping is a compile-shape choice, and coordinator/follower
            programs must stay bit-identical."""
            K = tokens.shape[0]

            def rows_of(side):
                return jax.tree.map(
                    lambda a: jnp.concatenate(
                        [jax.lax.dynamic_slice_in_dim(a, slots[k], 1,
                                                      axis=1)
                         for k in range(K)], axis=1), side)
            row_cache = llama.KVCache(k=rows_of(cache.k),
                                      v=rows_of(cache.v))
            logits, row_cache = prefill_forward(
                params, c, tokens, start_len, row_cache)

            def scatter(full, rows):
                for k in range(K):
                    full = jax.lax.dynamic_update_slice_in_dim(
                        full, jax.lax.dynamic_slice_in_dim(
                            rows, k, 1, axis=1), slots[k], axis=1)
                return full
            new_k = jax.tree.map(scatter, cache.k, row_cache.k)
            new_v = jax.tree.map(scatter, cache.v, row_cache.v)
            counts, count_rows = _prefill_counts(
                counts, tokens, start_len, slots, last_idx)
            rows = jax.lax.with_sharding_constraint(
                jnp.take_along_axis(
                    logits, last_idx[:, None, None], axis=1)[:, 0, :],
                replicated)
            samp = SamplingParams(temperature=samp_t, top_p=samp_p,
                                  top_k=samp_k, presence_penalty=samp_pp,
                                  frequency_penalty=samp_fp)
            # Phase marker (ISSUE 8): trace-time op metadata only — the
            # profiler segments sampling from the forward in Perfetto.
            with jax.named_scope("sampling"):
                first = jax.lax.with_sharding_constraint(
                    sample(rows, samp, key, counts=count_rows), replicated)
            return first, counts, llama.KVCache(k=new_k, v=new_v)

        def one_step(params, cache: llama.KVCache, counts: jax.Array,
                     tokens: jax.Array,
                     lengths: jax.Array, active: jax.Array,
                     samp: SamplingParams, key: jax.Array, *,
                     greedy: bool = False
                     ) -> tuple[jax.Array, jax.Array, jax.Array,
                                llama.KVCache]:
            """One decode step — the ONE copy of the forward+sample+advance
            body; both compiled programs below are built from it. Returns
            (next_tokens, new_lengths, cache) so the token/length feedback
            loop stays ON DEVICE across steps — host fetches happen
            asynchronously, steps behind (the tunnel's per-fetch latency is
            ~40 ms; chained dispatch amortizes it). Sampled tokens are
            pinned replicated so the host fetch is local on every process
            of a multi-host mesh. ``greedy=True`` compiles the
            argmax-only variant — it skips the full-vocab sort the general
            sampler pays per step; the scheduler picks it whenever every
            active slot has temperature 0 AND zero penalties (the common
            serving case; a penalized argmax differs from plain argmax,
            so penalty requests ride the general path). The general path
            counts each step's INPUT token before sampling, so the
            penalty counts cover prompt + generated through step t when
            sampling t+1 (engine/sampling.py apply_penalties); the
            greedy path passes counts through untouched (aliased
            donation, zero cost)."""
            if not greedy:
                counts = counts.at[jnp.arange(counts.shape[0]),
                                   tokens].add(active.astype(jnp.int32))
            logits, cache = model_forward(
                params, c, tokens[:, None], lengths, cache, active=active)
            with jax.named_scope("sampling"):
                if greedy:
                    next_tokens = jnp.argmax(
                        logits[:, 0, :], axis=-1).astype(jnp.int32)
                else:
                    next_tokens = sample(logits[:, 0, :], samp, key,
                                         counts=counts)
                next_tokens = jax.lax.with_sharding_constraint(
                    next_tokens, replicated)
            new_lengths = jnp.where(active, lengths + 1, lengths)
            return next_tokens, new_lengths, counts, cache

        self._prefill_fn = prefill_step
        self._decode_fns = _decode_programs(one_step, self._burst_depths)

        if self.spec_k:
            from .speculative import make_spec_burst, make_spec_step
            # Scan depth chosen so a worst-case fully-accepted burst emits
            # about decode_burst tokens (comparable pacing to normal mode).
            self._spec_scan_len = max(
                1, self.decode_burst // (self.spec_k + 1))
            # The verify forward (T=k+1) defers its cache writes like
            # decode does — the chunk path's per-layer functional insert
            # costs ~2 ms/step in serialized scatters (tools/
            # profile_insert.py), paid EVERY spec step otherwise.
            spec_forward = partial(
                family_forward,
                attention_fn=_spec_verify_attention_fn(
                    attention_fn, window=c.sliding_window))
            self._spec_scan = make_spec_burst(
                spec_forward, c, self.spec_k, self._spec_scan_len)
            self._spec_step = partial(jax.jit, donate_argnums=(1,))(
                make_spec_step(spec_forward, c, self.spec_k))

    def _resolve_attention_impl(self) -> str:
        """Validate cfg.attention and resolve "auto" (pallas on real TPU;
        interpret-mode Pallas on CPU is correct but slower than fused jnp)."""
        impl = self.cfg.attention
        if impl not in ("auto", "pallas", "reference"):
            raise ValueError(f"unknown attention impl {impl!r}; "
                             f"expected auto | pallas | reference")
        if self.seq_n > 1 or self.pipe_n > 1:
            # The Pallas kernels address a full-extent local cache; with S
            # sharded over `seq` (or the pipelined schedule, which fixes
            # its own dense per-stage attention) the path is the
            # GSPMD-partitioned dense reference.
            if impl == "pallas":
                logger.warning("attention=pallas is not available with a "
                               "seq- or pipe-sharded engine; using reference")
            else:
                logger.info("attention: reference (seq/pipe-sharded engine "
                            "— Pallas kernels need a full-extent local "
                            "cache)")
            return "reference"
        if impl == "auto":
            return "pallas" if jax.default_backend() == "tpu" else "reference"
        return impl

    def _compile_paged(self) -> None:
        """Compile the paged-cache step programs. The attention_fn is built
        INSIDE each jitted step, closing over the traced page table — the
        model forward signature stays cache-layout-agnostic."""
        c = self.model_cfg
        family_forward = forward_fn(c)
        from ..ops.paged_attention import PagedKVCache, make_paged_attention_fn

        impl = self._resolve_attention_impl()
        mesh = self.mesh if self.mesh.size > 1 else None
        logger.info("paged KV cache: %d pages × %d tokens, attention=%s"
                    "%s", self.allocator.num_pages,
                    self.allocator.page_size, impl,
                    (f", pages_per_block={self.kv_ppb}"
                     if self.kv_ppb > 1 else ""))
        S = self.S

        replicated = NamedSharding(self.mesh, P())

        if self.pipe_n > 1:
            # Paged × PP: the pool's layer dim is staged over `pipe`
            # (paged_cache_sharding) and the GPipe schedule slices TABLE
            # rows per microbatch instead of cache rows — the attention
            # builder must be identity-stable for the pipeline's program
            # memo, hence ONE partial per engine.
            make_attn = partial(make_paged_attention_fn, max_seq=S,
                                impl=impl, mesh=mesh,
                                window=c.sliding_window,
                                pages_per_block=self.kv_ppb)
            pipe_fwd = _pipelined_family_forward(self.mesh, self.pipe_n,
                                                 make_attention=make_attn)

            def call_forward(params, cache, table, tokens, lengths,
                             active=None, prefill=False):
                return pipe_fwd(params, c, tokens, lengths, cache,
                                active=active, table=table)
        elif self.seq_n > 1:
            # Paged × seq: whole-prompt prefill attends via ring/ulysses
            # over the fresh q/k/v (no cache read) and writes through the
            # shard_map'd BANDED scatter; decode gathers each chip's
            # local pages into the dense S-sharded view and runs the
            # dict-aware deferred dense attention under GSPMD — the same
            # partitioning story as the dense seq engine
            # (ops/paged_attention.make_seq_paged_attention_fn).
            from ..ops.paged_attention import make_seq_paged_attention_fn
            seq_kind = self.seq_attention
            eng_mesh = self.mesh

            def call_forward(params, cache, table, tokens, lengths,
                             active=None, prefill=False):
                attn = make_seq_paged_attention_fn(table, max_seq=S,
                                                   mesh=eng_mesh)
                if prefill:
                    attn = _seq_paged_prefill_attention_fn(
                        eng_mesh, seq_kind, attn)
                return family_forward(params, c, tokens, lengths, cache,
                                      active=active, attention_fn=attn)
        else:
            def call_forward(params, cache, table, tokens, lengths,
                             active=None, prefill=False, spec=False):
                # `spec` builds the dedicated verify-capable provider:
                # T = k+1 then routes through the deferred paged verify
                # (stale-pool gather + mixed-precision self-block) instead
                # of the chunk path — required for int8 greedy parity and
                # skips the per-layer pool scatters either way.
                attn = make_paged_attention_fn(table, max_seq=S, impl=impl,
                                               mesh=mesh,
                                               window=c.sliding_window,
                                               pages_per_block=self.kv_ppb,
                                               spec=spec)
                return family_forward(params, c, tokens, lengths, cache,
                                      active=active, attention_fn=attn)

        @partial(jax.jit, donate_argnums=(1, 2))
        def prefill_step(params, cache: PagedKVCache, counts: jax.Array,
                         table: jax.Array,
                         tokens: jax.Array, start_len: jax.Array,
                         slots: jax.Array, last_idx: jax.Array,
                         samp_t: jax.Array, samp_p: jax.Array,
                         samp_k: jax.Array, samp_pp: jax.Array,
                         samp_fp: jax.Array, key: jax.Array
                         ) -> tuple[jax.Array, jax.Array, PagedKVCache]:
            """One prompt chunk for each of K slots (dense twin's batched
            admission — see its docstring). tokens [K, C]; the pool is
            global, so unlike the dense path there is no per-slot cache
            slice — each slot's page-table row does the routing, and the
            K rows are sliced unrolled (same GSPMD-partitioned op as the
            K=1 path)."""
            K = tokens.shape[0]
            rows_tbl = jnp.concatenate(
                [jax.lax.dynamic_slice_in_dim(table, slots[k], 1, axis=0)
                 for k in range(K)], axis=0)
            logits, cache = call_forward(params, cache, rows_tbl, tokens,
                                         start_len, prefill=True)
            counts, count_rows = _prefill_counts(
                counts, tokens, start_len, slots, last_idx)
            rows = jax.lax.with_sharding_constraint(
                jnp.take_along_axis(
                    logits, last_idx[:, None, None], axis=1)[:, 0, :],
                replicated)
            samp = SamplingParams(temperature=samp_t, top_p=samp_p,
                                  top_k=samp_k, presence_penalty=samp_pp,
                                  frequency_penalty=samp_fp)
            with jax.named_scope("sampling"):
                first = jax.lax.with_sharding_constraint(
                    sample(rows, samp, key, counts=count_rows), replicated)
            return first, counts, PagedKVCache(k=cache.k, v=cache.v)

        def one_step(params, cache: PagedKVCache, counts: jax.Array,
                     table: jax.Array,
                     tokens: jax.Array, lengths: jax.Array,
                     active: jax.Array, samp: SamplingParams,
                     key: jax.Array, *, greedy: bool = False):
            """Paged one-step twin (page table routes the cache rows). The
            table is loop-invariant under the burst scan — pages are
            reserved for a request's whole lifetime at admission, so no
            page can change mid-burst. Penalty counts as the dense twin:
            general path counts the input token; greedy passes through."""
            if not greedy:
                counts = counts.at[jnp.arange(counts.shape[0]),
                                   tokens].add(active.astype(jnp.int32))
            logits, cache = call_forward(params, cache, table,
                                         tokens[:, None], lengths,
                                         active=active)
            with jax.named_scope("sampling"):
                if greedy:
                    next_tokens = jnp.argmax(
                        logits[:, 0, :], axis=-1).astype(jnp.int32)
                else:
                    next_tokens = sample(logits[:, 0, :], samp, key,
                                         counts=counts)
                next_tokens = jax.lax.with_sharding_constraint(
                    next_tokens, replicated)
            new_lengths = jnp.where(active, lengths + 1, lengths)
            return (next_tokens, new_lengths, counts,
                    PagedKVCache(k=cache.k, v=cache.v))

        self._prefill_fn = prefill_step
        self._decode_fns = _decode_programs(one_step, self._burst_depths)

        if self.spec_k:
            from .speculative import make_spec_burst, make_spec_step

            def make_fwd(tbl):
                def fwd(params, c_, tokens, lengths, cache, active=None):
                    # Only the single-host paged path has the dedicated
                    # verify provider; the seq- and pipe-sharded
                    # call_forwards verify on their chunk paths (exact
                    # for bf16 KV; int8 combos are rejected at build).
                    kw = ({"spec": True}
                          if self.seq_n == 1 and self.pipe_n == 1 else {})
                    return call_forward(params, cache, tbl, tokens,
                                        lengths, active=active, **kw)
                return fwd

            self._spec_scan_len = max(
                1, self.decode_burst // (self.spec_k + 1))
            self._spec_scan = make_spec_burst(
                None, c, self.spec_k, self._spec_scan_len,
                make_forward=make_fwd)

            @partial(jax.jit, donate_argnums=(1,))
            def spec_step1(params, cache, table, hist, tokens, lengths,
                           active, draft_ok):
                return make_spec_step(make_fwd(table), c, self.spec_k)(
                    params, cache, hist, tokens, lengths, active, draft_ok)
            self._spec_step = spec_step1

    def _warm_decode_variants(self) -> None:
        """AOT lower+compile the greedy AND general decode programs from
        input avals (no device buffers touched), populating the persistent
        compilation cache — the eventual first real call of the not-yet-
        used variant re-traces but hits the disk cache, turning a 30-60 s
        mid-serving stall into a ~1-2 s one. Best-effort: any failure just
        means lazy compilation as before."""
        try:
            def aval(x):
                return jax.ShapeDtypeStruct(
                    x.shape, x.dtype, sharding=getattr(x, "sharding", None))
            rep = NamedSharding(self.mesh, P())

            def vec(dt):
                return jax.ShapeDtypeStruct((self.B,), dt, sharding=rep)
            samp_a = SamplingParams(temperature=vec(jnp.float32),
                                    top_p=vec(jnp.float32),
                                    top_k=vec(jnp.int32),
                                    presence_penalty=vec(jnp.float32),
                                    frequency_penalty=vec(jnp.float32))
            table_a = (aval(self._device_table()),) if self.paged else ()
            args = (jax.tree.map(aval, self.params),
                    jax.tree.map(aval, self.cache),
                    aval(self._d_counts), *table_a,
                    vec(jnp.int32), vec(jnp.int32), vec(jnp.bool_),
                    samp_a, aval(self._rng))
            for greedy in (False, True):
                step, scans = self._decode_fns[greedy]
                for fn in (scans.values() if scans else [step]):
                    fn.lower(*args).compile()
        except Exception:
            logger.debug("decode program pre-warm failed", exc_info=True)

    def _device_table(self) -> jax.Array:
        if self._table_dirty or self._d_table is None:
            self._d_table = jax.device_put(
                self.allocator.table, NamedSharding(self.mesh, P()))
            self._table_dirty = False
        return self._d_table

    def _pick_attention(self):
        """Dense-cache attention_fn for the resolved impl ("reference" →
        None: llama.forward's default dense jnp path)."""
        impl = self._resolve_attention_impl()
        if impl == "pallas":
            w = self.model_cfg.sliding_window
            if self.mesh.size > 1:
                # Sharded cache → the kernels must run under shard_map
                # (pallas_call has no GSPMD partitioning rule). The
                # wrapper's per-leaf specs cover int8 {"q","s"} caches;
                # the sliding-window bound threads through (positions are
                # absolute — batch/head sharding doesn't touch them).
                from ..ops import make_sharded_cache_attention_fn
                logger.info("attention: pallas flash kernels (shard_map over "
                            "%s)%s", dict(self.mesh.shape),
                            f" (sliding window {w})" if w else "")
                return make_sharded_cache_attention_fn(self.mesh, window=w)
            from ..ops import make_cache_attention_fn
            logger.info("attention: pallas flash kernels%s",
                        f" (sliding window {w})" if w else "")
            return make_cache_attention_fn(window=w)
        return None

    def _enable_debug_nans(self) -> None:
        """The numerics sanitizer (SURVEY.md §5): compiled programs raise on
        NaN production instead of streaming garbage tokens. The flag is
        PROCESS-GLOBAL; the previous value is saved here and restored on
        stop() so one engine's config doesn't tax every other program in
        the process forever — and re-applied on start() so a restarted
        engine keeps its sanitizer."""
        if self.cfg.debug_nans and self._prev_debug_nans is None:
            self._prev_debug_nans = bool(jax.config.jax_debug_nans)
            jax.config.update("jax_debug_nans", True)

    # -- public API ----------------------------------------------------------
    async def start(self) -> None:
        if self._bridge.enabled and self._bridge._shutdown_sent:
            # Terminal in multihost mode: followers exited on SHUTDOWN, so
            # a restarted coordinator's first publish would hang forever in
            # the collective (advisor r1, medium).
            raise RuntimeError(
                "multihost engine is terminal after stop(); restart the "
                "whole fleet to serve again")
        if self.supervisor.state == "failed":
            raise EngineUnavailable(
                "engine is failed (restart budget exhausted or fatal "
                "fault); traffic stays on the fallback chain")
        if self._loop_task is None:
            self._stopped = False        # restartable after stop()
            self._enable_debug_nans()
            loop = asyncio.get_running_loop()
            if self._loop is not loop:
                # asyncio.Event binds to the first loop that awaits it; a
                # restarted engine on a NEW loop (sequential asyncio.run
                # phases — the bench does this between rungs) would die
                # with a cross-loop RuntimeError at its first idle
                # `_work_event.wait()`, silently stranding every later
                # submit. Fresh event per serving loop; submit()/stop()
                # set it only after start(), so no waiter is orphaned.
                self._work_event = asyncio.Event()
                self._loop = loop
            self._loop_task = loop.create_task(self._run_loop())
            if (self.supervisor.watchdog_ms > 0
                    and (self._watchdog_task is None
                         or self._watchdog_task.done())):
                self._watchdog_task = loop.create_task(
                    self._watchdog_loop())
        if (self._warm_thread is None and self.cfg.prewarm_sampler_variants
                and jax.default_backend() == "tpu"):
            # Pre-lower+compile BOTH sampler variants into the persistent
            # compilation cache off-thread: without this, the first
            # temperature>0 request after a greedy-only warm-up stalls
            # every in-flight decode for a full XLA compile.
            import threading
            self._warm_thread = threading.Thread(
                target=self._warm_decode_variants, daemon=True)
            self._warm_thread.start()

    async def stop(self) -> None:
        self._stopped = True
        if self._watchdog_task is not None:
            self._watchdog_task.cancel()
            try:
                await self._watchdog_task
            except asyncio.CancelledError:
                pass
            self._watchdog_task = None
        self._work_event.set()
        if self._loop_task is not None:
            await self._loop_task
            self._loop_task = None
        if self._prev_debug_nans is not None:
            jax.config.update("jax_debug_nans", self._prev_debug_nans)
            self._prev_debug_nans = None
        # Only after the loop has fully drained: an in-flight burst's
        # DECODE broadcast racing SHUTDOWN from another thread could reach
        # followers out of order and strand them mid-collective.
        if self._bridge.enabled:
            await asyncio.to_thread(self._bridge.publish_shutdown)
        # Flush terminal deltas so no consumer awaits a stream forever.
        for req in list(self._running.values()):
            req.out_queue.put_nowait(Delta(error="engine stopped"))
            self._release(req)
        if self._head is not None:
            self._head.out_queue.put_nowait(Delta(error="engine stopped"))
            self._head = None
        while not self._queue.empty():
            req = self._queue.get_nowait()
            req.out_queue.put_nowait(Delta(error="engine stopped"))
        self.supervisor.transition("stopped", "stop() requested")

    async def submit(self, req: GenRequest) -> None:
        """Admit a request; raises EngineOverloaded when the queue is
        full, EngineUnavailable while the supervisor has the engine
        draining/restarting/failed (the router fails over)."""
        if not self.supervisor.is_accepting():
            state = self.supervisor.state
            raise EngineUnavailable(
                f"engine is {state}",
                retry_after_s=(self.supervisor.backoff_s()
                               if state == "restarting" else None))
        max_prompt = self.S - 1 - self.spec_k
        if len(req.prompt_ids) > max_prompt:
            raise EngineOverloaded(
                f"prompt of {len(req.prompt_ids)} tokens exceeds engine "
                f"max_seq_len {self.S}")
        req.max_tokens = max(1, min(req.max_tokens,
                                    self.S - len(req.prompt_ids)))
        # HBM headroom watermark (ISSUE 8): when the runtime allocator
        # reports less free device memory than the configured fraction,
        # shed at admission exactly like a full queue — 429 + Retry-After
        # through the PR 3 path — instead of letting the next compile or
        # fragmentation event OOM mid-stream. Inert where the backend has
        # no allocator stats (CPU) unless a test injects a mem_fn.
        wm = self.cfg.hbm_headroom_watermark
        if wm > 0:
            frac = self.ledger.headroom_fraction()
            if frac is not None and frac < wm:
                self._shed_n += 1
                self._watermark_sheds += 1
                if self.flight is not None:
                    from ..obs.flight import SHED
                    self.flight.record(SHED, queued=self._queue.qsize(),
                                       free_slots=self._free_slot_count(),
                                       val=frac,
                                       rid=req.request_id or None)
                raise EngineOverloaded(
                    f"device memory headroom {frac:.1%} below the "
                    f"{wm:.0%} watermark")
        if self._disagg is not None:
            # Goodput-first admission (ISSUE 13): shed now — 429 with a
            # numeric Retry-After through the same path as a full queue
            # — when neither pool's predicted attainment meets the
            # request's SLO; a TTFT-only risk admits clamped instead.
            self._disagg.admit_or_shed(req)
        req.detok = IncrementalDetokenizer(self.tokenizer)
        try:
            self._queue.put_nowait(req)
        except asyncio.QueueFull:
            self._shed_n += 1
            if self.flight is not None:
                from ..obs.flight import SHED
                self.flight.record(SHED, queued=self._queue.qsize(),
                                   free_slots=self._free_slot_count(),
                                   rid=req.request_id or None)
            raise EngineOverloaded("engine admission queue is full") from None
        await self.start()
        self._work_event.set()
        # Re-stamp the heartbeat at admission: an engine that idled past
        # the watchdog deadline is NOT stalled — the deadline must start
        # from this wake-up, not from the last step before the idle gap.
        self.supervisor.heartbeat(self.flight.seq
                                  if self.flight is not None else 0)

    def _free_slot_count(self) -> int:
        """Free slots across every pool (ONE pool unified, two disagg)."""
        return sum(len(p.free) for p in self._pools)

    @property
    def _free_slots(self) -> list:
        """The admit pool's free list — the WHOLE free list when
        disaggregation is off (one pool), the prefill pool's under
        disaggregation. The pre-pool name, kept because the test surface
        and operator debug consoles reach for it; writes pass through to
        the pool so fault-injection tests can still pin slots."""
        return self._admit_pool.free

    @_free_slots.setter
    def _free_slots(self, slots) -> None:
        self._admit_pool.free = slots

    def retry_after_hint_s(self) -> float:
        """How long a just-shed client should wait before retrying, from the
        fitted step-time / queue-wait telemetry (ISSUE 3): the measured
        admission wait plus one decode step per queued request ahead of it.
        Bounded to [1, 30] s — a Retry-After, not a promise."""
        step_ms = self._ema_step_ms_stats
        if step_ms is None:
            est = self._step_ms_estimate()
            step_ms = est if est is not None else 0.0
        wait_ms = self._queue_wait_ema_ms or 0.0
        est_ms = wait_ms + step_ms * max(1, self._queue.qsize())
        return min(30.0, max(1.0, est_ms / 1000.0))

    async def stream(self, req: GenRequest) -> AsyncIterator[Delta]:
        """Yield deltas for a submitted request until it finishes."""
        while True:
            delta: Delta = await req.out_queue.get()
            yield delta
            if delta.finish_reason is not None or delta.error is not None:
                return

    # -- the batching loop ---------------------------------------------------
    async def _run_loop(self) -> None:
        logger.info("engine loop started (B=%d, S=%d)", self.B, self.S)
        sup = self.supervisor
        sup.transition("serving", "scheduler loop started")
        sup.heartbeat(self.flight.seq if self.flight is not None else 0)
        while not self._stopped:
            # Clear BEFORE stepping: a submit() that lands during the await
            # inside _step sets the event and must not be wiped afterwards
            # (missed-wakeup race — the request would strand in the queue).
            self._work_event.clear()
            try:
                progressed = await self._step()
                # Heartbeat AFTER the step returns (piggybacked on the
                # flight seq): a stuck _step leaves the heartbeat stale,
                # which is exactly what the watchdog needs to see.
                sup.heartbeat(self.flight.seq if self.flight is not None
                              else 0)
                if progressed:
                    self._clean_steps += 1
                    if self._clean_steps == 50:
                        # A sustained healthy stretch re-earns the full
                        # restart budget — one crash per day must not
                        # accumulate into "budget exhausted" forever.
                        sup.reset_restarts()
            except asyncio.CancelledError:
                # Watchdog kill path: the canceller owns recovery.
                raise
            except Exception as e:           # engine must never die silently
                logger.exception("engine step failed")
                from ..reliability.supervisor import EngineFailure
                await self._on_step_failure(EngineFailure.classify(e))
                progressed = True
            if not progressed:
                await self._work_event.wait()
                sup.heartbeat(self.flight.seq if self.flight is not None
                              else 0)
        logger.info("engine loop stopped")

    async def _on_step_failure(self, failure) -> None:
        """Supervised recovery from a classified step-loop failure
        (ISSUE 14). In-flight streams get an in-band error delta (the
        PR 3 mid-stream contract — providers/local.py turns it into a
        well-formed SSE error frame and partial usage records
        downstream); queued-but-unstarted admissions stay queued for the
        restarted engine, or are flushed with errors when the engine
        parks in `failed` (the router's fallback chain takes over either
        way, via EngineUnavailable at admission)."""
        sup = self.supervisor
        logger.error("engine failure (%s): %s", failure.kind, failure)
        sup.note_failure(failure)
        self._clean_steps = 0
        # _prefilling is a secondary index into _running (admission adds
        # to both), so flushing _running covers mid-prefill requests.
        for req in list(self._running.values()):
            req.out_queue.put_nowait(
                Delta(error=f"engine failure: {failure}"))
            self._release(req)
        if self._bridge.enabled:
            # Multihost: a local re-init would silently desync the
            # followers' cache shards (they saw no failure) and every
            # later SPMD call would compute garbage. The only safe
            # recovery is fleet shutdown; the gateway's fallback chain
            # takes over (provider error → remote).
            logger.error("multihost engine failure is fatal: "
                         "shutting the fleet down")
            sup.transition("failed", f"multihost {failure.kind} failure")
            self._stopped = True
            self._fail_queued(f"engine failure: {failure}")
            # Safe here: the failed burst's own broadcast completed
            # before its execution raised, and no other publisher runs
            # concurrently with this handler.
            await asyncio.to_thread(self._bridge.publish_shutdown)
            return
        if failure.kind == "fatal" or not sup.can_restart():
            reason = ("fatal failure (restart would loop on it)"
                      if failure.kind == "fatal" else
                      f"restart budget exhausted "
                      f"({sup.max_restarts} attempts)")
            logger.error("engine parked in failed state: %s", reason)
            sup.transition("failed", reason)
            self._stopped = True
            self._fail_queued(f"engine failed: {failure}")
            return
        sup.transition("restarting", f"{failure.kind}: {failure}")
        backoff = sup.backoff_s()
        sup.note_restart()
        if backoff > 0:
            await asyncio.sleep(backoff)
        try:
            self._rebuild_state()
            sup.transition("serving", "supervised restart complete")
        except Exception:
            logger.exception("engine state re-init failed")
            sup.transition("failed", "restart re-init failed")
            self._stopped = True
            self._fail_queued("engine failed: restart re-init failed")

    def _fail_queued(self, msg: str) -> None:
        """Flush queued-but-unstarted admissions with terminal errors —
        only on the no-recovery paths (failed / multihost shutdown)."""
        if self._head is not None:
            self._head.out_queue.put_nowait(Delta(error=msg))
            self._head = None
        while not self._queue.empty():
            req = self._queue.get_nowait()
            req.out_queue.put_nowait(Delta(error=msg))

    def _rebuild_state(self) -> None:
        """Tear down and rebuild device + scheduler state for a
        supervised restart. Ordering matters: the compile monitor
        re-arms FIRST so the rebuild's own compiles are attributed
        instead of lost (PR 8's install-before-compile bug class, same
        shape as PR 7's `_work_event` rebinding)."""
        from ..obs.device import install_compile_monitor
        install_compile_monitor()
        # donate_argnums may have consumed the cache buffer before the
        # failure: rebuild device state so the engine recovers instead
        # of failing every subsequent step on a deleted array. The radix
        # prefix cache restarts empty — its KV pages died with the pool,
        # so "re-seed" is organic re-warming, not resurrection.
        self._init_state()
        for pool in self._pools:
            pool.reset_free()
        self._running.clear()
        self._prefilling.clear()
        # The ledger's tracked buffers were donated/freed with the old
        # cache; rebuild it against the new buffers so /metrics doesn't
        # reconcile against ghosts (restart-recovery gap, ISSUE 14).
        self.ledger = self._build_ledger()

    async def _watchdog_loop(self) -> None:
        """Stall detector (ISSUE 14): when the scheduler heartbeat goes
        stale past `watchdog_ms` WHILE work is pending, cancel the
        scheduler task and route the stall through the same supervised
        restart path as a crash. An idle engine parked on its work
        event never trips it."""
        sup = self.supervisor
        from ..reliability.supervisor import EngineFailure
        while not self._stopped:
            # Recomputed each tick (capped at 250 ms) so watchdog_ms can
            # be tuned on a live engine without restarting the task.
            await asyncio.sleep(min(0.25, max(0.005,
                                              sup.watchdog_ms / 4000.0)))
            if self._stopped or sup.state != "serving":
                continue
            busy = bool(self._running or self._prefilling
                        or self._head is not None
                        or not self._queue.empty())
            if not sup.is_stalled(busy):
                continue
            age_ms = sup.heartbeat_age_s() * 1000.0
            logger.error("watchdog: engine stalled (heartbeat %.0f ms "
                         "past the %.0f ms deadline)", age_ms,
                         sup.watchdog_ms)
            task = self._loop_task
            if task is not None and not task.done():
                task.cancel()
                try:
                    await task
                except asyncio.CancelledError:
                    pass
                except Exception:
                    logger.exception("stalled loop died on cancel")
            self._loop_task = None
            await self._on_step_failure(EngineFailure(
                f"scheduler loop stalled: heartbeat {age_ms:.0f} ms past "
                f"the {sup.watchdog_ms:.0f} ms watchdog", kind="stall"))
            if not self._stopped:
                loop = asyncio.get_running_loop()
                self._loop_task = loop.create_task(self._run_loop())

    async def drain(self, *, restart: bool = False,
                    deadline_s: float | None = None) -> dict[str, Any]:
        """Administrative drain (ISSUE 14): stop admissions, let
        in-flight work finish under a bounded deadline, force-cancel
        stragglers past it, then either restart the engine in place
        (config hot-reload / planned maintenance) or stop it (SIGTERM).
        Returns a summary for the admin caller."""
        sup = self.supervisor
        sup.transition("draining", "administrative drain")
        limit = (sup.drain_deadline_ms / 1000.0
                 if deadline_s is None else deadline_s)
        t0 = time.monotonic()
        forced = 0
        while (self._running or self._head is not None
               or not self._queue.empty()):
            if time.monotonic() - t0 > limit:
                # Deadline expired: force-cancel stragglers. The
                # scheduler's cancel path frees slots but emits no
                # terminal delta (its client-gone semantics) — a drain's
                # clients are still connected, so the terminal frame is
                # emitted HERE; queued requests get terminal errors
                # directly (they never started).
                for req in list(self._running.values()):
                    req.cancelled = True
                    req.out_queue.put_nowait(
                        Delta(finish_reason="cancelled"))
                    forced += 1
                if self._head is not None:
                    self._head.cancelled = True
                    self._head.out_queue.put_nowait(
                        Delta(finish_reason="cancelled"))
                    forced += 1
                while not self._queue.empty():
                    req = self._queue.get_nowait()
                    req.out_queue.put_nowait(
                        Delta(error="engine draining"))
                    forced += 1
                self._work_event.set()
                t1 = time.monotonic()
                while self._running and time.monotonic() - t1 < 2.0:
                    await asyncio.sleep(0.01)
                break
            self._work_event.set()
            await asyncio.sleep(0.01)
        summary = {"forced_cancel": forced,
                   "drain_s": round(time.monotonic() - t0, 3)}
        if restart:
            sup.transition("restarting", "planned restart")
            self._stopped = True
            self._work_event.set()
            if self._loop_task is not None:
                await self._loop_task
                self._loop_task = None
            self._rebuild_state()
            self._stopped = False
            sup.transition("serving", "planned restart complete")
            summary["restarted"] = True
        else:
            await self.stop()
            summary["restarted"] = False
        return summary

    async def _step(self) -> bool:
        """One scheduler iteration. Emission always happens here, on the
        event-loop thread (asyncio.Queue is not thread-safe); worker-thread
        calls only touch device programs and host numpy state.

        With the flight recorder on, the iteration leaves ONE step record
        (composition, burst depth, tokens, fitted-vs-measured step time)
        plus lifecycle records for admissions/evictions it performed —
        appended loop-side only, after the worker-thread awaits return."""
        if self.fault_plan is not None:
            stall_s = self.fault_plan.on_step()
            if stall_s > 0:
                # Injected silent stall: the loop stays alive but stops
                # stepping — the failure shape only the watchdog sees.
                await asyncio.sleep(stall_s)
        fl = self.flight
        t_step0 = fl.clock() if fl is not None else 0.0
        clamps0 = self._busy_clamps
        n_chunks = 0                  # compiled prefill dispatches this step
        n_tok = 0                     # tokens emitted downstream this step
        spec_acc_n = 0                # accepted draft tokens landed this step
        # 1. Admit into free slots (dropping requests whose client is gone).
        #    Paged layout: the FIFO head also needs its full page reservation
        #    (engine/paged.py policy) — if pages are short it waits at the
        #    head (no starvation: held pages always return via releases).
        while True:
            # Pool capacity gate (ISSUE 13): the unified pool just needs
            # any free slot; a disaggregated COLD admission needs a free
            # prefill slot AND a free decode slot to reserve (so the
            # handoff can never strand a prompt-complete request), while
            # the direct-to-decode path (warm prefix hit / penalties —
            # decided below, after the prefix lookup) needs only the
            # decode slot.
            cold_ok = bool(self._admit_pool.free) and (
                self._disagg is None or bool(self._decode_pool.free))
            if not cold_ok and not (self._disagg is not None
                                    and self._decode_pool.free):
                break
            if self._head is None:
                if self._queue.empty():
                    break
                self._head = self._queue.get_nowait()
            req = self._head
            if req.cancelled:
                req.finish_reason = "cancelled"
                self._head = None
                continue
            if self.paged:
                total = min(len(req.prompt_ids) + req.max_tokens, self.S)
                # Radix prefix lookup (ISSUE 6): resident prompt blocks map
                # into the new slot's table row instead of allocating +
                # prefilling. Penalty requests bypass the cache — their
                # token-occurrence counts are rebuilt by prefill, which a
                # skipped span would leave incomplete. Matched nodes are
                # pinned here; the pins drop at slot release, or right
                # below if the request parks instead of admitting.
                matched, shared_pages, nodes = 0, [], []
                cache = self._prefix_cache
                if (cache is not None and req.presence_penalty == 0
                        and req.frequency_penalty == 0):
                    t_lk = time.monotonic()
                    matched, shared_pages, nodes = cache.match(
                        req.prompt_ids)
                    req.prefix_lookup_ms = 1000.0 * (time.monotonic()
                                                     - t_lk)
                ok = self.allocator.can_admit(
                    total, ring_pages=self._swa_ring_pages,
                    shared_pages=len(shared_pages))
                if not ok and cache is not None:
                    # Page pressure: reclaim cold cache entries (LRU
                    # leaves; pinned blocks are untouchable) before
                    # parking the head — the admission-side half of the
                    # overload/Retry-After machinery.
                    short = self.allocator.fresh_shortfall(
                        total, ring_pages=self._swa_ring_pages,
                        shared_pages=len(shared_pages))
                    evicted = cache.evict(short) if short > 0 else 0
                    if evicted > 0:
                        if fl is not None:
                            from ..obs.flight import EVICT
                            fl.record(EVICT, val=float(evicted),
                                      free_pages=self.allocator.free_pages)
                        ok = self.allocator.can_admit(
                            total, ring_pages=self._swa_ring_pages,
                            shared_pages=len(shared_pages))
                if not ok:
                    if cache is not None:
                        cache.release_nodes(nodes)
                    break
            direct = False
            if self._disagg is not None:
                # Direct-to-decode placement (no handoff): a warm prefix
                # hit whose unmatched tail fits ONE chunk skips the
                # prefill pool entirely (the matched span never prefills
                # at all — the composition the radix cache buys), and a
                # penalty request must build its on-device token counts
                # on the slot that will decode it (it bypasses the
                # prefix cache for the same reason, so matched == 0).
                direct = (req.presence_penalty != 0
                          or req.frequency_penalty != 0
                          or (matched > 0
                              and len(req.prompt_ids) - matched
                              <= self.prefill_chunk))
                if not direct and not cold_ok:
                    # Cold prompt but no prefill slot (or no decode slot
                    # to reserve): park at the FIFO head, exactly like a
                    # page-reservation shortfall.
                    if cache is not None:
                        cache.release_nodes(nodes)
                    break
            if self._disagg is None:
                target_pool = self._admit_pool
                req.slot = target_pool.take()
            elif direct:
                target_pool = self._decode_pool
                req.slot = target_pool.take()
                req.decode_slot = req.slot
            else:
                target_pool = self._admit_pool
                req.slot = target_pool.take()
                req.decode_slot = self._decode_pool.take()  # reservation
            req.pool = target_pool.pool_id
            target_pool.admits += 1
            self._head = None
            # Queue-wait gauge (submit → slot admission): the scheduler
            # half of TTFT — what the prefill-aware burst clamp bounds.
            # t_admitted also closes the trace's engine.queued phase.
            req.t_admitted = time.monotonic()
            wait_ms = 1000.0 * (req.t_admitted - req.t_submit)
            self._queue_wait_n += 1
            self._queue_wait_ema_ms = (
                wait_ms if self._queue_wait_ema_ms is None
                else 0.8 * self._queue_wait_ema_ms + 0.2 * wait_ms)
            self._queue_wait_max_ms = max(self._queue_wait_max_ms, wait_ms)
            if self.spec_k:
                # New text in this slot: acceptance starts unmeasured.
                # (Reset at ADMISSION, not release, so stats keep the last
                # measured rate while the engine drains/idles.) The
                # per-slot suspension lifts with it — the new request's
                # text regime owes nothing to its predecessor's.
                self._spec_ema[req.slot] = np.nan
                self._spec_suspended[req.slot] = False
                self._spec_slot_proposed[req.slot] = 0
                self._spec_slot_accepted[req.slot] = 0
            if self.paged:
                self.allocator.allocate(req.slot, total,
                                        ring_pages=self._swa_ring_pages,
                                        shared_pages=shared_pages)
                self._table_dirty = True
                if self._prefix_cache is not None:
                    self._prefix_cache.record_lookup(matched)
                    req.cached_tokens = matched
                    req.prefix_nodes = nodes
                if matched and self.spec_k:
                    # Prompt-lookup history for the skipped span: the
                    # per-chunk maintenance only covers chunks that
                    # actually run, and its pos==0 reset never fires on a
                    # warm admission.
                    self.hist[req.slot, :] = 0
                    self.hist[req.slot, :matched] = req.prompt_ids[:matched]
            # Warm admission starts prefill at the match boundary — the
            # matched span's prefill FLOPs are skipped outright (the
            # chunk's attention reads the shared pages through the table,
            # exactly like a later chunk of a cold prefill).
            req.prefill_pos = req.cached_tokens
            self._running[req.slot] = req
            self._prefilling[req.slot] = req
            if fl is not None:
                from ..obs.flight import ADMIT
                req.flight_admit_seq = fl.record(
                    ADMIT, slot=req.slot, val=wait_ms,
                    tokens=req.cached_tokens,
                    queued=self._queue.qsize() + (1 if self._head else 0),
                    free_slots=self._free_slot_count(),
                    free_pages=(self.allocator.free_pages if self.paged
                                else -1),
                    pool=req.pool,
                    rid=req.request_id or None)

        t_pf0 = fl.clock() if fl is not None else 0.0
        # 2. Advance each pending prefill by ONE chunk (chunked-prefill
        #    interleave: a long prompt never blocks decode for more than one
        #    chunk — SURVEY.md §7 hard part (6)). Same-bucket chunks group
        #    into ONE compiled call (batched admission — dispatch cost
        #    dominates chunk compute, see _prefill_chunk_group), the group
        #    size snapped down to a compiled K rung. Multihost runs K=1:
        #    followers replay per-slot PREFILL frames, and coordinator/
        #    follower programs must stay bit-identical. The seq-sharded
        #    engine also runs K=1 (its prefill is one whole-prompt ring
        #    program; admission concurrency is not its regime).
        eligible: list[GenRequest] = []
        for slot, req in list(self._prefilling.items()):
            if req.cancelled:
                self._finish(req, "cancelled", emit=False)
                continue
            eligible.append(req)
        batch_k = (1 if self._bridge.enabled or self.seq_n > 1
                   else self._prefill_k_rungs[0])
        if batch_k <= 1 or len(eligible) <= 1:
            for req in eligible:
                if req.cancelled:
                    # Cancelled during an earlier request's await this tick:
                    # don't burn one more prefill chunk on a dead client.
                    self._finish(req, "cancelled", emit=False)
                    continue
                prompt_done = await asyncio.to_thread(
                    self._prefill_one_chunk, req)
                n_chunks += 1
                if prompt_done:
                    del self._prefilling[req.slot]
                    if self._disagg is not None:
                        self._handoff(req)
                    n_tok += 1
                    self._emit_token(req)  # first token, sampled off prefill
        else:
            groups: dict[int, list[GenRequest]] = {}
            for req in eligible:
                pos = req.prefill_pos
                ch = min(self.prefill_chunk, len(req.prompt_ids) - pos)
                bucket = min(_bucket(ch, self.prefill_chunk), self.S - pos)
                groups.setdefault(bucket, []).append(req)
            for reqs in groups.values():
                pending = reqs
                while pending:
                    # Re-check cancellation per dispatch: a cancel that
                    # landed during a previous group's await must not burn
                    # one more prefill chunk, and dropping it here lets the
                    # survivors re-snap to a smaller compiled K rung.
                    live: list[GenRequest] = []
                    for req in pending:
                        if req.cancelled:
                            self._finish(req, "cancelled", emit=False)
                        else:
                            live.append(req)
                    if not live:
                        break
                    batch = self.prefill_groups(live)[0]
                    pending = live[len(batch):]
                    dones = await asyncio.to_thread(
                        self._prefill_chunk_group, batch)
                    n_chunks += 1
                    for req, prompt_done in zip(batch, dones):
                        if prompt_done:
                            del self._prefilling[req.slot]
                            if self._disagg is not None:
                                self._handoff(req)
                            n_tok += 1
                            self._emit_token(req)

        n_tok_prefill = n_tok           # first tokens, sampled off prefill
        if self._disagg is not None and fl is not None and n_chunks:
            # Disaggregated mode emits the PREFILL pool's step record
            # here, with its own wall window, so the per-pool Perfetto
            # lanes (tools/flight_report.py) show where each pool's time
            # actually went; the decode pool's record lands after the
            # burst below. A unified engine keeps its single combined
            # record — snapshot-identical to the pre-pool format.
            from ..obs import flight as _fl
            pf_wall_ms = 1000.0 * (fl.clock() - t_pf0)
            self._disagg.note_prefill_wall(pf_wall_ms / n_chunks)
            fitted = self._ema_step_ms_stats
            fl.record(
                _fl.STEP, flag=_fl.F_PREFILL, chunks=n_chunks,
                tokens=n_tok_prefill, dur_ms=pf_wall_ms,
                pool=_fl.POOL_PREFILL,
                active=len(self._running),
                free_slots=self._free_slot_count(),
                queued=self._queue.qsize() + (1 if self._head else 0),
                free_pages=self.allocator.free_pages,
                fitted_ms=(fitted if fitted is not None
                           else float("nan")))

        # 3. A decode burst for all slots in decode phase. Burst depth adapts:
        #    stay shallow when new work is waiting (prefill responsiveness →
        #    TTFT), go deep when the batch is just decoding (throughput; deep
        #    bursts hide the device↔host fetch latency).
        decoding = [r for r in self._running.values()
                    if not r.done and r.slot not in self._prefilling]
        if decoding:
            # Prefill-aware (DistServe/Sarathi-style interleave): any
            # admission waiting — queued, parked at the FIFO head for a
            # page reservation, or mid-chunked-prefill — clamps the next
            # burst so prefill work never starves behind a deep scan.
            busy = (self._head is not None or not self._queue.empty()
                    or bool(self._prefilling))
            # Speculation verifies against argmax, so it engages only while
            # EVERY active slot is greedy (the common serving case);
            # sampled requests flip the whole batch to the normal burst
            # path for their lifetime — mixed batches stay correct, just
            # unaccelerated.
            spec_now = self.spec_k and self._all_greedy()
            # Adaptive drafting gate: drafting only pays while accepted
            # tokens/step clears the verify forward's overhead
            # (config.spec_min_tokens_per_step). Below it, decode normally
            # and re-probe with a single spec step every
            # spec_probe_interval rounds — so enabling speculation in
            # config is safe for non-repetitive traffic.
            spec_probe = False
            if spec_now and self._spec_wall_gate_on \
                    and not self._bridge.enabled:
                # Baseline probe: the wall gate needs a NORMAL-path step
                # time to compare against, and spec-open traffic never
                # runs normal bursts. Two consecutive normal rounds (a
                # steady same-depth pair is what lands a wall sample),
                # immediately while no baseline exists, then refreshed
                # every 8*spec_probe_interval spec rounds. Multihost is
                # excluded: its bursts run synchronously through the
                # bridge (no lag-one walls are ever sampled), so the
                # wall gate is inert there and the probe would pin
                # spec_now=False forever on a never-measured baseline.
                if self._spec_base_rounds > 0:
                    self._spec_base_rounds -= 1
                    spec_now = False
                else:
                    est = self._step_ms_estimate()
                    if est is not None:
                        self._spec_base_fails = 0
                    self._spec_base_ctr += 1
                    # Periodic refresh only while a baseline EXISTS —
                    # once the starvation guard trips (workload can't
                    # land wall samples), probing again by schedule
                    # would pay the same fruitless normal rounds
                    # forever.
                    if ((est is None and self._spec_base_fails < 4)
                            or (est is not None
                                and self._spec_base_ctr
                                >= 8 * self.spec_probe_interval)):
                        self._spec_base_ctr = 0
                        if est is None:
                            self._spec_base_fails += 1
                        self._spec_base_rounds = 1
                        spec_now = False
            if spec_now and (self.spec_min_tps > 0
                             or self._spec_wall_gate_on):
                # A batch with NO measured slots always drafts — the burst
                # IS the measurement. Unmeasured slots in a mixed batch
                # count optimistically (k+1) so fresh requests can re-open
                # the gate; one low burst closes it again. The wall-clock
                # term applies even with the acceptance threshold
                # disabled (spec_min_tokens_per_step=0): each protects
                # against a different failure mode.
                below = False
                if self.spec_min_tps > 0:
                    slots = [r.slot for r in decoding]
                    if self.spec_floor > 0:
                        # Per-slot suspension already benches poor slots —
                        # their frozen EMAs must not drag the BATCH mean
                        # below the threshold and close the gate on the
                        # slots that are still profiting. (All-suspended
                        # batches skip the burst below regardless of what
                        # the mean says.)
                        slots = [s for s in slots
                                 if not self._spec_suspended[s]] or slots
                    ema = self._spec_ema[slots]
                    if not np.all(np.isnan(ema)):
                        mean_tps = float(np.mean(np.where(
                            np.isnan(ema), self.spec_k + 1, ema)))
                        below = mean_tps < self.spec_min_tps
                wall_lose = self._spec_wall_loses()
                if below or wall_lose:
                    self._spec_probe_ctr += 1
                    if self._spec_probe_ctr >= self.spec_probe_interval:
                        self._spec_probe_ctr = 0
                        spec_probe = True            # 1-step re-measure
                        # A probe re-measures ACCEPTANCE only. If the
                        # WALL term is what closed the gate, drop the
                        # wall gauge every few probe cycles so one full
                        # burst can re-time it under current conditions
                        # (bounded tax: one possibly-slow burst per 4
                        # probe intervals). An acceptance-only close
                        # must NOT drop it — no full spec burst would
                        # run to re-measure, silently losing the gauge
                        # (and its stats field) while a stale-free
                        # baseline still protects the reopen path.
                        if wall_lose and not below:
                            self._spec_wall_age += 1
                            if (self._spec_ms_per_tok is not None
                                    and self._spec_wall_age >= 4):
                                self._spec_wall_age = 0
                                self._spec_ms_per_tok = None
                    else:
                        spec_now = False
            if spec_now and self.spec_floor > 0 and not spec_probe:
                # Per-slot adaptive drafting (spec_acceptance_floor):
                # suspended slots ride along in the k+1-wide verify at a
                # deterministic 1 token/step, so when EVERY decoding slot
                # is suspended the burst is pure overhead — decode
                # normally instead, and every spec_probe_interval such
                # rounds run ONE probe burst with the mask lifted so
                # suspended slots get re-measured (text regimes change;
                # a permanent bench would strand them). A mixed batch
                # keeps bursting (drafting slots still profit) and the
                # same cadence lifts the mask for its benched slots.
                susp = sum(bool(self._spec_suspended[r.slot])
                           for r in decoding)
                if susp:
                    self._spec_suspend_probe_ctr += 1
                    if (self._spec_suspend_probe_ctr
                            >= self.spec_probe_interval):
                        self._spec_suspend_probe_ctr = 0
                        spec_probe = True        # 1-step, mask lifted
                    elif susp == len(decoding):
                        spec_now = False
            # While a spec burst is in flight (lag-one), the host lengths
            # lag dispatch by a data-dependent amount — cap against the
            # worst case (every in-flight step fully accepted).
            inflight = self._spec_inflight_advance() if self.spec_k else 0
            if spec_now:
                # A slot whose dispatch-true length is within k of the
                # cache extent can't fit a k+1-wide verify (possible when
                # lag-one normal bursts ran it ahead of emission): fall
                # back to the 1-wide normal path until emission retires it.
                spec_now = all(
                    self.S - (int(self.lengths[r.slot]) + inflight)
                    >= self.spec_k + 1
                    for r in decoding)
            if spec_now:
                # Speculative steps advance 1..k+1 positions each; cap so a
                # fully-accepted burst fits every slot's cache reserve and
                # token budget.
                kp1 = self.spec_k + 1
                burst = 1 if (busy or spec_probe) else self._spec_scan_len
                for r in decoding:
                    ub = int(self.lengths[r.slot]) + inflight
                    room = (self.S - ub) // kp1
                    dispatched = ub - len(r.prompt_ids) + 1
                    left = max(1, r.max_tokens - dispatched)
                    burst = min(burst, max(1, room), -(-left // kp1))
                if self._swa_ring_pages:
                    self._swa_rotate(decoding, inflight, max(1, burst) * kp1)
                burst = max(1, burst)
                t_dec0 = fl.clock() if fl is not None else 0.0
                spec_acc0 = self._spec_accepted_total
                step_tokens = await asyncio.to_thread(
                    self._spec_burst, burst, spec_probe)
                spec_acc_n = self._spec_accepted_total - spec_acc0
            else:
                burst = self._burst_depth(busy)
                # Never burst past any slot's cache capacity or token
                # budget — both computed from DISPATCH-TRUE state
                # (self.lengths advances at dispatch): with lag-one
                # pipelining, len(r.generated) lags a burst behind and
                # would let a whole discarded burst through. `inflight`
                # covers a pending spec burst (mode switch): its
                # data-dependent advance lands on the host mirrors inside
                # _decode_burst, AFTER these caps are computed.
                for r in decoding:
                    ub = int(self.lengths[r.slot]) + inflight
                    dispatched = ub - len(r.prompt_ids) + 1
                    burst = min(burst, self.S - ub,
                                max(1, r.max_tokens - dispatched))
                burst = max(1, burst)
                if self._swa_ring_pages:
                    self._swa_rotate(decoding, inflight, burst)
                t_dec0 = fl.clock() if fl is not None else 0.0
                step_tokens = await asyncio.to_thread(
                    self._decode_burst, burst)
            dec_wall_ms = (1000.0 * (fl.clock() - t_dec0)
                           if fl is not None else 0.0)
            for tokens in step_tokens:          # in generation order
                for req in decoding:
                    if req.done:
                        continue
                    tok = int(tokens[req.slot])
                    if tok < 0:
                        # Lag-one pipelining: this token array predates the
                        # slot's current request (masked in _flush_entry).
                        continue
                    req.generated.append(tok)
                    n_tok += 1
                    self._emit_token(req)
        progressed = bool(decoding) or bool(self._prefilling)
        if not progressed and self._free_slot_count() and (
                self._head is not None or not self._queue.empty()):
            # Slots freed DURING this step (e.g. every prefilling request
            # cancelled mid-chunk) while admissions still wait: phase 1
            # already ran with no free slot, and nothing but submit()
            # sets the work event — without this the loop parks and
            # strands the queue until the next request arrives (latent
            # since the chunked-prefill interleave; the flight recorder's
            # cancellation chaos test caught it).
            progressed = True
        if fl is not None and (n_chunks or decoding):
            # The step record: what this iteration ran, how long it took,
            # and the scheduler's fitted step time next to the measured
            # one — the per-decision feed the EMAs compress away.
            from ..obs import flight as _fl
            flag = 0
            depth = 0
            if n_chunks:
                flag |= _fl.F_PREFILL
            if decoding:
                flag |= _fl.F_DECODE
                depth = burst
                if spec_now:
                    flag |= _fl.F_SPEC
                if busy:
                    flag |= _fl.F_BUSY
                if self._busy_clamps > clamps0:
                    flag |= _fl.F_CLAMPED
            # The steady-pair EMA gauge, not _step_ms_estimate(): the
            # fit walks every wall sample and would cost more per step
            # than the record itself.
            fitted = self._ema_step_ms_stats
            if self._disagg is not None:
                # The prefill pool's share of this iteration already went
                # out after phase 2; this record is the decode pool's
                # view (dur = burst wall, so steps_overlapping() sums
                # true decode occupancy). Prefill-only iterations emit
                # nothing here.
                if decoding:
                    fl.record(
                        _fl.STEP, flag=flag & ~_fl.F_PREFILL,
                        depth=depth, tokens=n_tok - n_tok_prefill,
                        dur_ms=dec_wall_ms,
                        val=dec_wall_ms,
                        pool=_fl.POOL_DECODE,
                        active=len(self._running),
                        free_slots=self._free_slot_count(),
                        queued=(self._queue.qsize()
                                + (1 if self._head else 0)),
                        free_pages=self.allocator.free_pages,
                        fitted_ms=(fitted if fitted is not None
                                   else float("nan")))
            else:
                fl.record(
                    _fl.STEP, flag=flag, depth=depth, tokens=n_tok,
                    chunks=n_chunks,
                    dur_ms=1000.0 * (fl.clock() - t_step0),
                    spec_acc=spec_acc_n,
                    val=dec_wall_ms if decoding else 0.0,
                    active=len(self._running),
                    free_slots=self._free_slot_count(),
                    queued=self._queue.qsize() + (1 if self._head else 0),
                    free_pages=(self.allocator.free_pages if self.paged
                                else -1),
                    fitted_ms=(fitted if fitted is not None
                               else float("nan")))
        return progressed

    # -- compute (worker thread; no asyncio objects touched) ------------------
    def _prefill_one_chunk(self, req: GenRequest) -> bool:
        """Run one prompt chunk; returns True when the prompt is complete
        (first token sampled and slot armed for decode)."""
        return self._prefill_chunk_group([req])[0]

    def prefill_groups(self, items: list) -> list[list]:
        """Split ``items`` into batched-prefill group sizes, snapping each
        group DOWN to a compiled K rung. The ONE copy of the snapping
        policy: the scheduler's grouper and the bench's fill loop both
        call it, so the bench always warms/times exactly the programs
        serving admission runs."""
        out, i = [], 0
        while i < len(items):
            k = next(r for r in self._prefill_k_rungs if r <= len(items) - i)
            out.append(items[i:i + k])
            i += k
        return out

    def _prefill_chunk_group(self, reqs: list[GenRequest]) -> list[bool]:
        """Advance each request by one prompt chunk in ONE compiled call
        (K=1 is the single-request path). Batching cuts admission's
        dominant cost on a tunneled chip — the per-dispatch round trip
        (BENCH_SELF_r5b: 77 ms/chunk against ~3 ms of 1.1B chunk
        compute) — K queued prefills pay it once. The scheduler's
        grouper guarantees every request here shares one compile bucket
        and that multihost runs K=1 only (followers replay per-slot
        PREFILL frames; coordinator/follower programs must stay
        bit-identical). Returns per-request prompt-complete flags."""
        slots, poss, chunks, samps = [], [], [], []
        for req in reqs:
            slot = req.slot
            ids = req.prompt_ids
            pos = req.prefill_pos
            if pos == 0:
                self.lengths[slot] = 0
                self.active[slot] = False
            chunk = np.asarray(ids[pos:pos + self.prefill_chunk], np.int32)
            if self._swa_ring_pages:
                # Map the pages this chunk writes by recycling pages wholly
                # below the chunk's window floor (no in-flight margin: a
                # prefilling slot has no decode burst of its own in flight,
                # and cross-slot bursts touch only their own table rows).
                page = self.allocator.page_size
                dead = max(0, pos - self.model_cfg.sliding_window + 1) \
                    // page
                if self.allocator.ensure_mapped(
                        slot, (pos + len(chunk) - 1) // page, dead):
                    self._table_dirty = True
            if self.fault_plan:
                self.fault_plan.on_prefill()
            self._spec_hist_chunk(slot, pos, chunk)
            self._bridge.publish_prefill(slot, pos, chunk,
                                         table=self._table_to_publish())
            slots.append(slot)
            poss.append(pos)
            chunks.append(chunk)
            samps.append((req.temperature, req.top_p, req.top_k,
                          req.presence_penalty, req.frequency_penalty))
        self._rng, key = jax.random.split(self._rng)
        first, self.cache = self._exec_prefill(
            slots, poss, chunks, samp=samps, key=key)
        done: list[bool] = []
        first_np: np.ndarray | None = None
        for i, req in enumerate(reqs):
            req.prefill_pos = poss[i] + len(chunks[i])
            if req.prefill_pos < len(req.prompt_ids):
                done.append(False)
                continue
            # Prompt complete: the first token was sampled inside the
            # prefill program (see prefill_step) — ONE host fetch for the
            # whole group completes the TTFT path. Followers of a
            # multi-host mesh ran the same program with dummy sampling
            # inputs and never fetch; the real token reaches them inside
            # the next decode burst's broadcast state.
            if first_np is None:
                first_np = np.asarray(first)
            first_id = int(first_np[i])
            req.generated.append(first_id)
            req.t_first_token = time.monotonic()
            self.lengths[req.slot] = len(req.prompt_ids)
            self.last_token[req.slot] = first_id
            # (Token history for prompt-lookup drafting is maintained per
            # CHUNK above — identically on multihost followers, so every
            # process's hist mirror stays bit-identical at all times; the
            # first generated token is the input at P, written by the
            # spec step that consumes it.)
            self.active[req.slot] = True
            self.samp_temperature[req.slot] = req.temperature
            self.samp_top_p[req.slot] = req.top_p
            self.samp_top_k[req.slot] = req.top_k
            self.samp_presence[req.slot] = req.presence_penalty
            self.samp_frequency[req.slot] = req.frequency_penalty
            self._d_dirty = True
            done.append(True)
        return done

    def _exec_prefill(self, slot, pos, chunk,
                      samp=None, key: jax.Array | None = None):
        """The one compiled-prefill call — identical on coordinator and
        followers (np/uncommitted inputs are auto-replicated, so the same
        call works single-process and across a multi-host mesh; followers
        pass no sampling state and ignore the sampled token — the cache
        update is input-value-identical either way).

        ``slot``/``pos``/``chunk``/``samp`` are scalars-and-one-chunk for
        the K=1 path, or equal-length lists for BATCHED admission (the
        scheduler's grouper). The compile bucket is derived here, from
        chunk lengths and engine config, so coordinator/followers/bench
        can never disagree on it; batches share one bucket (the grouper
        only batches same-bucket chunks). Clamped so pos+bucket never
        exceeds the cache extent S for ANY row: XLA clamps
        dynamic_update_slice starts, so an overrunning padded chunk
        would silently shift and corrupt earlier KV entries. (Paged
        layout: out-of-range pad positions land on the trash page.)
        Returns (first_tokens [K, replicated device array], cache)."""
        single = np.isscalar(slot) or isinstance(slot, (int, np.integer))
        slots = [slot] if single else list(slot)
        poss = [pos] if single else list(pos)
        chunks = [chunk] if single else list(chunk)
        samps = ([samp] if single else list(samp)) if samp is not None \
            else [(0.0, 1.0, 0, 0.0, 0.0)] * len(slots)
        K = len(slots)
        bucket = min(_bucket(max(len(ch) for ch in chunks),
                             self.prefill_chunk),
                     self.S - max(poss))
        if self.seq_n > 1:
            # Ring attention shards the chunk's T dim over `seq`: round the
            # bucket up to a multiple of the axis size (pads are causally
            # invisible to real positions; their K/V lands beyond `lengths`
            # in the documented undefined zone).
            bucket = min(-(-bucket // self.seq_n) * self.seq_n,
                         self.S - max(poss))
        padded = np.zeros((K, bucket), np.int32)
        for i, ch in enumerate(chunks):
            padded[i, :len(ch)] = ch
        table = (self._device_table(),) if self.paged else ()
        if key is None:
            key = _DUMMY_KEY()
        args = (self.params, self.cache, self._d_counts, *table, padded,
                np.asarray(poss, np.int32), np.asarray(slots, np.int32),
                np.asarray([len(ch) - 1 for ch in chunks], np.int32),
                np.asarray([s[0] for s in samps], np.float32),
                np.asarray([s[1] for s in samps], np.float32),
                np.asarray([s[2] for s in samps], np.int32),
                np.asarray([s[3] for s in samps], np.float32),
                np.asarray([s[4] for s in samps], np.float32), key)
        # Kernel registry (ISSUE 8): one row per (bucket, K) prefill
        # program; the aval capture + cost closure is paid once per
        # variant. The wall is the dispatch wall (on an async backend the
        # device time lands in the group's later fetch; CPU is
        # synchronous) — per-step attribution for decode comes from the
        # flight ring, prefill rows are call/FLOPs accounting.
        kname = f"prefill.b{int(bucket)}.k{K}"
        if self.kernels.needs(kname):
            self.kernels.register(
                kname, "prefill", variant={"bucket": int(bucket), "k": K},
                cost_fn=_kernel_cost_fn(self._prefill_fn, args))
        t0 = time.monotonic()
        with _device_phase("prefill", annotate=self.profile_annotations):
            first, self._d_counts, cache = self._prefill_fn(*args)
        self.kernels.record(kname,
                            wall_ms=1000.0 * (time.monotonic() - t0))
        return first, cache

    def _kernel_variant(self, **base) -> dict:
        """Registry variant dict for a decode/spec kernel: the caller's
        keys plus the engine's KV identity (quantization, layout, DMA
        blocking) — so the roofline table's worst_kernel() ranking can be
        filtered to e.g. the int8 decode variants (ISSUE 10's kernel-work
        driver) instead of guessing from the engine config."""
        base["kv"] = self.kv_quant or "bf16"
        base["layout"] = "paged" if self.paged else "contiguous"
        if self.paged and self.kv_ppb > 1:
            base["ppb"] = self.kv_ppb
        return base

    def _exec_decode(self, n_steps: int, state: dict) -> list[np.ndarray]:
        """Run a burst from broadcast-packed host state (multihost path) —
        identical on coordinator and followers."""
        samp = SamplingParams(temperature=state["temperature"],
                              top_p=state["top_p"], top_k=state["top_k"],
                              presence_penalty=state["presence"],
                              frequency_penalty=state["frequency"])
        tokens = state["last_token"]
        lengths = state["lengths"]
        active = state["active"]
        key = jax.random.wrap_key_data(
            jnp.asarray(state["key"], jnp.uint32))
        table = (self._device_table(),) if self.paged else ()
        # Greedy fast path: computed from the broadcast state, so every
        # process of a multi-host mesh picks the same program.
        act = np.asarray(state["active"])
        greedy = not bool(
            np.any(np.asarray(state["temperature"])[act] > 0)
            or np.any(np.asarray(state["presence"])[act] != 0)
            or np.any(np.asarray(state["frequency"])[act] != 0))
        step_fn, scans = self._decode_fns[greedy]
        scan_fn = scans.get(n_steps)
        if scan_fn is not None:
            toks, _, _, self._d_counts, self.cache = scan_fn(
                self.params, self.cache, self._d_counts, *table, tokens,
                lengths, active, samp, key)
            host = np.asarray(toks)
            return [host[i] for i in range(n_steps)]
        # Feedback stays as device arrays across the chain (outputs are
        # pinned replicated, so the final fetches are process-local); only
        # the sampled tokens are pulled to host, asynchronously behind the
        # dispatch wave — same policy as the single-process path.
        pending = []
        for _ in range(n_steps):
            key, sub = jax.random.split(key)
            tokens, lengths, self._d_counts, self.cache = step_fn(
                self.params, self.cache, self._d_counts, *table, tokens,
                lengths, active, samp, sub)
            _start_host_copy(tokens)
            pending.append(tokens)
        return [np.asarray(t) for t in pending]

    def _table_to_publish(self) -> np.ndarray | None:
        """Coordinator side: the page table, but only when it changed since
        the last publish (admission/release mutate it between compiled
        calls; followers apply it before executing the op)."""
        if not (self.paged and self._bridge.enabled):
            return None
        if (self._published_table is not None
                and np.array_equal(self.allocator.table,
                                   self._published_table)):
            return None
        self._published_table = self.allocator.table.copy()
        return self._published_table

    def _apply_table(self, table: np.ndarray | None) -> None:
        """Follower side: adopt the broadcast page table as local truth."""
        if table is not None:
            self.allocator.table[:, :] = table
            self._table_dirty = True

    def _spec_hist_chunk(self, slot: int, pos: int,
                         chunk: np.ndarray) -> None:
        """Per-chunk token-history maintenance for prompt-lookup drafting
        — the ONE copy, run identically on the coordinator (from the
        scheduler) and on followers (from the replay loop), so every
        process's hist mirror is bit-identical at every moment (a spec
        upload may happen while another slot is mid-prefill)."""
        if not self.spec_k:
            return
        if pos == 0:
            self.hist[slot, :] = 0
            # Per-slot adaptive-drafting state resets HERE (not only at
            # coordinator admission): the suspension mirror now feeds
            # DEVICE data (the draft_ok mask), so it must evolve
            # bit-identically on every multihost process — and followers
            # only observe an admission through its first prefill chunk.
            # (Warm admissions skip pos==0, but the prefix cache is
            # single-host-only and the coordinator also resets at
            # admission.)
            self._spec_ema[slot] = np.nan
            self._spec_suspended[slot] = False
            self._spec_slot_proposed[slot] = 0
            self._spec_slot_accepted[slot] = 0
        self.hist[slot, pos:pos + len(chunk)] = chunk

    def _follow_prefill(self, slot: int, pos: int, chunk: np.ndarray,
                        table: np.ndarray | None = None) -> None:
        self._apply_table(table)
        self._spec_hist_chunk(slot, pos, chunk)
        _, self.cache = self._exec_prefill(slot, pos, chunk)

    def _follow_decode(self, n_steps: int, state: dict,
                       table: np.ndarray | None = None) -> None:
        self._apply_table(table)
        self.lengths[:] = state["lengths"]
        self.active[:] = state["active"]
        self.last_token[:] = state["last_token"]
        step_tokens = self._exec_decode(n_steps, state)
        # Same mirror advance as the coordinator (incl. the spec hist) so
        # a later spec reupload sees bit-identical host state.
        self._advance_after_decode(n_steps, step_tokens)

    def _advance_after_decode(self, n_steps: int,
                              step_tokens: list[np.ndarray]) -> None:
        """Shared multihost post-decode mirror advance: lengths,
        last_token, and — on speculative engines — the prompt-lookup
        history (otherwise a mixed-mode engine's hist would silently go
        stale and a later spec reupload would diverge from the device
        chain)."""
        for slot in np.nonzero(self.active)[0]:
            if self.spec_k:
                L = int(self.lengths[slot])
                if L < self.S:
                    self.hist[slot, L] = int(self.last_token[slot])
                m = min(n_steps, self.S - (L + 1))
                for t in range(m):
                    self.hist[slot, L + 1 + t] = int(step_tokens[t][slot])
            self.last_token[slot] = int(step_tokens[-1][slot])
        self.lengths[self.active] += n_steps
        if self.spec_k:
            self._d_hist_fresh = False

    def _follow_spec(self, n_steps: int, flags: int, state: dict,
                     table: np.ndarray | None = None) -> None:
        """Replay one speculative burst: sync host mirrors from the
        command state, execute the identical program (rebuilding device
        mirrors from the local hist on a reupload), and walk the fetched
        emitted matrix so lengths/last_token/hist advance exactly as on
        the coordinator. ``flags`` packs bit 0 = reupload, bit 1 = probe
        (per-slot suspension lifted for this burst); the drafting mask
        itself is derived locally — the suspension mirror evolves only
        inside _spec_walk, identically on every process."""
        reupload = bool(flags & 1)
        probe = bool(flags >> 1 & 1)
        self._apply_table(table)
        self.lengths[:] = state["lengths"]
        self.active[:] = state["active"]
        self.last_token[:] = state["last_token"]
        d_ok = self._spec_draft_ok(probe)
        host = self._exec_spec(n_steps, state if reupload else None,
                               draft_ok=d_ok)
        self._spec_walk(host, self.active.copy(), self.active.copy(),
                        drafting=d_ok)

    def run_follower(self) -> None:
        """Blocking replay loop for follower processes (process_index > 0)
        of a multi-host deployment: execute every compiled call the
        coordinator publishes, until shutdown."""
        self._bridge.follow(self._follow_prefill, self._follow_decode,
                            self._follow_spec if self.spec_k else None)

    def _spec_draft_ok(self, probe: bool) -> np.ndarray:
        """The per-slot drafting mask for one spec burst: every slot
        drafts unless per-slot suspension is on (spec_acceptance_floor)
        and the slot is suspended; a PROBE burst re-enables every slot
        for one re-measure. Identical on every multihost process: the
        suspension mirror only changes inside _spec_walk (shared), and
        the probe bit rides the OP_SPEC command."""
        if self.spec_floor <= 0 or probe:
            return np.ones((self.B,), bool)
        return ~self._spec_suspended

    def _spec_burst(self, n_steps: int,
                    probe: bool = False) -> list[np.ndarray]:
        """Run `n_steps` speculative draft+verify steps (engine/
        speculative.py). Full-size bursts run LAG-ONE pipelined like the
        normal path: this call dispatches burst N (device-side hist/token/
        length state chains between bursts) and returns burst N-1's rows,
        hiding the device→host round trip under compute. Host mirrors sync
        EXACTLY at flush time from the fetched emitted-token matrix —
        speculative advances are data-dependent (1..k+1 positions/step),
        so while a burst is in flight the host `lengths` lag dispatch and
        the scheduler caps against `_spec_inflight_advance()`'s upper
        bound. Returns emission-ready [B] token rows with -1 beyond each
        slot's accepted count (the emission loop's negative-token skip
        handles raggedness)."""
        if self.fault_plan:
            self.fault_plan.on_decode()
        if self._bridge.enabled:
            # Multihost: synchronous per burst (like the decode path) —
            # publish the command, run the identical program on every
            # process, and walk the fetched emitted matrix so all hosts'
            # mirrors stay bit-identical. The hist never rides the wire:
            # every process maintains its own mirror (see
            # _spec_hist_chunk / _spec_walk); a reupload rebuilds the
            # device hist from it on both sides. The per-slot drafting
            # mask is derived from the suspension mirror (identical on
            # every process — it evolves only through _spec_walk); only
            # the PROBE bit rides the wire, because the probe cadence
            # lives in the coordinator's scheduler.
            reupload = self._d_dirty or not self._d_hist_fresh
            self._rng, key = jax.random.split(self._rng)
            packed = self._bridge.pack_decode_state(
                self.lengths, self.active, self.last_token,
                self.samp_top_k, self.samp_temperature, self.samp_top_p,
                self.samp_presence, self.samp_frequency,
                np.asarray(jax.random.key_data(key)))
            self._bridge.publish_spec(n_steps, reupload, packed,
                                      table=self._table_to_publish(),
                                      probe=probe)
            state = self._bridge.unpack_decode_state(packed)
            d_ok = self._spec_draft_ok(probe)
            host = self._exec_spec(n_steps, state if reupload else None,
                                   draft_ok=d_ok)
            self._d_dirty = False
            self._d_hist_fresh = True
            return self._spec_walk(host, self.active.copy(),
                                   self.active.copy(), drafting=d_ok)
        # A mixed-mode engine may have a normal burst in flight (the batch
        # just turned all-greedy): land it first so mirrors are exact.
        pre = self._flush_pending()
        if self._d_dirty or not self._d_hist_fresh:
            # Upload needs exact host mirrors — land any in-flight spec
            # burst before reading them.
            pre += self._flush_spec_pending()
            self._spec_upload()
            self._d_dirty = False
            self._d_hist_fresh = True

        d_ok = self._spec_draft_ok(probe)
        d_ok_dev = jax.device_put(d_ok, NamedSharding(self.mesh, P()))
        table = (self._device_table(),) if self.paged else ()
        if n_steps == self._spec_scan_len:
            t0 = time.monotonic()
            args = (self.params, self.cache, *table, self._d_hist,
                    self._d_tokens, self._d_lengths, self._d_active,
                    d_ok_dev)
            kname = f"spec.s{n_steps}"
            if self.kernels.needs(kname):
                self.kernels.register(
                    kname, "spec", variant=self._kernel_variant(depth=n_steps),
                    cost_fn=_kernel_cost_fn(self._spec_scan, args))
            with _device_phase("spec.verify",
                               annotate=self.profile_annotations):
                emitted, self.cache, self._d_hist, self._d_tokens, \
                    self._d_lengths = self._spec_scan(*args)
                _start_host_copy(emitted)
            prev, self._spec_pending = self._spec_pending, (
                emitted, n_steps, self.active.copy(),
                self._slot_epoch.copy(), d_ok)
            before = self._spec_tokens_out
            out = pre + self._flush_spec_entry(prev)
            steady = prev is not None and prev[1] == n_steps
            self.kernels.record(
                kname, steps=n_steps,
                wall_ms=(1000.0 * (time.monotonic() - t0) if steady
                         else None))
            if steady:
                # Steady state at full spec depth: this call's wall time
                # covers one same-depth burst (lag-one), and the flushed
                # burst's emitted count is its token yield — feed the
                # wall-clock gate gauge (see _spec_wall_loses).
                toks = self._spec_tokens_out - before
                if toks > 0:
                    ms = 1000.0 * (time.monotonic() - t0) / toks
                    self._spec_ms_per_tok = (
                        ms if self._spec_ms_per_tok is None else
                        0.7 * self._spec_ms_per_tok + 0.3 * ms)
            return out

        # Partial bursts (cache/budget caps, busy depth 1) stay
        # synchronous: land the in-flight burst, then step one at a time.
        pre += self._flush_spec_pending()
        outs = []
        kname = "spec.step1"
        t0 = time.monotonic()
        with _device_phase("spec.verify", annotate=self.profile_annotations):
            for _ in range(n_steps):
                args = (self.params, self.cache, *table, self._d_hist,
                        self._d_tokens, self._d_lengths, self._d_active,
                        d_ok_dev)
                if self.kernels.needs(kname):
                    self.kernels.register(
                        kname, "spec", variant=self._kernel_variant(depth=1),
                        cost_fn=_kernel_cost_fn(self._spec_step, args))
                self._d_tokens, self._d_lengths, self.cache, self._d_hist, \
                    em, _ = self._spec_step(*args)
                _start_host_copy(em)
                outs.append(em)
            host = np.stack([np.asarray(e) for e in outs])
        self.kernels.record(kname, steps=n_steps,
                            wall_ms=1000.0 * (time.monotonic() - t0))
        return pre + self._spec_walk(host, self.active, self.active.copy(),
                                     drafting=d_ok)

    def _spec_upload(self, state: dict | None = None) -> None:
        """Rebuild EVERY device mirror for the speculative chain — the ONE
        copy for the single-process path (from the engine's own host
        mirrors) and the multihost path (from the broadcast slot state;
        the hist always comes from the LOCAL bit-identical mirror).
        Includes the sampler mirrors: a later spec→normal mode switch
        (e.g. the cache-end fallback) must not hand _decode_burst a
        never-built _d_samp — a None there retraces the decode program
        with a different pytree structure (full XLA compile
        mid-serving)."""
        rep = NamedSharding(self.mesh, P())
        s = state or {}
        self._d_tokens = jax.device_put(
            np.asarray(s.get("last_token", self.last_token), np.int32), rep)
        self._d_lengths = jax.device_put(
            np.asarray(s.get("lengths", self.lengths), np.int32), rep)
        self._d_active = jax.device_put(
            np.asarray(s.get("active", self.active), bool), rep)
        self._d_hist = jax.device_put(self.hist, rep)
        self._d_samp = SamplingParams(
            temperature=jax.device_put(np.asarray(
                s.get("temperature", self.samp_temperature), np.float32),
                rep),
            top_p=jax.device_put(np.asarray(
                s.get("top_p", self.samp_top_p), np.float32), rep),
            top_k=jax.device_put(np.asarray(
                s.get("top_k", self.samp_top_k), np.int32), rep),
            presence_penalty=jax.device_put(np.asarray(
                s.get("presence", self.samp_presence), np.float32), rep),
            frequency_penalty=jax.device_put(np.asarray(
                s.get("frequency", self.samp_frequency), np.float32), rep))

    def _exec_spec(self, n_steps: int, state: dict | None,
                   draft_ok: np.ndarray | None = None) -> np.ndarray:
        """The one compiled-speculative-burst call — identical on
        coordinator and followers. ``state`` non-None = reupload: rebuild
        every device mirror (incl. the hist, from the LOCAL bit-identical
        host mirror) from the broadcast slot state; None = chain the
        device arrays from the previous burst. ``draft_ok`` is the
        per-slot drafting mask (None = every slot drafts). Returns the
        fetched emitted matrix [n_steps, B, k+1] (synchronous — multihost
        has no lag-one)."""
        if state is not None:
            self._spec_upload(state)
        if draft_ok is None:
            draft_ok = np.ones((self.B,), bool)
        d_ok_dev = jax.device_put(draft_ok, NamedSharding(self.mesh, P()))
        table = (self._device_table(),) if self.paged else ()
        if n_steps == self._spec_scan_len:
            emitted, self.cache, self._d_hist, self._d_tokens, \
                self._d_lengths = self._spec_scan(
                    self.params, self.cache, *table, self._d_hist,
                    self._d_tokens, self._d_lengths, self._d_active,
                    d_ok_dev)
            return np.asarray(emitted)
        outs = []
        for _ in range(n_steps):
            self._d_tokens, self._d_lengths, self.cache, self._d_hist, \
                em, _ = self._spec_step(
                    self.params, self.cache, *table, self._d_hist,
                    self._d_tokens, self._d_lengths, self._d_active,
                    d_ok_dev)
            outs.append(em)
        return np.stack([np.asarray(e) for e in outs])

    def _spec_wall_loses(self) -> bool:
        """True when the measured spec wall-clock (ms per emitted token,
        EMA over full spec bursts) exceeds the normal path's (the stats
        step gauge is wall per step; every active slot advances one token
        per step). Acceptance tokens/step alone is not a profit signal:
        it ignores what the spec step itself costs, which on a tunneled
        chip (and any regime where the k+1-wide verify doesn't amortize)
        can dwarf the accepted-token win."""
        if not self._spec_wall_gate_on or self._spec_ms_per_tok is None:
            return False
        # Like-for-like baseline: the fitted per-step time (per-burst
        # fixed cost removed) — an amortized shallow-burst wall/d would
        # inflate the normal-path baseline and hold a net-loss spec open
        # under sustained busy traffic.
        base = self._step_ms_estimate()
        if base is None:
            return False
        n = max(1, int(self.active.sum()))
        return self._spec_ms_per_tok > base / n

    def _step_ms_estimate(self) -> float | None:
        """Per-decode-step ms from the per-depth burst-wall EMAs.

        wall(d) = C + d·step, so with two measured depths the slope
        Δwall/Δdepth is the fixed-cost-free step time (use the two
        LARGEST depths — widest Δ, best signal). With one depth, fall
        back to wall/d — an OVERestimate (C folded in), which errs the
        ttft cap toward shallower bursts (TTFT-safe), and is corrected
        as soon as a second depth is measured. The estimate is clamped
        to (0, min(wall/d)]: the slope can't exceed any amortized wall,
        and noise-negative slopes fall back to the conservative bound.
        Only entries refreshed within the last ``_BURST_WALL_WINDOW``
        samples participate: a depth that stopped running holds a wall
        measured under old conditions (shorter contexts, lighter
        batch), and a fit against it would bias the step time — if all
        are stale, only the most recent entry is used."""
        w = self._burst_walls
        if not w:
            return None
        stamp = self._burst_wall_stamp
        fresh = {d: ms for d, ms in w.items()
                 if self._burst_wall_n - stamp.get(d, self._burst_wall_n)
                 <= self._BURST_WALL_WINDOW}
        if not fresh:
            d = max(w, key=lambda k: stamp.get(k, 0))
            fresh = {d: w[d]}
        w = fresh
        ub = min(ms / d for d, ms in w.items())
        if len(w) >= 2:
            d1, d2 = sorted(w)[-2:]
            step = (w[d2] - w[d1]) / (d2 - d1)
            if step > 0:
                self._fit_slope = min(step, ub)
                self._fit_stamp = self._burst_wall_n
                return self._fit_slope
        # One fresh depth: the fitted slope (if it hasn't expired)
        # still carries the fixed-cost correction — wall/d alone would
        # re-fold C into the estimate and restart the shrink spiral.
        if (self._fit_slope is not None
                and self._burst_wall_n - self._fit_stamp <= self._SLOPE_TTL):
            return min(self._fit_slope, ub)
        return ub

    _BURST_WALL_WINDOW = 512
    _SLOPE_TTL = 4096
    _EXPLORE_EVERY = 32

    def _fixed_cost_ms(self) -> float | None:
        """Estimated per-burst fixed cost C from wall(d) = C + d·step —
        diagnostic only (engine-stats / bench extra): on a tunneled chip
        C is the dispatch round trip; on bare metal it is host work."""
        if (self._fit_slope is None or not self._burst_walls
                or self._burst_wall_n - self._fit_stamp > self._SLOPE_TTL):
            return None                 # expired slope = fabricated C
        d = max(self._burst_walls, key=lambda k:
                self._burst_wall_stamp.get(k, 0))
        return max(0.0, self._burst_walls[d] - d * self._fit_slope)

    def _spec_inflight_advance(self) -> int:
        """Upper bound on cache positions an in-flight speculative burst
        may still add per slot (every step fully accepted). The scheduler's
        burst caps add this to the host `lengths` mirror, which lags
        dispatch while a spec burst is pending."""
        if self._spec_pending is None:
            return 0
        return self._spec_pending[1] * (self.spec_k + 1)

    def _flush_spec_pending(self) -> list[np.ndarray]:
        entry, self._spec_pending = self._spec_pending, None
        return self._flush_spec_entry(entry)

    def _flush_spec_entry(self, entry) -> list[np.ndarray]:
        """Fetch an in-flight spec burst's emitted matrix and sync host
        mirrors exactly. The walk starts from the CURRENT host mirrors:
        bursts flush in dispatch order, so at flush time they are exact
        through the previous burst; slots released (or re-admitted) since
        dispatch are excluded by the epoch guard and their rows masked."""
        if entry is None:
            return []
        emitted, _, active_snap, epoch_snap, drafting = entry
        host = np.asarray(emitted)                       # [n, B, k+1]
        live = active_snap & (epoch_snap == self._slot_epoch)
        return self._spec_walk(host, active_snap, live, drafting=drafting)

    def _spec_walk(self, host: np.ndarray, active_snap: np.ndarray,
                   live: np.ndarray,
                   drafting: np.ndarray | None = None) -> list[np.ndarray]:
        """Exact host-mirror walk (lengths / last_token / history): each
        step's valid inputs are [current token] + accepted drafts, i.e.
        [cur] + emitted[:count-1]; the step's last emitted token becomes
        the next input. Returns emission rows (dead slots masked -1).

        ``drafting`` [B] bool is the burst's per-slot drafting mask: a
        suspended slot emitted exactly 1 token/step by construction (its
        drafts were masked to -1), so its rows carry NO acceptance signal
        — the EMA is frozen and proposal counters skip it. The suspension
        mirror itself is re-derived here (ratio = (ema-1)/k against
        spec_acceptance_floor), which keeps it bit-identical across
        multihost processes: every process runs the same walk."""
        kp1 = self.spec_k + 1
        if drafting is None:
            drafting = np.ones((self.B,), bool)
        for slot in np.nonzero(live)[0]:
            pos = int(self.lengths[slot])
            cur = int(self.last_token[slot])
            for i in range(host.shape[0]):
                toks = host[i, slot]
                count = int((toks >= 0).sum())
                if drafting[slot]:
                    # Acceptance EMA feeding the adaptive drafting gates.
                    # Asymmetric: an unmeasured slot decays from the
                    # optimistic k+1 prior — prompt-lookup needs ~10 steps
                    # for a fresh generation to enter its repetitive cycle
                    # (measured on the tiny-test workload), so a slow fall
                    # grants that grace — while a high-acceptance step
                    # rises fast (a=0.5), letting a single 1-step probe
                    # re-open a closed gate the moment text turns
                    # repetitive. Suspended slots contribute no samples:
                    # their 1 token/step is an artifact of the mask, not a
                    # measurement.
                    prev = self._spec_ema[slot]
                    if np.isnan(prev):
                        prev = float(self.spec_k + 1)
                    a = 0.5 if count > prev else 0.2
                    self._spec_ema[slot] = (1 - a) * prev + a * count
                    self._spec_slot_proposed[slot] += self.spec_k
                    self._spec_slot_accepted[slot] += max(0, count - 1)
                    self._spec_proposed_total += self.spec_k
                    self._spec_accepted_total += max(0, count - 1)
                if count == 0:
                    continue
                if pos < self.S:
                    self.hist[slot, pos] = cur
                m = min(count - 1, self.S - (pos + 1))
                if m > 0:
                    self.hist[slot, pos + 1:pos + 1 + m] = toks[:m]
                cur = int(toks[count - 1])
                pos += count
            self.lengths[slot] = pos
            self.last_token[slot] = cur
        if self.spec_floor > 0:
            # Re-derive the per-slot suspension mirror from the freshly
            # updated EMAs. ratio = (ema - 1) / k maps the EMA (1..k+1
            # tokens/step) onto the acceptance fraction [0, 1]; a slot
            # below the floor stops drafting until a probe burst (which
            # runs with the mask lifted) measures it back above. NaN =
            # never measured = keep drafting (the optimistic prior).
            for slot in np.nonzero(live & drafting)[0]:
                ema = self._spec_ema[slot]
                if np.isnan(ema):
                    continue
                ratio = (ema - 1.0) / max(1, self.spec_k)
                self._spec_suspended[slot] = bool(ratio < self.spec_floor)
        if not live.all():
            host = host.copy()
            host[:, ~live] = -1
        self._spec_steps_done += host.shape[0] * int(active_snap.sum())
        self._spec_tokens_out += int((host >= 0).sum())
        return [host[i, :, t] for i in range(host.shape[0])
                for t in range(kp1)]

    def _flush_pending(self) -> list[np.ndarray]:
        """Fetch the in-flight burst's tokens (if any) and sync the host
        ``last_token`` mirror for slots that survived unchanged since its
        dispatch. Returns the per-step host token arrays, in order."""
        entry, self._pending = self._pending, None
        return self._flush_entry(entry)

    def _flush_entry(self, entry) -> list[np.ndarray]:
        if entry is None:
            return []
        toks_dev, n, active_snap, epoch_snap, len_snap, last_snap = entry
        host = np.asarray(toks_dev)                      # [n, B]
        live = active_snap & (epoch_snap == self._slot_epoch)
        for slot in np.nonzero(live)[0]:
            self.last_token[slot] = int(host[-1][slot])
            if self.spec_k:
                # Keep the prompt-lookup history current through the
                # NORMAL path too (mixed spec/sampled serving): the burst's
                # inputs were [last@dispatch] + tokens at positions
                # [L, L+n] (L = dispatch-time length snapshot).
                L = int(len_snap[slot])
                if L < self.S:
                    self.hist[slot, L] = int(last_snap[slot])
                m = min(n, self.S - (L + 1))
                if m > 0:
                    self.hist[slot, L + 1:L + 1 + m] = host[:m, slot]
        if not live.all():
            # Slots released (or released+re-admitted) since this burst's
            # dispatch: their tokens belong to a dead request — mask with
            # -1 so the emission loop can't attribute them to the slot's
            # CURRENT request.
            host = host.copy()
            host[:, ~live] = -1
        return [host[i] for i in range(n)]

    def _swa_rotate(self, decoding, inflight: int, advance: int) -> None:
        """Sliding-window ring: before dispatching a burst, map the logical
        pages it will write (dispatch-true lengths + worst-case advance)
        by recycling pages wholly below the window floor minus one burst
        of margin — an undelivered lag-one burst may still read near its
        own, older floor. Runs on the event-loop thread (same as
        admission), before the worker-thread dispatch reads the table."""
        page = self.allocator.page_size
        w = self.model_cfg.sliding_window
        changed = False
        for r in decoding:
            pos = int(self.lengths[r.slot]) + inflight
            dead = max(0, pos - self._swa_margin - w + 1) // page
            changed |= self.allocator.ensure_mapped(
                r.slot, (pos + advance) // page, dead)
        if changed:
            self._table_dirty = True

    def _all_greedy(self) -> bool:
        """True when every ACTIVE slot is plain-greedy: temperature 0 and
        zero penalties — the condition for the argmax-only decode program
        AND for speculation (its verify is plain argmax)."""
        a = self.active
        return not bool(np.any(self.samp_temperature[a] > 0)
                        or np.any(self.samp_presence[a] != 0)
                        or np.any(self.samp_frequency[a] != 0))

    def _burst_depth(self, busy: bool) -> int:
        """Depth of the next normal decode burst.

        Busy (work queued or prefilling): the shallow depth, so new work
        interleaves within one shallow burst. Idle with ``ttft_target_ms``
        set: an arriving probe cannot preempt the scan already dispatched,
        so its TTFT floor is in-flight depth × step time plus the flush +
        prefill chunk that follow admission — cap the deep depth so the
        exposure spends at most HALF the target, sized by the engine's
        own fitted step time (``_step_ms_estimate``: Δwall/Δdepth, so
        per-burst fixed cost doesn't bias the cap). The cap snaps DOWN
        to a compiled scan depth (``_burst_depths``): an arbitrary
        depth would fall off the fused-scan fast path onto per-step
        dispatch. Until the model has a sample, run the configured
        depth — the first bursts are the measurement.

        Busy bursts are ALSO step-time-aware when a TTFT target is set
        (the prefill-aware clamp, ISSUE 2): at target scale a step costs
        ~23 ms, so even the configured busy depth can spend several
        hundred ms between prefill chunks — each chunk of a queued
        admission then waits out a full busy burst, and a multi-chunk
        prompt accumulates that into the 742.8 ms p50 measured in r5b.
        The clamp caps a busy burst at a QUARTER of the target (the
        interleave runs once per chunk; prefill + flush spend the rest),
        dropping below ``decode_burst_busy`` — to the synchronous
        burst=1 path if nothing compiled fits — while leaving idle-queue
        bursts at the unchanged deep/capped depth."""
        if busy:
            # A busy interleave splits an in-progress exploration pair —
            # its second burst would run against a busy-depth
            # predecessor and record nothing. Cancel rather than spend
            # the deep-burst TTFT exposure for no sample.
            self._explore_pending = 0
            pick = self.decode_burst_busy
            if self.ttft_target_ms > 0:
                est = self._step_ms_estimate()
                if est:
                    cap = 0.25 * self.ttft_target_ms / est
                    if cap < pick:
                        fitting = [d for d in self._burst_depths
                                   if d <= cap]
                        pick = max(fitting) if fitting else 1
                        self._busy_clamps += 1
            self._last_burst_depth = pick
            self._depth_hist[pick] = self._depth_hist.get(pick, 0) + 1
            return pick
        pick = self.decode_burst
        if self.ttft_target_ms > 0:
            est = self._step_ms_estimate()
            if est:
                cap = 0.5 * self.ttft_target_ms / est
                fitting = [d for d in self._burst_depths if d <= cap]
                pick = (min(max(fitting), self.decode_burst) if fitting
                        else self._burst_depths[0])
            # Exploration: a steady PAIR one compiled rung deeper, every
            # _EXPLORE_EVERY idle bursts, keeps a second fresh depth in
            # the wall model so the slope fit never degenerates to the
            # C-biased one-depth form (see _step_ms_estimate).
            if self._explore_pending > 0 and self._explore_depth > pick:
                self._explore_pending -= 1
                pick = self._explore_depth
            else:
                self._explore_pending = 0
                self._idle_burst_i += 1
                if pick < self.decode_burst and \
                        self._idle_burst_i % self._EXPLORE_EVERY == 0:
                    deeper = [d for d in self._burst_depths
                              if pick < d <= self.decode_burst]
                    if deeper:
                        self._explore_depth = deeper[0]
                        self._explore_pending = 1
                        pick = self._explore_depth
        self._last_burst_depth = pick
        self._depth_hist[pick] = self._depth_hist.get(pick, 0) + 1
        return pick

    def _decode_burst(self, n_steps: int) -> list[np.ndarray]:
        """Run `n_steps` chained decode steps; tokens/lengths feed back as
        device arrays (no host round-trip inside the chain) and each step's
        sampled tokens are fetched asynchronously behind the dispatch wave.
        Full-size bursts run LAG-ONE pipelined: this call dispatches burst
        N and returns burst N-1's tokens, so the fetch round trip hides
        under device compute. Returns host token arrays in generation
        order (possibly from the previous burst; possibly two bursts'
        worth when a flush was forced)."""
        if self.fault_plan:
            self.fault_plan.on_decode()
        if self._bridge.enabled:
            # Multihost: broadcast the full slot state + rng key every
            # burst (a few [B] vectors — negligible next to the decode
            # itself) so coordinator and followers build bit-identical
            # program inputs; then run the same _exec_decode both sides.
            self._rng, key = jax.random.split(self._rng)
            packed = self._bridge.pack_decode_state(
                self.lengths, self.active, self.last_token, self.samp_top_k,
                self.samp_temperature, self.samp_top_p, self.samp_presence,
                self.samp_frequency,
                np.asarray(jax.random.key_data(key)))
            self._bridge.publish_decode(n_steps, packed,
                                        table=self._table_to_publish())
            step_tokens = self._exec_decode(
                n_steps, self._bridge.unpack_decode_state(packed))
            self._advance_after_decode(n_steps, step_tokens)
            return step_tokens

        pre: list[np.ndarray] = []
        if self.spec_k:
            # Mode switch (a sampled request joined): land any in-flight
            # SPECULATIVE burst first — its data-dependent advances must
            # reach the host mirrors before this path reads/advances them.
            pre += self._flush_spec_pending()
        if self._d_dirty:
            # Host slot state changed (admission/release/prefill). The
            # in-flight burst must land first: the upload below reads the
            # host `last_token` mirror, which that burst's tokens update.
            pre += self._flush_pending()
            # Upload once, pinned to the SAME replicated sharding the
            # compiled programs produce — a plain jnp.asarray upload
            # carries SingleDeviceSharding while the program outputs fed
            # back next burst carry NamedSharding(mesh, P()), and that
            # aval mismatch silently recompiled the whole burst program on
            # the first post-upload call (the r2 bench's "64.5 ms/step"
            # was mostly this one recompile).
            rep = NamedSharding(self.mesh, P())
            self._d_tokens = jax.device_put(self.last_token, rep)
            self._d_lengths = jax.device_put(self.lengths, rep)
            self._d_active = jax.device_put(self.active, rep)
            self._d_samp = SamplingParams(
                temperature=jax.device_put(self.samp_temperature, rep),
                top_p=jax.device_put(self.samp_top_p, rep),
                top_k=jax.device_put(self.samp_top_k, rep),
                presence_penalty=jax.device_put(self.samp_presence, rep),
                frequency_penalty=jax.device_put(self.samp_frequency, rep))
            self._d_dirty = False

        table = (self._device_table(),) if self.paged else ()
        # Greedy fast path: when every active slot decodes at temperature 0
        # with zero penalties (the common case), run the argmax-only
        # program — the general sampler's full-vocab sort costs
        # measurable per-step time (penalties force the general path:
        # a penalized argmax differs from plain argmax).
        greedy = self._all_greedy()
        step_fn, scans = self._decode_fns[greedy]
        scan_fn = scans.get(n_steps)
        if scan_fn is not None:
            # Full-size burst → the single fused scan program, lag-one
            # pipelined: dispatch burst N, then fetch burst N-1 — its
            # device→host copy was queued at its own dispatch
            # (copy_to_host_async), so the transfer streamed while burst N
            # computes and the asarray below is (near-)immediate. Partial
            # bursts (tail of a request's token budget, or prefill work
            # pending) fall through to the synchronous step loop below.
            t0 = time.monotonic()
            self._rng, key = jax.random.split(self._rng)
            args = (self.params, self.cache, self._d_counts, *table,
                    self._d_tokens, self._d_lengths, self._d_active,
                    self._d_samp, key)
            kname = (f"decode.d{n_steps}."
                     f"{'greedy' if greedy else 'sampled'}")
            if self.kernels.needs(kname):
                self.kernels.register(
                    kname, "decode",
                    variant=self._kernel_variant(depth=n_steps, greedy=greedy),
                    cost_fn=_kernel_cost_fn(scan_fn, args))
            with _device_phase("decode", annotate=self.profile_annotations):
                toks, self._d_tokens, self._d_lengths, self._d_counts, \
                    self.cache = scan_fn(*args)
                _start_host_copy(toks)
            prev, self._pending = self._pending, (
                toks, n_steps, self.active.copy(), self._slot_epoch.copy(),
                self.lengths.copy(), self.last_token.copy())
            # Host length mirror advances at DISPATCH time — the burst-
            # capping logic in _step must see the device-true lengths.
            self.lengths[self.active] += n_steps
            if self.spec_k:
                self._d_hist_fresh = False
            out = pre + self._flush_entry(prev)
            if prev is not None and prev[1] == n_steps:
                # Steady same-depth pair: this call's wall time covers
                # exactly one burst at this depth (lag-one). Depth
                # transitions (busy<->idle) are excluded — the previous
                # burst's wait divided by the new depth would feed
                # ~4x-off samples. Feeds BOTH the per-depth wall model
                # (_step_ms_estimate — the ttft cap's input) and the
                # operator stats gauge.
                wall = 1000.0 * (time.monotonic() - t0)
                prev_w = self._burst_walls.get(n_steps)
                self._burst_walls[n_steps] = (
                    wall if prev_w is None else 0.8 * prev_w + 0.2 * wall)
                self._burst_wall_n += 1
                self._burst_wall_stamp[n_steps] = self._burst_wall_n
                ms_any = wall / n_steps
                self._ema_step_ms_stats = (
                    ms_any if self._ema_step_ms_stats is None else
                    0.8 * self._ema_step_ms_stats + 0.2 * ms_any)
                # Steady-pair walls are the only honest lag-one walls —
                # transition bursts count calls but contribute no time.
                self.kernels.record(kname, steps=n_steps, wall_ms=wall)
            else:
                self.kernels.record(kname, steps=n_steps)
            return out

        # Synchronous path: flush any in-flight burst first so tokens are
        # returned in generation order.
        pre += self._flush_pending()
        pending: list[jax.Array] = []
        kname = f"decode.step1.{'greedy' if greedy else 'sampled'}"
        t0 = time.monotonic()
        with _device_phase("decode", annotate=self.profile_annotations):
            for _ in range(n_steps):
                self._rng, key = jax.random.split(self._rng)
                args = (self.params, self.cache, self._d_counts, *table,
                        self._d_tokens, self._d_lengths, self._d_active,
                        self._d_samp, key)
                if self.kernels.needs(kname):
                    self.kernels.register(
                        kname, "decode",
                        variant=self._kernel_variant(depth=1, greedy=greedy),
                        cost_fn=_kernel_cost_fn(step_fn, args))
                self._d_tokens, self._d_lengths, self._d_counts, \
                    self.cache = step_fn(*args)
                _start_host_copy(self._d_tokens)
                pending.append(self._d_tokens)
            step_tokens = [np.asarray(t) for t in pending]
        # The fetch above synchronizes, so this wall is honest per call.
        self.kernels.record(kname, steps=n_steps,
                            wall_ms=1000.0 * (time.monotonic() - t0))
        # Mirror device-side length advance on the host (+ history for
        # mixed-mode speculative engines).
        for slot in np.nonzero(self.active)[0]:
            if self.spec_k:
                L = int(self.lengths[slot])
                if L < self.S:
                    self.hist[slot, L] = int(self.last_token[slot])
                m = min(n_steps, self.S - (L + 1))
                for t in range(m):
                    self.hist[slot, L + 1 + t] = int(step_tokens[t][slot])
            self.last_token[slot] = int(step_tokens[-1][slot])
        self.lengths[self.active] += n_steps
        if self.spec_k:
            self._d_hist_fresh = False
        return pre + step_tokens

    # -- emission / lifecycle (event-loop thread only) ------------------------
    def _emit_token(self, req: GenRequest) -> None:
        if req.cancelled:
            self._finish(req, "cancelled", emit=False)
            return
        tok = req.generated[-1]
        if tok in self.tokenizer.eos_ids:
            self._finish(req, "stop")
            return
        req.text += req.detok.push(tok)

        # OpenAI `stop` semantics: the stop sequence (and anything after it)
        # is excluded from the output. Because stops can span token/delta
        # boundaries, text that could still be a stop prefix is HELD BACK
        # until resolved — a complete match therefore always starts at or
        # after `emitted_upto`.
        if req.stop:
            idx = -1
            for s in req.stop:
                found = req.text.find(s, req.emitted_upto)
                if found >= 0 and (idx < 0 or found < idx):
                    idx = found
            if idx >= 0:
                req.text = req.text[:idx]
                self._finish(req, "stop", flush_detok=False)
                return

        if len(req.generated) >= req.max_tokens:
            self._finish(req, "length")
            return
        # Exact per-token cache-capacity check (host `lengths` may already be
        # a whole burst ahead of the token being emitted). Speculative
        # engines reserve k tail positions so a k+1-wide verify never
        # writes past the cache extent.
        if (len(req.prompt_ids) + len(req.generated) + 1
                >= self.S - self.spec_k):
            self._finish(req, "length")
            return

        # Emit everything except the longest tail that is a proper prefix of
        # some stop string (held back until it resolves either way).
        hold = 0
        unemitted = len(req.text) - req.emitted_upto
        for s in req.stop:
            for k in range(min(len(s) - 1, unemitted), hold, -1):
                if req.text.endswith(s[:k]):
                    hold = k
                    break
        safe_upto = len(req.text) - hold
        if safe_upto > req.emitted_upto:
            delta = req.text[req.emitted_upto:safe_upto]
            req.emitted_upto = safe_upto
            req.out_queue.put_nowait(Delta(text=delta))

    def _finish(self, req: GenRequest, reason: str, emit: bool = True,
                flush_detok: bool = True) -> None:
        if flush_detok and reason != "cancelled":
            req.text += req.detok.flush()
        req.finish_reason = reason
        req.t_done = time.monotonic()
        if emit:
            delta = req.text[req.emitted_upto:]
            req.emitted_upto = len(req.text)
            req.out_queue.put_nowait(Delta(text=delta, finish_reason=reason))
        self._release(req)

    def _prefix_release(self, req: GenRequest) -> None:
        """Insert-on-release + unpin (ISSUE 6): index the slot's completed
        KV into the radix cache BEFORE the allocator frees the row, then
        drop the pins taken at admission. Only tokens whose cache writes
        have provably landed are indexed: a mid-prefill cancellation
        covers the chunks that ran (`prefill_pos`); a decoding slot
        covers the prompt plus every generated token that has been the
        INPUT of a fetched step — the last emitted token's KV write may
        still be in flight, and with lag-one pipelining positions beyond
        it may hold a dead burst's writes, but both lie in blocks past
        the indexed span."""
        cache = self._prefix_cache
        try:
            if req.slot in self._prefilling:
                n_ok = req.prefill_pos
            else:
                n_ok = len(req.prompt_ids) + max(0, len(req.generated) - 1)
            seq = req.prompt_ids + req.generated
            cache.insert(seq, min(n_ok, self.S, len(seq)),
                         self.allocator.table[req.slot])
        finally:
            cache.release_nodes(req.prefix_nodes)
            req.prefix_nodes = []

    def _release(self, req: GenRequest) -> None:
        if req.slot in self._running:
            if self.paged and self._prefix_cache is not None:
                self._prefix_release(req)
            del self._running[req.slot]
            if self.flight is not None:
                # Every admit record gets a matching finish — the chaos
                # tests assert the pair count balances (a "leaked" flight
                # record is a request the scheduler lost track of).
                from ..obs.flight import FINISH, FINISH_REASONS
                reason = req.finish_reason or "error"
                code = (FINISH_REASONS.index(reason)
                        if reason in FINISH_REASONS else 3)
                req.flight_done_seq = self.flight.record(
                    FINISH, slot=req.slot, flag=code,
                    tokens=len(req.generated),
                    active=len(self._running),
                    free_slots=self._free_slot_count(),
                    pool=req.pool,
                    rid=req.request_id or None)
            self._prefilling.pop(req.slot, None)
            self.active[req.slot] = False
            self.lengths[req.slot] = 0
            self._pool_by_slot[req.slot].free.append(req.slot)
            if req.decode_slot >= 0 and req.decode_slot != req.slot:
                # Cold admission cancelled/shed mid-prefill: its reserved
                # decode slot was never consumed by a handoff — return it
                # or the decode pool leaks a slot per aborted prefill.
                self._decode_pool.free.append(req.decode_slot)
            req.decode_slot = -1
            if self._disagg is not None:
                self._disagg.clamp_release(req)
            self._slot_epoch[req.slot] += 1
            self._d_dirty = True
            if self.paged:
                self.allocator.release(req.slot)
                self._table_dirty = True

    def _handoff(self, req: GenRequest) -> None:
        """Promote a just-completed prefill into the decode pool
        (ISSUE 13). Zero-copy: the KV pages move by refcount transfer
        inside the allocator (same physical ids, no device memcpy) and
        only the HOST page table + per-slot mirrors change rows — the
        next dirty upload carries both. Runs on the loop thread in the
        gap between the prefill dispatch returning and the next decode
        burst, so no in-flight burst has ever seen ``active`` true for
        either slot: lag-one ``_pending`` snapshots predate the move and
        mask both rows to -1."""
        from ..obs.flight import POOL_DECODE, POOL_PREFILL
        if self.fault_plan is not None:
            self.fault_plan.on_handoff()
        if req.disagg_clamped:
            self._disagg.clamp_release(req)
        if req.pool != POOL_PREFILL:
            return      # admitted direct-to-decode: already home
        p, d = req.slot, req.decode_slot
        pages = self.allocator.transfer(p, d)
        self.lengths[d] = self.lengths[p]
        self.last_token[d] = self.last_token[p]
        self.samp_temperature[d] = self.samp_temperature[p]
        self.samp_top_p[d] = self.samp_top_p[p]
        self.samp_top_k[d] = self.samp_top_k[p]
        self.samp_presence[d] = self.samp_presence[p]
        self.samp_frequency[d] = self.samp_frequency[p]
        # (Penalty count rows are NOT moved: requests with penalties are
        # admitted direct-to-decode so their on-device counts build in
        # place; a penalty-free request's stale counts row is multiplied
        # by zero.)
        self.active[d] = True
        self.active[p] = False
        self.lengths[p] = 0
        self._slot_epoch[p] += 1
        self._d_dirty = True
        self._table_dirty = True
        del self._running[p]
        self._running[d] = req
        req.slot = d
        req.pool = POOL_DECODE
        self._admit_pool.free.append(p)
        self._disagg.note_handoff(len(pages))

    # -- stats ----------------------------------------------------------------
    def _resident_param_bytes(self) -> int:
        """HBM bytes one decode step streams for WEIGHTS: every resident
        leaf read once per step (scales included — they move over the bus
        too; int4 packs two elements per byte). Cached — the tree never
        changes after init."""
        b = getattr(self, "_param_bytes_cache", None)
        if b is None:
            b = 0
            for leaf in jax.tree.leaves(self.params):
                itemsize = (0.5 if leaf.dtype == jnp.int4
                            else leaf.dtype.itemsize)
                b = b + int(np.prod(leaf.shape) * itemsize)
            self._param_bytes_cache = b
        return b

    def _kv_bytes_per_step(self) -> int:
        """HBM bytes one decode step reads from the KV cache: the live
        (window-clamped) stale prefix of every active slot, K and V, at
        the cache's element width (int8-KV: 1 B + the per-token fp32
        scale amortized over head_dim). The bytes-touched half of the
        roofline model — achieved GB/s = (weights + this) / step time."""
        c = self.model_cfg
        live = self.lengths[self.active].astype(np.int64)
        if c.sliding_window:
            live = np.minimum(live, c.sliding_window)
        if self.kv_quant:
            elem = 1.0 + 4.0 / c.head_dim
        else:
            # np.dtype, not jnp: host metadata — stats() runs on the event
            # loop and must not even look like a device sync (graftlint v2
            # chases this call from the async stats handlers).
            elem = float(np.dtype(self.dtype).itemsize)
        return int(2 * c.n_layers * c.n_kv_heads * c.head_dim * elem
                   * int(live.sum()))

    def _build_ledger(self):
        """Static HBM accounting (ISSUE 8): what the engine INTENDS to
        hold in device memory — parameter bytes at their checkpoint
        dtypes, KV-pool bytes from page geometry × cache dtype (incl.
        int8-KV scale planes), penalty/table auxiliaries, and the spec
        history twin — reconciled at scrape time against the live
        buffers' metadata and, where the backend has an allocator
        (TPU), ``device.memory_stats()``. All byte totals are GLOBAL
        (logical array bytes across the mesh), matching what
        ``tracked_fn`` sums."""
        from ..obs.device import HbmLedger, device_memory_stats
        c = self.model_cfg
        if self.kv_quant:
            kv_elem, kv_scale = 1, 4        # int8 K/V + fp32/token scale
        else:
            kv_elem, kv_scale = int(np.dtype(self.dtype).itemsize), 0
        page = self.kv_page
        if self.paged:
            tokens = self.allocator.num_pages * page
            page_bytes = 2 * c.n_layers * c.n_kv_heads * page * (
                c.head_dim * kv_elem + kv_scale)
        else:
            tokens = self.B * self.S
            page_bytes = 0
        kv_pool = 2 * c.n_layers * c.n_kv_heads * tokens * (
            c.head_dim * kv_elem + kv_scale)
        aux = self.B * c.vocab_size * 4          # penalty counts [B, V]
        if self.paged:
            aux += int(self.allocator.table.size) * 4   # device page table
        spec = self.B * self.S * 4 if self.spec_k else 0  # device hist

        def tracked() -> int:
            # Live buffer bytes: array METADATA only — never a device
            # sync. Params + KV cache + the big auxiliaries; the tiny
            # per-slot mirrors fall inside the reconciliation band.
            total = 0
            for leaf in jax.tree.leaves((self.params, self.cache)):
                itemsize = (0.5 if leaf.dtype == jnp.int4
                            else leaf.dtype.itemsize)
                total += int(np.prod(leaf.shape) * itemsize)
            for extra in (self._d_counts, getattr(self, "_d_hist", None),
                          self._d_table if self.paged else None):
                if extra is not None:
                    total += int(np.prod(extra.shape)
                                 * extra.dtype.itemsize)
            return total

        try:
            pidx = jax.process_index()
            local = [d for d in self.mesh.devices.flat
                     if d.process_index == pidx] or None
        except Exception:
            # Best-effort device scoping: fall back to all local devices
            # inside device_memory_stats (the numbers stay correct for
            # single-engine processes, which is every deployment today).
            logger.debug("mesh-local device scoping failed", exc_info=True)
            local = None
        return HbmLedger(
            weights=self._resident_param_bytes(), kv_pool=kv_pool,
            aux=aux, spec=spec, page_bytes=page_bytes, tracked_fn=tracked,
            mem_fn=lambda: device_memory_stats(local))

    def kernel_table(self) -> list[dict[str, Any]]:
        """Per-kernel roofline rows (obs/device.py) joined with the
        flight ring's measured step walls — what ``GET /v1/api/roofline``
        serves. Decode/spec rows carry the engine's bytes-touched model
        (same formula as the aggregate ``hbm_bytes_per_step``, so the
        table reconciles with it by construction); prefill rows report
        the XLA static analysis only (prefill is FLOPs-bound)."""
        def bytes_for(kind: str) -> int | None:
            if kind in ("decode", "spec"):
                return (self._resident_param_bytes()
                        + self._kv_bytes_per_step())
            return None
        return self.kernels.table(
            bytes_per_step_fn=bytes_for, peak_gbps=self.cfg.hbm_peak_gbps,
            flight=(self.flight.snapshot() if self.flight is not None
                    else None))

    def stats(self) -> dict[str, Any]:
        out = {
            "running": len(self._running),
            "queued": self._queue.qsize() + (1 if self._head else 0),
            "free_slots": self._free_slot_count(),
            "batch_size": self.B,
            "max_seq_len": self.S,
            "kv_layout": self.cfg.kv_layout,
        }
        # Supervisor block (ISSUE 14): lifecycle state, restart budget,
        # heartbeat age, recent transitions — the incident story.
        out.update(self.supervisor.stats())
        if self._disagg is not None:
            out["pools"] = self._disagg.stats()
            out["disagg_handoffs"] = self._disagg.handoffs
            out["disagg_handoff_pages"] = self._disagg.handoff_pages
            out["disagg_clamps"] = self._disagg.clamps
            out["disagg_goodput_sheds"] = self._disagg.goodput_sheds
        # Precision config — operators correlating quality/throughput need
        # to see what the engine is actually running.
        if self.quant:
            out["quant"] = self.quant
        if self.kv_quant:
            out["kv_quant"] = self.kv_quant
        if self.paged:
            out["free_pages"] = self.allocator.free_pages
            out["total_pages"] = (self.allocator.num_pages
                                  - (self.allocator.pages_per_block
                                     if self.allocator.pages_per_block > 1
                                     else self.allocator.n_bands))
            out["page_size"] = self.allocator.page_size
            if self.kv_ppb > 1:
                out["pages_per_block"] = self.kv_ppb
            if self._prefix_cache is not None:
                # Radix prefix cache (ISSUE 6): hit/miss/cached-token
                # totals plus residency/pin gauges — the obs collector
                # bridges these onto the engine_prefix_* /metrics series,
                # and the bench's shared-prefix rung asserts skipped
                # prefill from them (not from wall clock).
                out.update(self._prefix_cache.stats())
        gauge = (self._ema_step_ms_stats
                 if self._ema_step_ms_stats is not None
                 else self._step_ms_estimate())
        if gauge is not None:
            out["decode_ms_per_step"] = round(gauge, 3)
            active_n = int(self.active.sum())
            if active_n:
                out["decode_tok_s"] = round(1000.0 * active_n / gauge, 1)
        # Roofline counters (ISSUE 2): bytes one decode step must stream
        # (weights + live KV) and the achieved bandwidth that implies at
        # the measured step time — the number the bench ladder and the
        # stats UI both read, so the 0.478→1.0 roofline trajectory is a
        # reading instead of a post-hoc reconstruction.
        hbm_bytes = self._resident_param_bytes() + self._kv_bytes_per_step()
        out["hbm_bytes_per_step"] = hbm_bytes
        if gauge:
            out["achieved_gbps"] = round(hbm_bytes / (gauge / 1e3) / 1e9, 1)
            if self.cfg.hbm_peak_gbps > 0:
                out["roofline_fraction"] = round(
                    out["achieved_gbps"] / self.cfg.hbm_peak_gbps, 3)
        # Scheduler-side TTFT counters: where bursts ran, how often the
        # prefill-aware clamp bit, and how long admissions waited.
        if self._last_burst_depth:
            out["burst_depth_last"] = self._last_burst_depth
        out["burst_busy_clamps"] = self._busy_clamps
        if self._queue_wait_n:
            out["queue_wait_ms_ema"] = round(self._queue_wait_ema_ms, 1)
            out["queue_wait_ms_max"] = round(self._queue_wait_max_ms, 1)
            out["queue_waits"] = self._queue_wait_n
        # Overload sheds (queue-full admissions the gateway 429'd).
        out["shed_total"] = self._shed_n
        # Burst-depth controller diagnostics (ttft_target_ms): fitted
        # per-step slope, per-burst fixed cost, and where bursts actually
        # ran — the fields that turn an on-chip TTFT/throughput anomaly
        # from a guess into a reading.
        if self.ttft_target_ms > 0:
            est = self._step_ms_estimate()
            if est is not None:
                out["burst_step_ms_fit"] = round(est, 3)
            c = self._fixed_cost_ms()
            if c is not None:
                out["burst_fixed_cost_ms"] = round(c, 1)
            if self._depth_hist:
                out["burst_depth_hist"] = dict(
                    sorted(self._depth_hist.items()))
            out["burst_walls_ms"] = {
                d: round(ms, 1)
                for d, ms in sorted(self._burst_walls.items())}
        if self.flight is not None:
            # Flight-recorder counters (ISSUE 7): ring position, loss
            # under load, and lifecycle balance — bridged onto /metrics
            # by the obs collector like the prefix/shed counters.
            out.update(self.flight.stats())
        # Device observability plane (ISSUE 8): the HBM ledger (static
        # intent, live buffer bytes, runtime allocator where available),
        # kernel-registry counters, watermark sheds, and the process-wide
        # XLA compile monitor (identical across engines in one process).
        out.update(self.ledger.snapshot(
            prefix_resident_pages=out.get("prefix_resident_pages", 0)))
        out.update(self.kernels.stats())
        out["watermark_sheds"] = self._watermark_sheds
        from ..obs.device import compile_monitor
        cm = compile_monitor().stats()
        out["xla_compile_total"] = cm["xla_compile_total"]
        out["xla_compile_seconds"] = cm["xla_compile_seconds"]
        if self.spec_k:
            out["spec_draft_len"] = self.spec_k
            # Speculative acceptance telemetry (ROADMAP item 3 stub):
            # drafted-vs-accepted token totals, bridged to the
            # gateway_engine_spec_* /metrics series. Counted explicitly
            # per drafting slot in _spec_walk — a suspended slot
            # (spec_acceptance_floor) proposes nothing, so steps*k would
            # overcount the denominator and understate the true rate.
            out["spec_proposed"] = self._spec_proposed_total
            out["spec_accepted"] = self._spec_accepted_total
            if self._spec_steps_done:
                out["spec_tokens_per_step"] = round(
                    self._spec_tokens_out / self._spec_steps_done, 2)
            if self.spec_floor > 0:
                # Per-slot adaptive drafting: the floor, which slots are
                # currently benched, and each measured slot's EMA-derived
                # acceptance ratio ((ema-1)/k — the quantity the floor
                # compares against). Bridged to the per-slot
                # gateway_engine_spec_slot_acceptance_ratio gauge and the
                # gateway_engine_spec_suspended_slots_total count.
                out["spec_acceptance_floor"] = self.spec_floor
                out["spec_suspended_slots"] = int(
                    self._spec_suspended.sum())
                ratios = {}
                for s in range(self.B):
                    ema = self._spec_ema[s]
                    if not np.isnan(ema):
                        ratios[s] = round(
                            (float(ema) - 1.0) / max(1, self.spec_k), 3)
                out["spec_slot_acceptance"] = ratios
            if self.spec_min_tps > 0 or self._spec_wall_gate_on:
                # Live view of the adaptive gate: mean measured acceptance
                # (active slots when serving, else the last measured
                # rates) and whether drafting currently pays. The wall
                # term reports even with the acceptance threshold
                # disabled (spec_min_tokens_per_step=0).
                act = self._spec_ema[self.active]
                basis = act if act.size else self._spec_ema
                known = basis[~np.isnan(basis)]
                accept_ok = True
                if known.size:
                    out["spec_ema_tokens_per_step"] = round(
                        float(known.mean()), 2)
                    if self.spec_min_tps > 0:
                        accept_ok = bool(
                            float(np.mean(np.where(np.isnan(basis),
                                                   self.spec_k + 1, basis)))
                            >= self.spec_min_tps)
                out["spec_gate_open"] = (accept_ok
                                         and not self._spec_wall_loses())
                if self._spec_ms_per_tok is not None:
                    out["spec_ms_per_token"] = round(
                        self._spec_ms_per_tok, 3)
        return out


def _pipelined_family_forward(mesh, n_stages: int, make_attention=None):
    """family-forward adapter running the GPipe schedule
    (parallel/pipeline.py) — same signature contract as llama.forward, so
    the engine's prefill/decode step bodies don't change. Microbatch count
    adapts to the call's batch: `n_stages` when divisible (the schedule's
    sweet spot), else 1 — the ONE copy of that policy for both the dense
    and the paged pipelines. ``make_attention`` + the ``table`` kwarg
    switch the schedule to paged mode (parallel/pipeline.py)."""
    from ..parallel.pipeline import pipelined_forward

    def fwd(params, c, tokens, lengths, cache, active=None,
            attention_fn=None, mlp_fn=None, table=None):
        B = tokens.shape[0]
        M = n_stages if B % n_stages == 0 else 1
        return pipelined_forward(params, c, tokens, lengths, cache, mesh,
                                 M, active=active,
                                 make_attention=make_attention, table=table)

    return fwd


def _spec_verify_attention_fn(base, window: int = 0):
    """Attention provider for the speculative verify forward: the engine's
    configured attention (``base``; None = family default), extended with
    ``.verify`` so the T=k+1 verify step runs deferred-insert block
    attention (llama.dense_verify_attention) instead of the chunk path's
    insert-then-attend. A separate provider — adding ``.verify`` to the
    shared one would silently reroute PREFILL chunks off the Pallas causal
    kernel too (llama.forward dispatches on the attribute for any T>1).
    ``window``: sliding-window bound for mistral-family engines — threads
    through the default base AND the verify twin."""
    if base is None:
        base = llama.windowed_dense_attention(window) if window \
            else llama.dense_cache_attention

    def attn(q, k_new, v_new, layer_k, layer_v, lengths, active=None):
        return base(q, k_new, v_new, layer_k, layer_v, lengths, active)
    attn.verify = partial(llama.dense_verify_attention, window=window) \
        if window else llama.dense_verify_attention
    attn.decode = getattr(base, "decode", llama.dense_decode_attention)
    attn.insert_all = getattr(base, "insert_all", llama.insert_kv_stacked)
    return attn


def _seq_paged_prefill_attention_fn(mesh, kind, base):
    """Whole-prompt prefill for the PAGED seq engine: same ring/ulysses
    collective attention as the dense twin below (prefill starts at
    position 0, so the chunk is the full visible context — no cache
    read), but writes land through the seq-paged provider's shard_map'd
    banded scatter (``base.insert``)."""
    from ..parallel.ring_attention import ring_attention
    from ..parallel.ulysses import ulysses_attention

    op = ring_attention if kind == "ring" else ulysses_attention

    def attention_fn(q, k_new, v_new, layer_k, layer_v, lengths, active=None):
        B, T, H, Dh = q.shape
        attn = op(q, k_new, v_new, mesh, axis="seq", causal=True)
        layer_k, layer_v = base.insert(layer_k, layer_v, k_new, v_new,
                                       lengths, active)
        return attn.reshape(B, T, H * Dh), layer_k, layer_v

    return attention_fn


def _seq_prefill_attention_fn(mesh, kind: str = "ring"):
    """Whole-prompt prefill attention for a seq-sharded engine: causal
    attention over the chunk itself (prefill always starts at position 0 in
    seq mode, so the chunk IS the full visible context — no prior cache to
    attend), plus the standard local KV insert into the S-sharded cache.
    ``kind`` picks the collective pattern: "ring" (n-1 ppermute hops, any
    head count) or "ulysses" (2 all-to-alls, needs heads % seq == 0)."""
    from ..parallel.ring_attention import ring_attention
    from ..parallel.ulysses import ulysses_attention

    op = ring_attention if kind == "ring" else ulysses_attention

    def attention_fn(q, k_new, v_new, layer_k, layer_v, lengths, active=None):
        B, T, H, Dh = q.shape
        attn = op(q, k_new, v_new, mesh, axis="seq", causal=True)
        layer_k, layer_v = llama.insert_kv(layer_k, layer_v, k_new, v_new,
                                           lengths, active)
        return attn.reshape(B, T, H * Dh), layer_k, layer_v

    return attention_fn


def _prefill_counts(counts, tokens, start_len, slots, last_idx):
    """Penalty-count maintenance for a prefill chunk group: reset each
    slot's row at prompt start (start_len == 0), add the chunk's REAL
    tokens (bucket pads masked via last_idx), and return (updated
    counts [B, V], the K updated rows [K, V] — the penalty source for
    this program's folded first-token sampling). Multihost-safe: every
    input is broadcast state, so follower counts stay bit-identical."""
    K, C = tokens.shape
    pos_ok = (jnp.arange(C)[None, :] <= last_idx[:, None]).astype(jnp.int32)
    rows = []
    for k in range(K):
        row = jax.lax.dynamic_slice_in_dim(counts, slots[k], 1, axis=0)[0]
        row = jnp.where(start_len[k] == 0, jnp.zeros_like(row), row)
        row = row.at[tokens[k]].add(pos_ok[k])
        counts = jax.lax.dynamic_update_slice_in_dim(
            counts, row[None], slots[k], axis=0)
        rows.append(row)
    return counts, jnp.stack(rows)


def _decode_programs(one_step, burst_lens: tuple[int, ...]):
    """Build the decode programs from one step body: the per-step program,
    and a fused lax.scan per distinct burst length in ``burst_lens`` — ONE
    dispatch + ONE host fetch per burst instead of per step; through a
    remote-device tunnel, dispatch latency is the decode bottleneck, not
    FLOPs. Two lengths are compiled in practice: the deep throughput burst
    and the shallow "busy" burst used while prefill work is interleaving
    (so busy-mode decode stays pipelined instead of dropping to
    synchronous single steps). `one_step(params, cache, counts, [table,]
    tokens, lengths, active, samp, key, greedy=) -> (next_tokens,
    new_lengths, counts, cache)`; the penalty-count state rides the
    scan carry beside the cache (donated like it).

    Returns ``{greedy: (step, {n: scan})}`` for greedy in (False, True);
    the scheduler picks per burst (jit compiles lazily, so an engine that
    only ever serves one mode compiles one set)."""
    lens = sorted({n for n in burst_lens if n > 1})

    def build(greedy: bool):
        step = partial(one_step, greedy=greedy)
        decode_step = partial(jax.jit, donate_argnums=(1, 2))(step)

        def make_scan(n_burst: int):
            @partial(jax.jit, donate_argnums=(1, 2))
            def decode_scan(params, cache, counts, *rest):
                *table, tokens, lengths, active, samp, key = rest

                def body(carry, _):
                    cache, counts, tokens, lengths, key = carry
                    key, sub = jax.random.split(key)
                    nt, nl, counts, cache = step(
                        params, cache, counts, *table, tokens,
                        lengths, active, samp, sub)
                    return (cache, counts, nt, nl, key), nt
                (cache, counts, tokens, lengths, key), toks = jax.lax.scan(
                    body, (cache, counts, tokens, lengths, key), None,
                    length=n_burst)
                return toks, tokens, lengths, counts, cache
            return decode_scan

        return decode_step, {n: make_scan(n) for n in lens}

    return {greedy: build(greedy) for greedy in (False, True)}


_dummy_key: jax.Array | None = None


def _DUMMY_KEY() -> jax.Array:
    """A fixed typed PRNG key for calls whose sampled output is ignored
    (multi-host followers, bench prefill) — cached so the input aval is
    identical across calls (no recompiles)."""
    global _dummy_key
    if _dummy_key is None:
        _dummy_key = jax.random.key(0)
    return _dummy_key


def _machine_fingerprint() -> str:
    """Backend + host-CPU-feature fingerprint scoping the default cache dir.

    XLA's persistent cache reloads AOT executables compiled on a DIFFERENT
    machine with only a stderr warning when the CPU feature sets mismatch —
    and the mismatched program can silently produce wrong tokens rather
    than SIGILL (observed in round-3 judging: a home-dir cache populated
    elsewhere failed one paged-engine test until wiped). Scoping the
    default path by this fingerprint makes a foreign cache invisible
    instead of trusted; entries for other machines coexist in sibling
    directories."""
    import hashlib
    import platform
    parts = [jax.__version__, jax.default_backend(), platform.machine()]
    try:
        with open("/proc/cpuinfo") as f:
            for line in f:
                # x86 "flags", arm64 "Features" — the AOT-relevant ISA set.
                if line.startswith(("flags", "Features")):
                    parts.append(line.strip())
                    break
    except OSError:
        parts.append(platform.processor())
    return hashlib.sha256("\n".join(parts).encode()).hexdigest()[:12]


def _default_cache_dir() -> str:
    import os
    return os.path.join(
        os.path.expanduser("~"), ".cache", "llmapigateway_tpu", "xla",
        _machine_fingerprint())


def _enable_compilation_cache(cfg_dir: str) -> None:
    """Persistent XLA compilation cache (VERDICT r2 item 7): a restarted
    gateway re-inits its engine in seconds instead of re-compiling for
    ~60 s (provider builds block on engine init — routing/router.py). The
    flag is process-global and idempotent; first engine wins.

    The default directory is namespaced by :func:`_machine_fingerprint`
    (VERDICT r3 item 4); an explicit ``compilation_cache_dir`` is used
    verbatim — the operator owns its hygiene."""
    if cfg_dir.strip().lower() == "off":
        return
    import os
    path = cfg_dir or _default_cache_dir()
    try:
        os.makedirs(path, exist_ok=True)
        if not jax.config.jax_compilation_cache_dir:
            jax.config.update("jax_compilation_cache_dir", path)
            jax.config.update(
                "jax_persistent_cache_min_compile_time_secs", 1.0)
    except Exception:                     # cache is an optimization only
        logger.warning("compilation cache unavailable", exc_info=True)


def _bucket(n: int, cap: int) -> int:
    """Next power of two ≥ n, capped (prefill compile buckets)."""
    b = 8
    while b < n:
        b *= 2
    return min(b, cap)


def _config_from_checkpoint(model_path: str) -> ModelConfig:
    """Derive ModelConfig from an HF checkpoint's config.json."""
    import json
    from pathlib import Path
    cfg = json.loads((Path(model_path) / "config.json").read_text())
    mtype = cfg.get("model_type", "llama")
    common = dict(
        rope_scaling=_parse_rope_scaling(cfg.get("rope_scaling")),
        vocab_size=cfg["vocab_size"],
        d_model=cfg["hidden_size"],
        n_layers=cfg["num_hidden_layers"],
        n_heads=cfg["num_attention_heads"],
        n_kv_heads=cfg.get("num_key_value_heads", cfg["num_attention_heads"]),
        d_ff=cfg["intermediate_size"],
        rope_theta=cfg.get("rope_theta", 10000.0),
        rms_eps=cfg.get("rms_norm_eps", 1e-5),
        max_seq_len=cfg.get("max_position_embeddings", 4096),
        tie_embeddings=cfg.get("tie_word_embeddings", False),
    )
    if mtype == "mixtral":
        return ModelConfig(family="mixtral",
                           n_experts=cfg.get("num_local_experts", 8),
                           experts_per_token=cfg.get("num_experts_per_tok", 2),
                           **common)
    if mtype == "mistral":
        # Mistral = llama block + sliding-window attention (null in
        # v0.2+ configs → full attention). Explicit head_dim: Nemo-style
        # checkpoints have head_dim * n_heads != hidden_size.
        return ModelConfig(family="llama",
                           sliding_window=cfg.get("sliding_window") or 0,
                           head_dim_override=cfg.get("head_dim", 0) or 0,
                           **common)
    if mtype == "qwen2":
        return ModelConfig(family="qwen2", attn_bias=True, **common)
    if mtype == "phi3":
        # Phi-3 = llama block with FUSED qkv/gate_up checkpoint tensors
        # (split by the loader — checkpoint.py _fused_bounds) + sliding
        # window (mini-4k: 2047). 128k "longrope" variants are refused
        # by _parse_rope_scaling — silently-wrong RoPE is worse.
        return ModelConfig(family="llama",
                           sliding_window=cfg.get("sliding_window") or 0,
                           **common)
    if mtype == "gemma":
        # Gemma always ties embeddings (HF omits the flag in some configs)
        # and carries an explicit head_dim (7B: 16 x 256 != hidden 3072).
        common["tie_embeddings"] = True
        return ModelConfig(family="gemma", act="gelu_tanh", rms_offset=1.0,
                           scale_embed=True,
                           head_dim_override=cfg.get("head_dim", 0),
                           **common)
    return ModelConfig(family="llama", **common)


def _parse_rope_scaling(block: dict | None):
    """HF config.json ``rope_scaling`` → RopeScaling. Unsupported types
    raise — loading a checkpoint with silently-wrong RoPE is worse than
    refusing it. The no-op "default" type and null are both accepted."""
    if not block:
        return None
    from ..models.config import RopeScaling
    rtype = block.get("rope_type", block.get("type", "llama3"))
    if rtype == "default":
        return None
    return RopeScaling(            # RopeScaling validates rtype
        rope_type=rtype,
        factor=float(block.get("factor", 8.0)),
        low_freq_factor=float(block.get("low_freq_factor", 1.0)),
        high_freq_factor=float(block.get("high_freq_factor", 4.0)),
        original_max_seq=int(block.get("original_max_position_embeddings",
                                       8192)))
