"""Tokenization for the local engine.

Two implementations behind one interface:

* :class:`HFTokenizer` — wraps a HuggingFace ``tokenizer.json`` (via the
  ``tokenizers`` library) with the checkpoint's chat template (jinja2, from
  ``tokenizer_config.json``).
* :class:`ByteTokenizer` — dependency-free byte-level fallback used by tests
  and random-init presets: ids 0..255 are raw bytes, specials above.

Detokenization for SSE streaming is **incremental and UTF-8-safe**: a token
may end mid-multibyte-character (and byte-level BPE merges routinely split
code points), so :class:`IncrementalDetokenizer` buffers undecodable tails
until the next token completes them — SURVEY.md §7 hard-part (5).
"""
from __future__ import annotations

import json
import logging
from pathlib import Path
from typing import Protocol, Sequence

logger = logging.getLogger(__name__)


class TokenizerLike(Protocol):
    bos_id: int | None
    eos_ids: set[int]
    vocab_size: int

    def encode(self, text: str) -> list[int]: ...
    def decode(self, ids: Sequence[int]) -> str: ...
    def decode_bytes(self, ids: Sequence[int]) -> bytes: ...
    def apply_chat_template(self, messages: list[dict], add_generation_prompt: bool = True) -> str: ...


DEFAULT_CHAT_TEMPLATE = (
    "{% for message in messages %}"
    "<|{{ message['role'] }}|>\n{{ message['content'] }}\n"
    "{% endfor %}"
    "{% if add_generation_prompt %}<|assistant|>\n{% endif %}"
)


class ByteTokenizer:
    """Byte-level tokenizer: id = byte value; specials from 256 up.
    Works with any vocab_size >= 256 + len(specials)."""

    BOS, EOS, PAD = 256, 257, 258

    def __init__(self, vocab_size: int = 512):
        if vocab_size < 260:
            raise ValueError("ByteTokenizer needs vocab_size >= 260")
        self.vocab_size = vocab_size
        self.bos_id = self.BOS
        self.eos_ids = {self.EOS}
        self.pad_id = self.PAD

    def encode(self, text: str) -> list[int]:
        return list(text.encode("utf-8"))

    def decode_bytes(self, ids: Sequence[int]) -> bytes:
        return bytes(i for i in ids if 0 <= i < 256)

    def decode(self, ids: Sequence[int]) -> str:
        return self.decode_bytes(ids).decode("utf-8", errors="replace")

    def apply_chat_template(self, messages: list[dict],
                            add_generation_prompt: bool = True) -> str:
        parts = [f"<|{m.get('role', 'user')}|>\n{_content_text(m)}\n"
                 for m in messages]
        if add_generation_prompt:
            parts.append("<|assistant|>\n")
        return "".join(parts)


class HFTokenizer:
    """HF tokenizer.json + chat template from tokenizer_config.json."""

    def __init__(self, model_dir: str | Path):
        from tokenizers import Tokenizer
        model_dir = Path(model_dir)
        self._tok = Tokenizer.from_file(str(model_dir / "tokenizer.json"))
        self.vocab_size = self._tok.get_vocab_size()

        cfg: dict = {}
        cfg_path = model_dir / "tokenizer_config.json"
        if cfg_path.exists():
            cfg = json.loads(cfg_path.read_text())
        self._chat_template = cfg.get("chat_template") or DEFAULT_CHAT_TEMPLATE

        def _tok_id(value) -> int | None:
            if value is None:
                return None
            if isinstance(value, dict):     # {"content": "<s>", ...}
                value = value.get("content")
            return self._tok.token_to_id(value) if value else None

        self.bos_id = _tok_id(cfg.get("bos_token"))
        self.eos_ids = set()
        eos = _tok_id(cfg.get("eos_token"))
        if eos is not None:
            self.eos_ids.add(eos)
        # Llama-3 chat ends turns with <|eot_id|>; Zephyr-style with <|im_end|>.
        for extra in ("<|eot_id|>", "<|im_end|>", "</s>", "<|end_of_text|>"):
            tid = self._tok.token_to_id(extra)
            if tid is not None:
                self.eos_ids.add(tid)

    def encode(self, text: str) -> list[int]:
        return self._tok.encode(text, add_special_tokens=False).ids

    def decode(self, ids: Sequence[int]) -> str:
        return self._tok.decode(list(ids), skip_special_tokens=True)

    def decode_bytes(self, ids: Sequence[int]) -> bytes:
        return self.decode(ids).encode("utf-8")

    def apply_chat_template(self, messages: list[dict],
                            add_generation_prompt: bool = True) -> str:
        import jinja2
        env = jinja2.Environment()
        env.globals["raise_exception"] = _jinja_raise
        tmpl = env.from_string(self._chat_template)
        msgs = [{"role": m.get("role", "user"), "content": _content_text(m)}
                for m in messages]
        return tmpl.render(messages=msgs,
                           add_generation_prompt=add_generation_prompt,
                           bos_token="", eos_token="")


def _jinja_raise(message):
    raise ValueError(message)


def _content_text(message: dict) -> str:
    """OpenAI message content may be a string or a list of typed parts."""
    content = message.get("content", "")
    if isinstance(content, str):
        return content
    if isinstance(content, list):
        return "".join(p.get("text", "") for p in content
                       if isinstance(p, dict) and p.get("type") == "text")
    return str(content)


class IncrementalDetokenizer:
    """Streaming token→text with UTF-8 boundary buffering, O(1) per token.

    Byte-level path: maintain a pending byte tail (≤3 bytes) and emit the
    longest valid UTF-8 prefix as bytes arrive.

    HF path: the sliding-window algorithm — keep ``prefix`` / ``read``
    offsets into the id list; each push decodes only ids[prefix:], emits the
    delta beyond the previously-read prefix once it no longer ends in a
    partial character, then advances the window. Cost per token is bounded
    by the window (a few ids), not the sequence length.
    """

    def __init__(self, tokenizer: TokenizerLike):
        self._tok = tokenizer
        self._byte_mode = isinstance(tokenizer, ByteTokenizer)
        if self._byte_mode:
            self._pending = bytearray()
        else:
            self._ids: list[int] = []
            self._prefix = 0       # window start
            self._read = 0         # ids already fully emitted

    # -- byte-level ----------------------------------------------------------
    def _push_bytes(self, token_id: int) -> str:
        if 0 <= token_id < 256:
            self._pending.append(token_id)
        raw = bytes(self._pending)
        # Longest valid UTF-8 prefix; a partial char is at most 3 bytes.
        for cut in range(len(raw), max(len(raw) - 4, -1), -1):
            try:
                text = raw[:cut].decode("utf-8")
            except UnicodeDecodeError:
                continue
            del self._pending[:cut]
            return text
        return ""

    # -- HF sliding window ---------------------------------------------------
    def _push_hf(self, token_id: int) -> str:
        self._ids.append(token_id)
        window = self._ids[self._prefix:]
        read_text = self._tok.decode(self._ids[self._prefix:self._read])
        full_text = self._tok.decode(window)
        if len(full_text) <= len(read_text) or full_text.endswith("�"):
            return ""          # partial char / merge pending — hold back
        delta = full_text[len(read_text):]
        self._prefix = self._read
        self._read = len(self._ids)
        return delta

    def push(self, token_id: int) -> str:
        if self._byte_mode:
            return self._push_bytes(token_id)
        return self._push_hf(token_id)

    def flush(self) -> str:
        if self._byte_mode:
            raw = bytes(self._pending)
            self._pending.clear()
            return raw.decode("utf-8", errors="replace") if raw else ""
        window = self._ids[self._prefix:]
        read_text = self._tok.decode(self._ids[self._prefix:self._read])
        full_text = self._tok.decode(window)
        self._prefix = self._read = len(self._ids)
        return full_text[len(read_text):]


def load_tokenizer(model_dir: str | Path | None,
                   vocab_size: int = 512) -> TokenizerLike:
    if model_dir:
        path = Path(model_dir)
        if (path / "tokenizer.json").exists():
            return HFTokenizer(path)
        logger.warning("no tokenizer.json under %s; using byte fallback", path)
    return ByteTokenizer(vocab_size=max(512, vocab_size if vocab_size >= 260 else 512))
