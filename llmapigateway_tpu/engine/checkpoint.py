"""HF safetensors checkpoints → stacked-layer JAX params, sharded on load.

The reference has no model checkpoints at all (SURVEY.md §5 "Checkpoint /
resume"); this implements the TPU-side story: stream tensors from
safetensors shards and place each directly into its GSPMD sharding layout
(per-device ``jax.device_put``), so a 70B model never materializes unsharded
on one host.

Supports the HF Llama/Mistral naming scheme (TinyLlama, Llama-2/3) and
Mixtral's MoE naming. Torch ``nn.Linear`` stores ``[out, in]``; JAX matmul
layout here is ``[in, out]`` — every projection is transposed on load.
"""
from __future__ import annotations

import json
import logging
import re
from pathlib import Path
from typing import Any, Callable

import jax
import jax.numpy as jnp
import numpy as np
from safetensors import safe_open

from ..models.config import ModelConfig

logger = logging.getLogger(__name__)


def _discover_shards(model_dir: Path) -> list[Path]:
    index = model_dir / "model.safetensors.index.json"
    if index.exists():
        data = json.loads(index.read_text())
        files = sorted(set(data["weight_map"].values()))
        return [model_dir / f for f in files]
    single = model_dir / "model.safetensors"
    if single.exists():
        return [single]
    shards = sorted(model_dir.glob("*.safetensors"))
    if not shards:
        raise FileNotFoundError(f"no safetensors files in {model_dir}")
    return shards


# HF tensor name → (our path, needs_transpose). {i} = layer, {e} = expert.
_LLAMA_MAP: list[tuple[re.Pattern, str, bool]] = [
    (re.compile(r"^model\.embed_tokens\.weight$"), "embed", False),
    (re.compile(r"^model\.norm\.weight$"), "final_norm", False),
    (re.compile(r"^lm_head\.weight$"), "lm_head", False),
    (re.compile(r"^model\.layers\.(\d+)\.input_layernorm\.weight$"),
     "layers.attn_norm.{i}", False),
    (re.compile(r"^model\.layers\.(\d+)\.self_attn\.q_proj\.weight$"),
     "layers.wq.{i}", True),
    (re.compile(r"^model\.layers\.(\d+)\.self_attn\.k_proj\.weight$"),
     "layers.wk.{i}", True),
    (re.compile(r"^model\.layers\.(\d+)\.self_attn\.v_proj\.weight$"),
     "layers.wv.{i}", True),
    (re.compile(r"^model\.layers\.(\d+)\.self_attn\.o_proj\.weight$"),
     "layers.wo.{i}", True),
    (re.compile(r"^model\.layers\.(\d+)\.post_attention_layernorm\.weight$"),
     "layers.mlp_norm.{i}", False),
    # Qwen2 QKV bias (1-D: no transpose)
    (re.compile(r"^model\.layers\.(\d+)\.self_attn\.q_proj\.bias$"),
     "layers.bq.{i}", False),
    (re.compile(r"^model\.layers\.(\d+)\.self_attn\.k_proj\.bias$"),
     "layers.bk.{i}", False),
    (re.compile(r"^model\.layers\.(\d+)\.self_attn\.v_proj\.bias$"),
     "layers.bv.{i}", False),
    (re.compile(r"^model\.layers\.(\d+)\.mlp\.gate_proj\.weight$"),
     "layers.wg.{i}", True),
    (re.compile(r"^model\.layers\.(\d+)\.mlp\.up_proj\.weight$"),
     "layers.wu.{i}", True),
    (re.compile(r"^model\.layers\.(\d+)\.mlp\.down_proj\.weight$"),
     "layers.wd.{i}", True),
    # Phi-3 family: HF ships the attention and MLP up-projections FUSED
    # (qkv_proj [(H+2KV)*Dh, D], gate_up_proj [2F, D]). Mapped to
    # placeholder keys; load_checkpoint splits them into the stacked
    # wq/wk/wv and wg/wu params (split happens at SOURCE precision and
    # BEFORE the preprocess hook, so int8-at-source quantization scales
    # are per-projection, identical to an unfused checkpoint's).
    (re.compile(r"^model\.layers\.(\d+)\.self_attn\.qkv_proj\.weight$"),
     "layers.__qkv__.{i}", False),
    (re.compile(r"^model\.layers\.(\d+)\.mlp\.gate_up_proj\.weight$"),
     "layers.__gu__.{i}", False),
    # Mixtral MoE
    (re.compile(r"^model\.layers\.(\d+)\.block_sparse_moe\.gate\.weight$"),
     "layers.router.{i}", True),
    (re.compile(r"^model\.layers\.(\d+)\.block_sparse_moe\.experts\.(\d+)\.w1\.weight$"),
     "layers.wg.{i}.{e}", True),
    (re.compile(r"^model\.layers\.(\d+)\.block_sparse_moe\.experts\.(\d+)\.w3\.weight$"),
     "layers.wu.{i}.{e}", True),
    (re.compile(r"^model\.layers\.(\d+)\.block_sparse_moe\.experts\.(\d+)\.w2\.weight$"),
     "layers.wd.{i}.{e}", True),
]


def _map_name(hf_name: str) -> tuple[str, int | None, int | None, bool] | None:
    """→ (bare param key, layer index, expert index, transpose) or None.
    The key is the leaf name inside the params tree ('wq', 'attn_norm', ...
    or 'embed'/'final_norm'/'lm_head' for layerless tensors)."""
    for pattern, target, transpose in _LLAMA_MAP:
        m = pattern.match(hf_name)
        if m:
            groups = m.groups()
            layer = int(groups[0]) if groups else None
            expert = int(groups[1]) if len(groups) > 1 else None
            key = target.split(".{i}")[0]
            if key.startswith("layers."):
                key = key[len("layers."):]
            return key, layer, expert, transpose
    return None


def _fused_bounds(key: str, c: ModelConfig) -> list[tuple[str, int, int]]:
    """Row ranges of each projection inside a Phi-3 fused tensor (HF
    orientation: rows are the output dim)."""
    if key == "__qkv__":
        qw = c.n_heads * c.head_dim
        kvw = c.n_kv_heads * c.head_dim
        return [("wq", 0, qw), ("wk", qw, qw + kvw),
                ("wv", qw + kvw, qw + 2 * kvw)]
    return [("wg", 0, c.d_ff), ("wu", c.d_ff, 2 * c.d_ff)]


def load_checkpoint(model_dir: str | Path, config: ModelConfig,
                    dtype: jnp.dtype = jnp.bfloat16,
                    put: Callable[[str, np.ndarray], jax.Array] | None = None,
                    preprocess: Callable[[str, np.ndarray],
                                         np.ndarray | dict] | None = None
                    ) -> dict[str, Any]:
    """Load an HF checkpoint into the stacked-layer params layout.

    ``put(param_path, np_array) -> jax.Array`` controls placement — the
    engine passes a sharded ``device_put``; default is plain host transfer.
    Stacking happens per-parameter: each layer's tensor is placed as soon as
    all layers for that name are read, bounding host memory.

    ``preprocess(param_path, tensor)`` runs on each tensor at the
    checkpoint's SOURCE precision, before the target-dtype cast and before
    layer stacking — the int8-quantization hook (quant levels computed from
    fp16/fp32 source values, not from a bf16-rounded copy, and the host
    stacks int8 instead of bf16). It may return a ``{"q": ..., "s": ...}``
    dict; each sub-leaf is then stacked and placed under ``path.key``.
    Default: cast to ``dtype``.
    """
    model_dir = Path(model_dir)
    shards = _discover_shards(model_dir)
    put = put or (lambda path, arr: jnp.asarray(arr))
    preprocess = preprocess or (
        lambda path, arr: arr.astype(_np_dtype(dtype)))

    # Pass 1: index — which shard holds each mapped tensor (metadata only).
    index: dict[str, tuple[Path, str, bool, int | None, int | None]] = {}
    grouped: dict[str, list[str]] = {}     # param key -> [hf names]
    for shard in shards:
        with safe_open(str(shard), framework="numpy") as f:
            for name in f.keys():
                mapped = _map_name(name)
                if mapped is None:
                    logger.debug("skipping unmapped tensor %s", name)
                    continue
                key, layer, expert, transpose = mapped
                index[name] = (shard, key, transpose, layer, expert)
                grouped.setdefault(key, []).append(name)

    # Pass 2: one parameter group at a time — read its tensors (layer by
    # layer), stack, place sharded, free. Host memory is bounded by the
    # largest single stacked parameter, not the whole checkpoint.
    open_shards: dict[Path, Any] = {}

    def read_raw(name: str) -> np.ndarray:
        """One tensor at source precision, HF orientation."""
        shard, _, _, _, _ = index[name]
        if shard not in open_shards:
            open_shards[shard] = safe_open(str(shard), framework="numpy")
        return np.asarray(open_shards[shard].get_tensor(name))

    def read(name: str, path: str) -> np.ndarray | dict:
        """One tensor at source precision → preprocessed (cast/quantized)."""
        arr = read_raw(name)
        if index[name][2]:
            arr = arr.T
        return preprocess(path, arr)

    def place(path: str, value: np.ndarray | dict):
        if isinstance(value, dict):
            return {k: put(f"{path}.{k}", v) for k, v in value.items()}
        return put(path, value)

    def stack(values: list) -> np.ndarray | dict:
        if isinstance(values[0], dict):
            return {k: np.stack([v[k] for v in values]) for k in values[0]}
        return np.stack(values)

    params: dict[str, Any] = {"layers": {}}
    try:
        for key, names in grouped.items():
            entries = [(index[n][3], index[n][4], n) for n in names]
            if key in ("__qkv__", "__gu__"):
                # Phi-3 fused tensors: split rows per projection at source
                # precision, then transpose/preprocess/stack each exactly
                # like an unfused checkpoint's tensors. Rows are read via
                # get_slice so each projection's range is read once (no
                # whole-tensor re-read per sub) — and the fused row count
                # is validated against the config-derived bounds: numpy
                # slice-clamping would otherwise turn a geometry mismatch
                # into silently wrong weights with config-derived shapes
                # that pass _validate_shapes.
                by_l = {l: n for l, _, n in entries}
                n_layers = max(by_l) + 1
                subs = _fused_bounds(key, config)
                expect_rows = subs[-1][2]

                def read_rows(name, lo, hi):
                    shard = index[name][0]
                    if shard not in open_shards:
                        open_shards[shard] = safe_open(str(shard),
                                                       framework="numpy")
                    sl = open_shards[shard].get_slice(name)
                    rows = sl.get_shape()[0]
                    if rows != expect_rows:
                        raise ValueError(
                            f"fused tensor {name} has {rows} rows; config "
                            f"implies {expect_rows} "
                            f"({[s[0] for s in subs]})")
                    return np.asarray(sl[lo:hi])

                for sub, lo, hi in subs:
                    path = f"layers.{sub}"
                    stacked = stack([
                        preprocess(path, read_rows(by_l[l], lo, hi).T)
                        for l in range(n_layers)])
                    params["layers"][sub] = place(path, stacked)
                    del stacked
                continue
            if entries[0][0] is None:                       # layerless tensor
                params[key] = place(key, read(names[0], key))
                continue
            path = f"layers.{key}"
            has_experts = any(e is not None for (_, e, _) in entries)
            by_pos = {(l, e): n for l, e, n in entries}
            n_layers = max(l for l, _, _ in entries) + 1
            if has_experts:
                n_experts = max(e for _, e, _ in entries) + 1
                stacked = stack([
                    stack([read(by_pos[(l, e)], path)
                           for e in range(n_experts)])
                    for l in range(n_layers)])
            else:
                stacked = stack([read(by_pos[(l, None)], path)
                                 for l in range(n_layers)])
            params["layers"][key] = place(path, stacked)
            del stacked
    finally:
        open_shards.clear()

    if "lm_head" not in params:
        if not config.tie_embeddings:
            logger.info("no lm_head in checkpoint; using tied embeddings")
        params["lm_head"] = params["embed"]
    _validate_shapes(params, config)
    return params


def _np_dtype(dtype: jnp.dtype):
    # numpy has no bfloat16; use ml_dtypes (bundled with jax).
    if dtype == jnp.bfloat16:
        import ml_dtypes
        return ml_dtypes.bfloat16
    return np.dtype(dtype)


def _shape(p: Any) -> tuple[int, ...]:
    """Leaf shape; an int8-quantized leaf is a {"q","s"} dict whose logical
    shape is the int8 tensor's (models/quant.py)."""
    return tuple(p["q"].shape) if isinstance(p, dict) else tuple(p.shape)


def _validate_shapes(params: dict[str, Any], config: ModelConfig) -> None:
    c = config
    checks = {
        "embed": (c.vocab_size, c.d_model),
        "final_norm": (c.d_model,),
    }
    for key, want in checks.items():
        got = _shape(params[key])
        if got != want:
            raise ValueError(f"checkpoint/config mismatch: {key} is {got}, "
                             f"config implies {want}")
    lk = params["layers"]
    required = {"attn_norm", "wq", "wk", "wv", "wo", "mlp_norm"}
    required |= {"router"} if c.is_moe else {"wg", "wu", "wd"}
    if c.attn_bias:
        # A qwen2-family checkpoint with missing/unmapped bias tensors must
        # refuse to load, not silently run bias-free.
        required |= {"bq", "bk", "bv"}
    missing = required - set(lk)
    if missing:
        raise ValueError(f"checkpoint is missing layer params {sorted(missing)}; "
                         f"loaded keys: {sorted(lk)}")
    want = (c.n_layers, c.d_model, c.n_heads * c.head_dim)
    if _shape(lk["wq"]) != want:
        raise ValueError(f"checkpoint/config mismatch: layers.wq is "
                         f"{_shape(lk['wq'])}, config implies {want}")
