"""TPU-native LLM gateway: OpenAI-compatible fault-tolerant gateway with an
in-process JAX/XLA/Pallas inference engine.

A from-scratch rebuild of the capability set of fabiojbg/LLMApiGateway
(see /root/repo/SURVEY.md), designed TPU-first: the gateway routes
``/v1/chat/completions`` either to remote OpenAI-compatible HTTP providers
(with fallback chains, retries, rotation, parameter injection) or to a local
GSPMD-sharded JAX inference engine (``local`` provider) running on TPU.
"""

__version__ = "0.1.0"
