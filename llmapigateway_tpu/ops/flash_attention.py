"""Flash attention over the serving KV cache, as Pallas TPU kernels.

Two kernels cover the two compiled serving programs (engine/engine.py):

* :func:`flash_decode_attention` — one query token per slot against the
  whole cache. Grid ``(B, KV, S/BS)``; each program block holds one slot's
  one KV head's key/value block in VMEM. GQA is handled *inside* the
  kernel (queries arrive grouped ``[B, KV, G, Dh]``), so cache reads are
  never expanded ``G×`` the way the jnp path's ``jnp.repeat`` does — at
  serving batch sizes decode attention is pure HBM bandwidth, making this
  the kernel that sets the tok/s ceiling. Sequence blocks past the slot's
  live length contribute nothing: their compute is skipped with ``pl.when``
  AND their HBM→VMEM copies are elided by clamping the K/V block index maps
  to the last live block (the pipeline skips the DMA when the next block
  index equals the current one), so slots early in their generation truly
  don't pay ``S_max`` bandwidth (ragged attention).
* :func:`flash_prefill_attention` — a prompt chunk of ``T`` queries against
  the cache prefix plus itself. Grid ``(B, H, T/TB, S/BS)`` with online
  softmax over the S blocks; causally-invisible key blocks are skipped
  entirely, and per-element causal masking handles the block diagonal.
  Nothing ``[T, S]``-shaped ever hits HBM (the jnp path materializes
  ``[B, H, T, S]`` scores).

Both kernels accumulate in fp32 scratch (``m``/``l``/``acc`` — the classic
online-softmax triple) and run in interpret mode off-TPU, so the same code
path is exercised by the CPU test suite (tests/test_ops_attention.py
compares against models/llama.py's reference jnp attention).

The :func:`make_cache_attention_fn` wrapper adapts these to the model's
``attention_fn`` contract (llama.py:132 ``dense_cache_attention``): cache
insertion stays in XLA (dynamic_update_slice lowers well), the kernels do
the bandwidth-heavy read.
"""
from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from ..utils.jax_compat import shard_map

NEG_INF = -1e30


def _cdiv(a: int, b: int) -> int:
    return (a + b - 1) // b


def _interpret_default() -> bool:
    return jax.default_backend() != "tpu"


# ---------------------------------------------------------------------------
# Decode kernel: q [B, KV, G, Dh] vs cache [B, KV, S, Dh], ragged by n_valid
# ---------------------------------------------------------------------------

def self_column_init(q_ref, kn_ref, vn_ref, m_ref, l_ref, acc_ref) -> None:
    """Initialize a decode kernel's online-softmax state from the SELF
    column (the new token attending itself): m = q·k_new, l = 1,
    acc = v_new. The cache is STALE — the current token's K/V never
    touched HBM; its contribution lives entirely in registers (the
    deferred-insert decode protocol, models/llama.py forward()). Shared by
    the dense and paged decode kernels."""
    q = q_ref[0, 0].astype(jnp.float32)            # [G, Dh]
    kn = kn_ref[0, 0].astype(jnp.float32)          # [1, Dh]
    vn = vn_ref[0, 0].astype(jnp.float32)          # [1, Dh]
    self_s = jax.lax.dot_general(
        q, kn, (((1,), (1,)), ((), ())),
        preferred_element_type=jnp.float32)        # [G, 1]
    self_s *= q.shape[-1] ** -0.5
    m_ref[:] = jnp.broadcast_to(self_s, m_ref.shape)
    l_ref[:] = jnp.ones_like(l_ref)
    acc_ref[:] = jnp.broadcast_to(vn, acc_ref.shape)


def attend_block(q_ref, k_ref, v_ref, m_ref, l_ref, acc_ref, mask,
                 ks_ref=None, vs_ref=None, sub: int = 0) -> None:
    """One online-softmax block update — THE shared compute of every flash
    kernel here and in ops/paged_attention.py (dense/paged × decode/prefill
    × bf16/int8-KV). ``mask(scores)`` applies the caller's visibility rule;
    ``ks_ref``/``vs_ref`` are the optional int8-KV per-token scale blocks
    ``[1, 1, 1, BS]`` (rank-4: the unit dim before the token axis keeps the
    block's trailing two dims ``(1, BS)`` legal under the TPU (8, 128)
    tiling rule — a ``(1, BS)`` block of a rank-3 ``[B, KV, S]`` array
    would put a block of 1 on the KV dim, which real Mosaic lowering
    rejects; interpret mode never catches this): the scale factors out of
    the Dh contraction, so scores
    multiply by ``ks`` after the QK dot and probs by ``vs`` before the PV
    dot (after ``l`` accumulates — the softmax denominator is unscaled),
    and no dequantized [BS, Dh] block is ever built.

    ``sub`` (static) selects the K/V/scale sub-block along the leading
    block dim: the multi-page paged kernels fetch ``pages_per_block``
    physical pages in ONE ``(ppb, 1, page, Dh)`` block and attend them
    per-page (ops/paged_attention.py), so each call here stays the exact
    per-page update — only the DMA granularity grows."""
    q = q_ref[0, 0]                                # [rows, Dh]
    k = k_ref[sub, 0]                              # [BS, Dh] (bf16 or int8)
    v = v_ref[sub, 0].astype(jnp.float32)
    if k.dtype == jnp.int8:
        # int8-KV QK dot (the worst_kernel() pick on the int8 ladder —
        # decode.d*.greedy sat at ~0.4 of the HBM roof): dequant is fused
        # into the dot as a cast to q's NATIVE dtype. Every int8 value is
        # exact in bf16 (8 mantissa bits ≥ the 7 magnitude bits of ±127),
        # so scores are bit-identical to the old `.astype(float32)` pair —
        # but the MXU now runs one native low-precision pass with fp32
        # accumulation instead of the multi-pass fp32×fp32 matmul the
        # explicit upcast forced.
        k = k.astype(q.dtype)
    else:
        q = q.astype(jnp.float32)
        k = k.astype(jnp.float32)
    scores = jax.lax.dot_general(
        q, k, (((1,), (1,)), ((), ())),
        preferred_element_type=jnp.float32)        # [rows, BS]
    scores *= q.shape[-1] ** -0.5
    if ks_ref is not None:
        scores = scores * ks_ref[sub, 0]
    scores = mask(scores)

    m_prev = m_ref[:, :1]
    m_new = jnp.maximum(m_prev, jnp.max(scores, axis=1, keepdims=True))
    alpha = jnp.exp(m_prev - m_new)
    e = jnp.exp(scores - m_new)                    # [rows, BS]
    l_ref[:, :1] = alpha * l_ref[:, :1] + jnp.sum(e, axis=1, keepdims=True)
    p = e if vs_ref is None else e * vs_ref[sub, 0]
    acc_ref[:] = acc_ref[:] * alpha + jax.lax.dot_general(
        p, v, (((1,), (0,)), ((), ())),
        preferred_element_type=jnp.float32)        # [rows, Dh]
    m_ref[:, :1] = m_new


def unpack_kv_refs(refs):
    """(k, ks, v, vs, o, m, l, acc) from a kernel's trailing refs. Without
    int8-KV the scale refs are absent (arity 6) and come back None — THE
    one copy of this arity contract, shared by all four flash kernels
    (dense/paged × decode/prefill)."""
    if len(refs) == 8:
        return refs
    k_ref, v_ref, o_ref, m_ref, l_ref, acc_ref = refs
    return k_ref, None, v_ref, None, o_ref, m_ref, l_ref, acc_ref


def _decode_kernel(nvalid_ref, q_ref, kn_ref, vn_ref, *refs, block_s: int,
                   window: int = 0):
    k_ref, ks_ref, v_ref, vs_ref, o_ref, m_ref, l_ref, acc_ref = \
        unpack_kv_refs(refs)
    b = pl.program_id(0)
    s = pl.program_id(2)
    n_sb = pl.num_programs(2)

    @pl.when(s == 0)
    def _init():
        self_column_init(q_ref, kn_ref, vn_ref, m_ref, l_ref, acc_ref)

    n_valid = nvalid_ref[b]
    # Sliding window: the query (at position n_valid) sees stale keys j
    # with n_valid - j < window, i.e. j >= w0. Blocks entirely below w0
    # skip compute (and their DMA is elided by the index-map clamp).
    w0 = jnp.maximum(n_valid - (window - 1), 0) if window else 0
    live = s * block_s < n_valid
    if window:
        live = live & ((s + 1) * block_s > w0)

    @pl.when(live)
    def _block():
        def mask(scores):
            s_global = s * block_s + jax.lax.broadcasted_iota(
                jnp.int32, scores.shape, 1)
            ok = s_global < n_valid
            if window:
                ok = ok & (s_global >= w0)
            return jnp.where(ok, scores, NEG_INF)
        attend_block(q_ref, k_ref, v_ref, m_ref, l_ref, acc_ref, mask,
                     ks_ref, vs_ref)

    @pl.when(s == n_sb - 1)
    def _out():
        l = l_ref[:, :1]                               # >= 1 (self column)
        o_ref[0, 0] = (acc_ref[:] / l).astype(o_ref.dtype)


def flash_decode_attention(q: jax.Array, k_new: jax.Array,
                           v_new: jax.Array, layer_k, layer_v,
                           n_stale: jax.Array,
                           *, block_s: int = 128,
                           window: int = 0,
                           interpret: bool | None = None) -> jax.Array:
    """Ragged single-token attention over a STALE cache plus the new token.

    q: [B, H, Dh] (RoPE applied); k_new/v_new: [B, KV, Dh] — the current
    token's key/value (NOT yet in the cache; folded in as the online
    softmax's initial state); layer_k/v: [B, KV, S, Dh] (head-major), or
    the int8 ``{"q","s"}`` dicts (models/llama.py kv_quant layout — the
    kernel gains per-token scale blocks, see :func:`attend_block`);
    n_stale: [B] int32 — visible stale prefix per slot (the query's
    position; 0 for a fresh slot). ``window``: sliding-window bound
    (mistral family; 0 = full) — out-of-window leading blocks skip both
    compute and DMA. Returns [B, H * Dh] in q.dtype.
    """
    B, H, Dh = q.shape
    quant = isinstance(layer_k, dict)
    kq = layer_k["q"] if quant else layer_k
    KV, S = kq.shape[1], kq.shape[2]
    G = H // KV
    block_s = min(block_s, S)
    if S % block_s:
        raise ValueError(f"cache extent {S} not a multiple of block {block_s}")
    qg = q.reshape(B, KV, G, Dh)
    grid = (B, KV, S // block_s)

    def _live_range(nv_b):
        """(first, last) live block for a slot — iterations outside re-
        reference a live block so the pipeline elides their DMA (pl.when
        already skips their compute). max() guards n_stale == 0 (fresh
        slot: all cache blocks dead, only the self column counts)."""
        last = jnp.maximum((nv_b + block_s - 1) // block_s - 1, 0)
        if window:
            first = jnp.maximum(nv_b - (window - 1), 0) // block_s
            first = jnp.minimum(first, last)
        else:
            first = 0
        return first, last

    def kv_index(b, h, s, nv):
        first, last = _live_range(nv[b])
        return b, h, jnp.clip(s, first, last), 0

    def scale_index(b, h, s, nv):
        first, last = _live_range(nv[b])
        return b, h, 0, jnp.clip(s, first, last)

    # Scales are STORED rank-4 [B, KV, 1, S] (models/llama.py KVCache) so
    # the block's trailing dims are (1, block_s) — legal under the TPU
    # (8, 128) tiling rule for any KV (a (1, block_s) block of a
    # [B, KV, S] layout would block the KV dim at 1, which real Mosaic
    # lowering rejects; see attend_block) — and no per-call relayout of
    # the scale tensor is needed.
    kv_spec = pl.BlockSpec((1, 1, block_s, Dh), kv_index)
    s_spec = pl.BlockSpec((1, 1, 1, block_s), scale_index)
    if quant:
        kv_operands = (layer_k["q"], layer_k["s"],
                       layer_v["q"], layer_v["s"])
        kv_specs = [kv_spec, s_spec, kv_spec, s_spec]
    else:
        kv_operands = (layer_k, layer_v)
        kv_specs = [kv_spec, kv_spec]

    out = pl.pallas_call(
        functools.partial(_decode_kernel, block_s=block_s, window=window),
        grid_spec=pltpu.PrefetchScalarGridSpec(
            num_scalar_prefetch=1,
            grid=grid,
            in_specs=[
                pl.BlockSpec((1, 1, G, Dh), lambda b, h, s, nv: (b, h, 0, 0)),
                pl.BlockSpec((1, 1, 1, Dh), lambda b, h, s, nv: (b, h, 0, 0)),
                pl.BlockSpec((1, 1, 1, Dh), lambda b, h, s, nv: (b, h, 0, 0)),
                *kv_specs,
            ],
            out_specs=pl.BlockSpec((1, 1, G, Dh),
                                   lambda b, h, s, nv: (b, h, 0, 0)),
            scratch_shapes=[
                pltpu.VMEM((G, 128), jnp.float32),      # m
                pltpu.VMEM((G, 128), jnp.float32),      # l
                pltpu.VMEM((G, Dh), jnp.float32),       # acc
            ],
        ),
        out_shape=jax.ShapeDtypeStruct((B, KV, G, Dh), q.dtype),
        interpret=_interpret_default() if interpret is None else interpret,
    )(n_stale.astype(jnp.int32), qg, k_new[:, :, None, :],
      v_new[:, :, None, :], *kv_operands)
    return out.reshape(B, H * Dh)


# ---------------------------------------------------------------------------
# Prefill kernel: q [B, T, H, Dh] vs cache [B, KV, S, Dh], causal from start
# ---------------------------------------------------------------------------

def _prefill_kernel(start_ref, q_ref, *refs, block_t: int, block_s: int,
                    window: int = 0):
    k_ref, ks_ref, v_ref, vs_ref, o_ref, m_ref, l_ref, acc_ref = \
        unpack_kv_refs(refs)
    b = pl.program_id(0)
    t = pl.program_id(2)
    s = pl.program_id(3)
    n_sb = pl.num_programs(3)

    @pl.when(s == 0)
    def _init():
        m_ref[:] = jnp.full_like(m_ref, NEG_INF)
        l_ref[:] = jnp.zeros_like(l_ref)
        acc_ref[:] = jnp.zeros_like(acc_ref)

    start = start_ref[b]
    # Query block t covers absolute positions [start + t*TB, start + t*TB +
    # TB); key block s is (partially) visible iff its first key position is
    # <= the block's last query position (and, with a sliding window, its
    # last key position within `window` of the block's FIRST query).
    first_q_pos = start + t * block_t
    last_q_pos = first_q_pos + (block_t - 1)
    live = s * block_s <= last_q_pos
    if window:
        live = live & ((s + 1) * block_s - 1 > first_q_pos - window)

    @pl.when(live)
    def _block():
        def mask(scores):
            q_pos = start + t * block_t + jax.lax.broadcasted_iota(
                jnp.int32, scores.shape, 0)
            s_pos = s * block_s + jax.lax.broadcasted_iota(
                jnp.int32, scores.shape, 1)
            ok = s_pos <= q_pos
            if window:
                ok = ok & (s_pos > q_pos - window)
            return jnp.where(ok, scores, NEG_INF)
        attend_block(q_ref, k_ref, v_ref, m_ref, l_ref, acc_ref, mask,
                     ks_ref, vs_ref)

    @pl.when(s == n_sb - 1)
    def _out():
        l = l_ref[:, :1]
        o_ref[0, 0] = (acc_ref[:] / jnp.where(l == 0.0, 1.0, l)
                       ).astype(o_ref.dtype)


def flash_prefill_attention(q: jax.Array, layer_k, layer_v,
                            start: jax.Array,
                            *, block_t: int = 128, block_s: int = 128,
                            window: int = 0,
                            interpret: bool | None = None) -> jax.Array:
    """Causal chunk attention over an (already updated) cache.

    q: [B, T, H, Dh] — the chunk's queries at absolute positions
    ``start + t``; layer_k/v: [B, KV, S, Dh] (head-major) with the chunk's
    keys already inserted at ``[start, start+T)``, or the int8 ``{"q","s"}``
    dicts (kv_quant layout); start: [B] int32. ``window``: sliding-window
    bound (0 = full causal) — out-of-window key blocks skip compute and
    their DMA is elided.
    Returns [B, T, H * Dh] in q.dtype.
    """
    B, T, H, Dh = q.shape
    quant = isinstance(layer_k, dict)
    kq = layer_k["q"] if quant else layer_k
    KV, S = kq.shape[1], kq.shape[2]
    G = H // KV
    block_t = min(block_t, T)
    block_s = min(block_s, S)
    if T % block_t or S % block_s:
        raise ValueError(f"T={T} / S={S} not multiples of blocks "
                         f"{block_t}/{block_s}")
    qh = q.transpose(0, 2, 1, 3)                 # [B, H, T, Dh]
    grid = (B, H, T // block_t, S // block_s)

    def _live_range(st_b, t):
        # Clamp to the causally-visible (and in-window) key-block range
        # for query block t — out-of-range iterations repeat a live block
        # index so their HBM→VMEM copy is elided (compute already skipped
        # by pl.when).
        last = (st_b + t * block_t + (block_t - 1)) // block_s
        if window:
            first_q = st_b + t * block_t
            first = jnp.maximum(first_q - (window - 1), 0) // block_s
            first = jnp.minimum(first, last)
        else:
            first = 0
        return first, last

    def kv_index(b, h, t, s, st):
        first, last = _live_range(st[b], t)
        return b, h // G, jnp.clip(s, first, last), 0

    def scale_index(b, h, t, s, st):
        first, last = _live_range(st[b], t)
        return b, h // G, 0, jnp.clip(s, first, last)

    # Stored rank-4 [B, KV, 1, S] scale layout — see flash_decode_attention.
    kv_spec = pl.BlockSpec((1, 1, block_s, Dh), kv_index)
    s_spec = pl.BlockSpec((1, 1, 1, block_s), scale_index)
    if quant:
        kv_operands = (layer_k["q"], layer_k["s"],
                       layer_v["q"], layer_v["s"])
        kv_specs = [kv_spec, s_spec, kv_spec, s_spec]
    else:
        kv_operands = (layer_k, layer_v)
        kv_specs = [kv_spec, kv_spec]

    out = pl.pallas_call(
        functools.partial(_prefill_kernel, block_t=block_t, block_s=block_s,
                          window=window),
        grid_spec=pltpu.PrefetchScalarGridSpec(
            num_scalar_prefetch=1,
            grid=grid,
            in_specs=[
                pl.BlockSpec((1, 1, block_t, Dh),
                             lambda b, h, t, s, st: (b, h, t, 0)),
                *kv_specs,
            ],
            out_specs=pl.BlockSpec((1, 1, block_t, Dh),
                                   lambda b, h, t, s, st: (b, h, t, 0)),
            scratch_shapes=[
                pltpu.VMEM((block_t, 128), jnp.float32),   # m
                pltpu.VMEM((block_t, 128), jnp.float32),   # l
                pltpu.VMEM((block_t, Dh), jnp.float32),    # acc
            ],
        ),
        out_shape=jax.ShapeDtypeStruct((B, H, T, Dh), q.dtype),
        interpret=_interpret_default() if interpret is None else interpret,
    )(start.astype(jnp.int32), qh, *kv_operands)
    return out.transpose(0, 2, 1, 3).reshape(B, T, H * Dh)


# ---------------------------------------------------------------------------
# attention_fn adapter (llama.forward contract)
# ---------------------------------------------------------------------------


def _auto_block(n: int, cap: int) -> int:
    """Largest power-of-two divisor of n, capped — shapes are static at
    trace time, so each distinct (T, S) picks its own legal blocking (the
    final prefill bucket can be a non-power-of-two after the cache-extent
    clamp in engine._prefill_one_chunk)."""
    b = n & (-n)
    return min(b, cap)


def make_cache_attention_fn(block_s: int | None = None,
                            block_t: int | None = None,
                            interpret: bool | None = None,
                            window: int = 0):
    """Build an ``attention_fn`` (llama.py forward contract) backed by the
    flash kernels. Prefill chunks (T>1): insert in XLA, attend with the
    causal kernel. Decode (T==1): the deferred protocol — ``.decode``
    attends the stale cache + self column in the ragged GQA kernel and
    ``.insert_all`` (models/llama.py insert_kv_stacked) writes every
    layer's token once per step, outside the layer scan.
    ``block_s``/``block_t`` default to auto (largest pow2 divisor ≤128)."""
    def attention_fn(q, k_new, v_new, layer_k, layer_v, lengths, active=None):
        B, T, H, Dh = q.shape
        quant = isinstance(layer_k, dict)
        S = (layer_k["q"] if quant else layer_k).shape[2]
        from ..models.llama import insert_kv
        bs = block_s if block_s is not None else _auto_block(S, 128)
        layer_k, layer_v = insert_kv(layer_k, layer_v, k_new, v_new,
                                     lengths, active)
        bt = block_t if block_t is not None else _auto_block(T, 128)
        out = flash_prefill_attention(
            q, layer_k, layer_v, lengths,
            block_t=bt, block_s=bs, window=window, interpret=interpret)
        return out, layer_k, layer_v

    def decode(q, k_new, v_new, layer_k, layer_v, lengths, active=None):
        quant = isinstance(layer_k, dict)
        S = (layer_k["q"] if quant else layer_k).shape[2]
        # Decode blocks default wider than prefill (256 vs 128): the grid
        # is (B, KV, S/bs) programs whose per-program work is one small
        # matmul — at bs=128 the launch/DMA overhead of 256 tiny programs
        # dominates; bs=256 measured fastest on v5e (tools/profile_decode
        # sweep: 3.0 ms/step vs 3.3 at 128, 4.1 at 512 for TinyLlama).
        bs = block_s if block_s is not None else _auto_block(S, 256)
        n_stale = lengths if active is None else jnp.where(active, lengths, 0)
        out = flash_decode_attention(
            q[:, 0], k_new[:, 0], v_new[:, 0], layer_k, layer_v,
            n_stale, block_s=bs, window=window, interpret=interpret)
        return out[:, None, :]

    from ..models.llama import insert_kv_stacked
    attention_fn.decode = decode
    attention_fn.insert_all = insert_kv_stacked
    return attention_fn


def make_sharded_cache_attention_fn(mesh, block_s: int | None = None,
                                    block_t: int | None = None,
                                    interpret: bool | None = None,
                                    window: int = 0):
    """Mesh-aware ``attention_fn``: the flash kernels under ``shard_map``.

    ``pallas_call`` has no GSPMD partitioning rule, so invoking the kernels
    inside ``jit`` on mesh-sharded arrays would force XLA to gather the full
    KV cache onto every chip. Attention is embarrassingly parallel over
    batch (``data`` axis) and KV heads (``model`` axis — cache_sharding's
    layout), so we go manual over exactly the axes the shapes allow:
    ``model`` when heads divide, ``data`` when the batch divides (prefill
    runs a single slot's [1, ...] row, so batch stays automatic there).
    Falls back to the unsharded fn when nothing divides (e.g. 1-chip mesh).
    """
    from jax.sharding import PartitionSpec as P

    # The window bound threads straight through: positions are absolute
    # per slot, untouched by batch (data) or head (model) sharding.
    base = make_cache_attention_fn(block_s, block_t, interpret,
                                   window=window)

    def _axes(q, layer_k):
        B, _, H, _ = q.shape
        KV = (layer_k["q"] if isinstance(layer_k, dict) else layer_k).shape[1]
        msize = mesh.shape.get("model", 1)
        dsize = mesh.shape.get("data", 1)
        model = "model" if (msize > 1 and KV % msize == 0 and H % msize == 0) \
            else None
        data = "data" if (dsize > 1 and B % dsize == 0) else None
        return model, data, {ax for ax in (model, data) if ax}

    def _cache_spec(side, data, model):
        """Per-leaf spec: an int8 {"q","s"} cache leaf carries a 4-D
        [B, KV, S, Dh] value + 4-D [B, KV, 1, S] scale plane (batch and
        head dims shard identically; the scale's trailing (1, S) dims
        stay whole)."""
        val = P(data, model, None, None)
        if isinstance(side, dict):
            return {"q": val, "s": P(data, model, None, None)}
        return val

    def attention_fn(q, k_new, v_new, layer_k, layer_v, lengths, active=None):
        model, data, manual = _axes(q, layer_k)
        if not manual:
            return base(q, k_new, v_new, layer_k, layer_v, lengths, active)

        head = P(data, None, model, None)       # q / k_new / v_new
        cache = _cache_spec(layer_k, data, model)
        slot = P(data)                          # lengths / active
        # `active=None` means "all slots live" — materialize it so the
        # shard_map signature is static.
        act = active if active is not None \
            else jnp.ones((q.shape[0],), bool)
        f = shard_map(
            lambda q_, kn, vn, lk, lv, ln, ac:
                base(q_, kn, vn, lk, lv, ln, ac),
            mesh=mesh,
            in_specs=(head, head, head, cache, cache, slot, slot),
            out_specs=(P(data, None, model), cache, cache),
            axis_names=manual, check_vma=False)
        return f(q, k_new, v_new, layer_k, layer_v, lengths, act)

    def decode(q, k_new, v_new, layer_k, layer_v, lengths, active=None):
        model, data, manual = _axes(q, layer_k)
        if not manual:
            return base.decode(q, k_new, v_new, layer_k, layer_v, lengths,
                               active)
        head = P(data, None, model, None)
        cache = _cache_spec(layer_k, data, model)
        slot = P(data)
        act = active if active is not None \
            else jnp.ones((q.shape[0],), bool)
        f = shard_map(
            lambda q_, kn, vn, lk, lv, ln, ac:
                base.decode(q_, kn, vn, lk, lv, ln, ac),
            mesh=mesh,
            in_specs=(head, head, head, cache, cache, slot, slot),
            out_specs=P(data, None, model),
            axis_names=manual, check_vma=False)
        return f(q, k_new, v_new, layer_k, layer_v, lengths, act)

    from ..models.llama import insert_kv_stacked
    attention_fn.decode = decode
    # The stacked insert stays in GSPMD land: dynamic_update_slice with
    # replicated offsets partitions cleanly over the cache's data/model
    # sharded dims, and it runs ONCE per step outside the layer scan.
    attention_fn.insert_all = insert_kv_stacked
    return attention_fn
