"""Paged KV cache attention as Pallas TPU kernels (ragged paged attention).

The dense per-slot cache (models/llama.py ``KVCache``) reserves ``S_max``
tokens of HBM for every slot; the paged layout allocates fixed-size pages
from a global pool only as sequences grow, so HBM holds the *actual* token
count and the same memory serves more concurrent slots (cf. PAPERS.md
"Ragged Paged Attention" — re-derived here, not copied). No reference
counterpart: the reference proxies HTTP and has no KV cache at all
(SURVEY.md §2b "Serving scheduler" row).

Layout:
* ``k_pages``/``v_pages``: ``[P, KV, page, Dh]`` — global page pool,
  head-major within a page. **Physical page 0 is the trash page**: scatter
  targets for inactive slots and out-of-range positions are redirected
  there, so masked writes need no branching. The allocator
  (engine/paged.py) never hands page 0 out.
* ``page_table``: ``[B, NP]`` int32 — slot's logical page j → physical
  page. Unallocated entries are 0 (trash) and are never read: reads are
  bounded by ``n_valid``.

Kernel structure mirrors ops/flash_attention.py (online-softmax fp32
scratch, ``pl.when`` compute skip) with one addition: the K/V BlockSpec
index maps translate logical → physical through the scalar-prefetched page
table, *and* clamp to the last live logical page so dead iterations repeat
a block index and their HBM→VMEM DMA is elided. That makes decode cost
proportional to live tokens, not ``S_max`` — the ragged property.

The adapter :func:`make_paged_attention_fn` is built INSIDE the engine's
jitted step (closing over the traced page table), so ``llama.forward``
needs no signature change: a ``PagedKVCache`` pytree scans over layers
exactly like the dense cache.
"""
from __future__ import annotations

import functools
from typing import Any, NamedTuple

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from ..models.config import ModelConfig
from .flash_attention import (attend_block, self_column_init, shard_map,
                              unpack_kv_refs)

NEG_INF = -1e30


def _interpret_default() -> bool:
    return jax.default_backend() != "tpu"


class PagedKVCache(NamedTuple):
    """k, v: [L, P, KV, page, Dh] — global page pool per layer. Scans over
    the leading layer dim in llama.forward exactly like the dense KVCache.
    With ``kv_quant="int8"`` each of k/v is the ``{"q": int8, "s": f32
    [L, P, KV, 1, page]}`` dict (per-token-per-head scales; the unit dim
    before the token axis is the Mosaic-legal, relayout-free rank the
    kernels consume — models/llama.py KVCache convention)."""
    k: Any
    v: Any

    @classmethod
    def create(cls, config: ModelConfig, num_pages: int, page_size: int,
               dtype=jnp.bfloat16, kv_quant: str = "") -> "PagedKVCache":
        shape = (config.n_layers, num_pages, config.n_kv_heads, page_size,
                 config.head_dim)
        if kv_quant == "int8":
            def qz():
                return {"q": jnp.zeros(shape, jnp.int8),
                        "s": jnp.zeros(shape[:-2] + (1, shape[-2]),
                                       jnp.float32)}
            return cls(k=qz(), v=qz())
        return cls(k=jnp.zeros(shape, dtype=dtype),
                   v=jnp.zeros(shape, dtype=dtype))

    @property
    def page_size(self) -> int:
        k = self.k["q"] if isinstance(self.k, dict) else self.k
        return k.shape[3]


def paged_insert_kv(layer_k, layer_v,
                    k_new: jax.Array, v_new: jax.Array,
                    page_table: jax.Array, lengths: jax.Array,
                    active: jax.Array | None):
    """Scatter new tokens into the page pool at logical positions
    ``[lengths, lengths+T)`` per slot.

    layer_k/v: [P, KV, page, Dh] (or the int8 ``{"q","s"}`` dict — new
    tokens quantize at write time); k_new/v_new: [B, T, KV, Dh];
    page_table: [B, NP]; lengths: [B]. Inactive slots and positions past
    the table's reach land on trash page 0 (one scatter, no branches).
    """
    quant = isinstance(layer_k, dict)
    P, KV, page, Dh = (layer_k["q"] if quant else layer_k).shape
    B, T = k_new.shape[:2]
    NP = page_table.shape[1]

    pos = lengths[:, None] + jnp.arange(T, dtype=jnp.int32)[None, :]  # [B,T]
    logical = jnp.clip(pos // page, 0, NP - 1)
    phys = jnp.take_along_axis(page_table, logical, axis=1)           # [B,T]
    ok = (pos // page) < NP
    if active is not None:
        ok = ok & active[:, None]
    phys = jnp.where(ok, phys, 0)            # trash page for masked writes
    off = pos % page

    flat_page = phys.reshape(-1)                                      # [B*T]
    flat_off = off.reshape(-1)

    # [P, KV, page(, Dh)] scattered at (page, :, offset(, :)) per token.
    # In-bounds by construction (phys from the table or trash page 0;
    # off = pos % page) — the mode hint drops XLA's per-element clamping.
    def scatter(pool, new):
        return pool.at[flat_page, :, flat_off].set(
            new.astype(pool.dtype), mode="promise_in_bounds")

    def scatter_s(pool, new):
        # Scale pool [P, KV, 1, page]: same token positions, through the
        # unit dim.
        return pool.at[flat_page, :, 0, flat_off].set(
            new.astype(pool.dtype), mode="promise_in_bounds")

    if quant:
        from ..models.llama import quantize_kv
        kq, ks = quantize_kv(k_new)                  # [B,T,KV,Dh], [B,T,KV]
        vq, vs = quantize_kv(v_new)
        return (
            {"q": scatter(layer_k["q"], kq.reshape(B * T, KV, Dh)),
             "s": scatter_s(layer_k["s"], ks.reshape(B * T, KV))},
            {"q": scatter(layer_v["q"], vq.reshape(B * T, KV, Dh)),
             "s": scatter_s(layer_v["s"], vs.reshape(B * T, KV))},
        )
    layer_k = scatter(layer_k, k_new.reshape(B * T, KV, Dh))
    layer_v = scatter(layer_v, v_new.reshape(B * T, KV, Dh))
    return layer_k, layer_v


def paged_insert_all(pool_k, pool_v,
                     k_news: jax.Array, v_news: jax.Array,
                     page_table: jax.Array, lengths: jax.Array,
                     active: jax.Array | None):
    """Insert every layer's new tokens into the page pool with a single
    scatter (the paged half of the deferred-insert protocol —
    models/llama.py ``insert_kv_stacked`` is the dense twin).

    pool_k/v: [L, P, KV, page, Dh] (or the int8 ``{"q","s"}`` dict);
    k_news/v_news: [L, B, T, KV, Dh] (the layer scan's stacked ys, always
    bf16/fp32 — quantization happens here at write time); lengths: [B] —
    the first token's logical position (token t lands at lengths + t:
    T = 1 is the decode step, T = k+1 the speculative verify, whose
    rejected tail lands in the undefined zone past the advanced lengths
    exactly like the dense twin). Masked/overflow writes land on trash
    page 0 as usual.
    """
    quant = isinstance(pool_k, dict)
    page = (pool_k["q"] if quant else pool_k).shape[3]
    NP = page_table.shape[1]
    L, B, T = k_news.shape[:3]

    pos = lengths[:, None] + jnp.arange(T, dtype=jnp.int32)[None, :]  # [B,T]
    logical = jnp.clip(pos // page, 0, NP - 1)
    phys = jnp.take_along_axis(page_table, logical, axis=1)           # [B,T]
    ok = (pos // page) < NP
    if active is not None:
        ok = ok & active[:, None]
    phys = jnp.where(ok, phys, 0).reshape(-1)                         # [B*T]
    off = (pos % page).reshape(-1)

    # Advanced indices (phys, off) are separated by slices, so the indexed
    # result is [B*T, L, KV(, Dh)] — the [L, B, T, ...] new tokens
    # transpose to match. In-bounds by construction (see paged_insert_kv).
    def scatter(pool, news):
        new = news.transpose(1, 2, 0, 3, 4).reshape(
            B * T, L, *news.shape[3:]).astype(pool.dtype)
        return pool.at[:, phys, :, off].set(new, mode="promise_in_bounds")

    def scatter_s(pool, news):
        # Scale pool [L, P, KV, 1, page]: through the unit dim.
        new = news.transpose(1, 2, 0, 3).reshape(
            B * T, L, news.shape[3]).astype(pool.dtype)
        return pool.at[:, phys, :, 0, off].set(new,
                                               mode="promise_in_bounds")

    if quant:
        from ..models.llama import quantize_kv
        kq, ks = quantize_kv(k_news)      # [L,B,T,KV,Dh], [L,B,T,KV]
        vq, vs = quantize_kv(v_news)
        return (
            {"q": scatter(pool_k["q"], kq),
             "s": scatter_s(pool_k["s"], ks)},
            {"q": scatter(pool_v["q"], vq),
             "s": scatter_s(pool_v["s"], vs)},
        )
    return (scatter(pool_k, k_news), scatter(pool_v, v_news))


# ---------------------------------------------------------------------------
# Decode kernel: q [B, KV, G, Dh] vs pages [P, KV, page, Dh]
# ---------------------------------------------------------------------------

def _paged_decode_kernel(pt_ref, nvalid_ref, q_ref, kn_ref, vn_ref,
                         *refs, page: int, window: int = 0,
                         pages_per_block: int = 1):
    k_ref, ks_ref, v_ref, vs_ref, o_ref, m_ref, l_ref, acc_ref = \
        unpack_kv_refs(refs)
    b = pl.program_id(0)
    j = pl.program_id(2)        # run of `pages_per_block` logical pages
    n_pb = pl.num_programs(2)

    @pl.when(j == 0)
    def _init():
        self_column_init(q_ref, kn_ref, vn_ref, m_ref, l_ref, acc_ref)

    n_valid = nvalid_ref[b]
    # Sliding window (ops/flash_attention.py _decode_kernel is the dense
    # twin): the query at position n_valid sees stale keys p with
    # n_valid - p < window, i.e. p >= w0. Pages wholly below w0 skip
    # compute here AND their HBM→VMEM DMA (the index-map clamp makes them
    # repeat an in-window physical page) — a windowed paged decode reads
    # O(window) pages, not O(context): SWA's whole point, compounded.
    w0 = jnp.maximum(n_valid - (window - 1), 0) if window else 0
    # Per-page attends over the block's sub-pages, unrolled
    # (pages_per_block is compile-time): the SAME online-softmax updates
    # in the SAME order as the per-page kernel, so any pages_per_block is
    # bit-for-bit with 1 — only the HBM→VMEM DMA granularity changes
    # (one (ppb·page, Dh) copy instead of ppb (page, Dh) copies).
    for i in range(pages_per_block):
        lp = j * pages_per_block + i                   # logical page
        live = lp * page < n_valid
        if window:
            live = live & ((lp + 1) * page > w0)

        @pl.when(live)
        def _block(i=i, lp=lp):
            def mask(scores):
                pos = lp * page + jax.lax.broadcasted_iota(
                    jnp.int32, scores.shape, 1)
                ok = pos < n_valid
                if window:
                    ok = ok & (pos >= w0)
                return jnp.where(ok, scores, NEG_INF)
            attend_block(q_ref, k_ref, v_ref, m_ref, l_ref, acc_ref, mask,
                         ks_ref, vs_ref, sub=i)

    @pl.when(j == n_pb - 1)
    def _out():
        l = l_ref[:, :1]                               # >= 1 (self column)
        o_ref[0, 0] = (acc_ref[:] / l).astype(o_ref.dtype)


def _check_pages_per_block(ppb: int, NP: int, P: int) -> None:
    """Static geometry gate for the multi-page kernels: the table width and
    the pool's page count must both split into whole runs. The SEMANTIC
    requirement — every aligned group of ``ppb`` logical pages maps to an
    aligned contiguous run of physical pages (``pt[b, g·ppb+i] ==
    pt[b, g·ppb] + i`` with ``pt[b, g·ppb] % ppb == 0``) — is the
    caller's promise; the engine's superpage-packing allocator
    (engine/paged.py ``pages_per_block``) is the one producer that
    guarantees it, and the engine falls back to per-page blocks whenever
    it can't (SWA ring, seq banding, non-divisible geometry)."""
    if ppb < 1:
        raise ValueError(f"pages_per_block must be >= 1, got {ppb}")
    if ppb > 1 and (NP % ppb or P % ppb):
        raise ValueError(
            f"pages_per_block={ppb} needs the page-table width ({NP}) and "
            f"the pool's page count ({P}) divisible by it")


def paged_decode_attention(q: jax.Array, k_new: jax.Array,
                           v_new: jax.Array, k_pages, v_pages,
                           page_table: jax.Array,
                           n_stale: jax.Array, *,
                           window: int = 0,
                           pages_per_block: int = 1,
                           interpret: bool | None = None) -> jax.Array:
    """Ragged single-token attention over the STALE page pool plus the new
    token (self column folded into the online-softmax init).

    q: [B, H, Dh] (RoPE applied); k_new/v_new: [B, KV, Dh];
    k_pages/v_pages: [P, KV, page, Dh] or the int8 ``{"q","s"}`` dicts;
    page_table: [B, NP]; n_stale: [B] int32 (the query's position; 0 for a
    fresh slot). ``window``: sliding-window bound (mistral family; 0 =
    full) — pages wholly out of window skip compute and DMA, so a
    windowed decode reads O(window) pages. ``pages_per_block``: fetch a
    compile-time run of contiguous logical pages per grid step — the
    K/V block grows to ``(ppb, 1, page, Dh)`` (one pages_per_block×
    larger HBM→VMEM DMA) and the grid's page dim shrinks by the same
    factor; requires a PACKED table (see :func:`_check_pages_per_block`).
    Numerics are bit-for-bit identical across pages_per_block values
    (per-page attends, unrolled in order). Returns [B, H*Dh].
    """
    B, H, Dh = q.shape
    quant = isinstance(k_pages, dict)
    kq = k_pages["q"] if quant else k_pages
    KV, page = kq.shape[1], kq.shape[2]
    NP = page_table.shape[1]
    ppb = pages_per_block
    _check_pages_per_block(ppb, NP, kq.shape[0])
    bs = ppb * page                      # tokens per grid step
    G = H // KV
    qg = q.reshape(B, KV, G, Dh)
    grid = (B, KV, NP // ppb)

    def _live_range(nv_b):
        """(first, last) live BLOCK (run of ppb logical pages) —
        out-of-range iterations re-reference a live block so their DMA is
        elided (pl.when skips their compute); flash_attention._live_range
        is the dense twin."""
        last = jnp.maximum((nv_b + bs - 1) // bs - 1, 0)
        if window:
            first = jnp.minimum(
                jnp.maximum(nv_b - (window - 1), 0) // bs, last)
        else:
            first = 0
        return first, last

    def _phys_block(pt, b, g):
        # Gather-free: ONE table lookup per grid step. The packed-table
        # promise makes the run's first physical page ppb-aligned, so its
        # superpage id IS the block index along the pool's page dim
        # (block size ppb ⇒ element offset sp·ppb).
        p0 = pt[b, g * ppb]
        return p0 // ppb if ppb > 1 else p0

    def kv_index(b, h, j, pt, nv):
        first, last = _live_range(nv[b])
        return _phys_block(pt, b, jnp.clip(j, first, last)), h, 0, 0

    def scale_index(b, h, j, pt, nv):
        first, last = _live_range(nv[b])
        return _phys_block(pt, b, jnp.clip(j, first, last)), h, 0, 0

    # Scales are STORED rank-4 [P, KV, 1, page] so the block's trailing
    # dims are (1, page) — legal under the TPU (8, 128) tiling rule for
    # any KV (see flash_attention.attend_block) — with no per-call
    # relayout of the pool-sized scale tensor.
    kv_spec = pl.BlockSpec((ppb, 1, page, Dh), kv_index)
    s_spec = pl.BlockSpec((ppb, 1, 1, page), scale_index)
    if quant:
        kv_operands = (k_pages["q"], k_pages["s"],
                       v_pages["q"], v_pages["s"])
        kv_specs = [kv_spec, s_spec, kv_spec, s_spec]
    else:
        kv_operands = (k_pages, v_pages)
        kv_specs = [kv_spec, kv_spec]

    out = pl.pallas_call(
        functools.partial(_paged_decode_kernel, page=page, window=window,
                          pages_per_block=ppb),
        grid_spec=pltpu.PrefetchScalarGridSpec(
            num_scalar_prefetch=2,
            grid=grid,
            in_specs=[
                pl.BlockSpec((1, 1, G, Dh),
                             lambda b, h, j, pt, nv: (b, h, 0, 0)),
                pl.BlockSpec((1, 1, 1, Dh),
                             lambda b, h, j, pt, nv: (b, h, 0, 0)),
                pl.BlockSpec((1, 1, 1, Dh),
                             lambda b, h, j, pt, nv: (b, h, 0, 0)),
                *kv_specs,
            ],
            out_specs=pl.BlockSpec((1, 1, G, Dh),
                                   lambda b, h, j, pt, nv: (b, h, 0, 0)),
            scratch_shapes=[
                pltpu.VMEM((G, 128), jnp.float32),
                pltpu.VMEM((G, 128), jnp.float32),
                pltpu.VMEM((G, Dh), jnp.float32),
            ],
        ),
        out_shape=jax.ShapeDtypeStruct((B, KV, G, Dh), q.dtype),
        interpret=_interpret_default() if interpret is None else interpret,
    )(page_table.astype(jnp.int32), n_stale.astype(jnp.int32),
      qg, k_new[:, :, None, :], v_new[:, :, None, :], *kv_operands)
    return out.reshape(B, H * Dh)


# ---------------------------------------------------------------------------
# Prefill kernel: q [B, T, H, Dh] vs pages, causal from per-slot start
# ---------------------------------------------------------------------------

def _paged_prefill_kernel(pt_ref, start_ref, q_ref, *refs,
                          block_t: int, page: int, window: int = 0,
                          pages_per_block: int = 1):
    k_ref, ks_ref, v_ref, vs_ref, o_ref, m_ref, l_ref, acc_ref = \
        unpack_kv_refs(refs)
    b = pl.program_id(0)
    t = pl.program_id(2)
    j = pl.program_id(3)        # run of `pages_per_block` logical pages
    n_pb = pl.num_programs(3)

    @pl.when(j == 0)
    def _init():
        m_ref[:] = jnp.full_like(m_ref, NEG_INF)
        l_ref[:] = jnp.zeros_like(l_ref)
        acc_ref[:] = jnp.zeros_like(acc_ref)

    start = start_ref[b]
    first_q_pos = start + t * block_t
    last_q_pos = first_q_pos + (block_t - 1)

    # Causal upper bound; with a sliding window also a lower bound — a
    # page is dead unless its last key position is within `window` of the
    # block's FIRST query (flash_attention._chunk_kernel is the dense
    # twin). Dead pages skip compute and DMA (index-map clamp). Per-page
    # attends unrolled over the block's sub-pages keep any
    # pages_per_block bit-for-bit with the per-page kernel (see
    # _paged_decode_kernel).
    for i in range(pages_per_block):
        lp = j * pages_per_block + i                   # logical page
        live = lp * page <= last_q_pos
        if window:
            live = live & ((lp + 1) * page - 1 > first_q_pos - window)

        @pl.when(live)
        def _block(i=i, lp=lp):
            def mask(scores):
                q_pos = first_q_pos + jax.lax.broadcasted_iota(
                    jnp.int32, scores.shape, 0)
                s_pos = lp * page + jax.lax.broadcasted_iota(
                    jnp.int32, scores.shape, 1)
                ok = s_pos <= q_pos
                if window:
                    ok = ok & (s_pos > q_pos - window)
                return jnp.where(ok, scores, NEG_INF)
            attend_block(q_ref, k_ref, v_ref, m_ref, l_ref, acc_ref, mask,
                         ks_ref, vs_ref, sub=i)

    @pl.when(j == n_pb - 1)
    def _out():
        l = l_ref[:, :1]
        o_ref[0, 0] = (acc_ref[:] / jnp.where(l == 0.0, 1.0, l)
                       ).astype(o_ref.dtype)


def paged_prefill_attention(q: jax.Array, k_pages, v_pages,
                            page_table: jax.Array,
                            start: jax.Array, *, block_t: int = 128,
                            window: int = 0,
                            pages_per_block: int = 1,
                            interpret: bool | None = None) -> jax.Array:
    """Causal chunk attention over the page pool (keys already inserted).

    q: [B, T, H, Dh] at absolute positions ``start + t``;
    k_pages/v_pages: [P, KV, page, Dh] or the int8 ``{"q","s"}`` dicts;
    page_table: [B, NP]; start: [B]. ``window``: sliding-window bound
    (0 = full causal) — out-of-window pages skip compute and DMA.
    ``pages_per_block``: run of contiguous logical pages fetched per
    inner-loop step (same packed-table contract and bit-for-bit
    guarantee as :func:`paged_decode_attention`).
    Returns [B, T, H*Dh].
    """
    B, T, H, Dh = q.shape
    quant = isinstance(k_pages, dict)
    kq = k_pages["q"] if quant else k_pages
    KV, page = kq.shape[1], kq.shape[2]
    NP = page_table.shape[1]
    ppb = pages_per_block
    _check_pages_per_block(ppb, NP, kq.shape[0])
    bs = ppb * page
    G = H // KV
    block_t = min(block_t, T)
    if T % block_t:
        raise ValueError(f"T={T} not a multiple of block_t={block_t}")
    qh = q.transpose(0, 2, 1, 3)
    grid = (B, H, T // block_t, NP // ppb)

    def _live_range(st_b, t):
        first_q = st_b + t * block_t
        last = (first_q + block_t - 1) // bs
        if window:
            first = jnp.minimum(
                jnp.maximum(first_q - (window - 1), 0) // bs, last)
        else:
            first = 0
        return first, last

    def _phys_block(pt, b, g):
        # Gather-free superpage lookup — see paged_decode_attention.
        p0 = pt[b, g * ppb]
        return p0 // ppb if ppb > 1 else p0

    def kv_index(b, h, t, j, pt, st):
        first, last = _live_range(st[b], t)
        return _phys_block(pt, b, jnp.clip(j, first, last)), h // G, 0, 0

    def scale_index(b, h, t, j, pt, st):
        first, last = _live_range(st[b], t)
        return _phys_block(pt, b, jnp.clip(j, first, last)), h // G, 0, 0

    # Stored rank-4 [P, KV, 1, page] scale layout — see
    # paged_decode_attention.
    kv_spec = pl.BlockSpec((ppb, 1, page, Dh), kv_index)
    s_spec = pl.BlockSpec((ppb, 1, 1, page), scale_index)
    if quant:
        kv_operands = (k_pages["q"], k_pages["s"],
                       v_pages["q"], v_pages["s"])
        kv_specs = [kv_spec, s_spec, kv_spec, s_spec]
    else:
        kv_operands = (k_pages, v_pages)
        kv_specs = [kv_spec, kv_spec]

    out = pl.pallas_call(
        functools.partial(_paged_prefill_kernel, block_t=block_t, page=page,
                          window=window, pages_per_block=ppb),
        grid_spec=pltpu.PrefetchScalarGridSpec(
            num_scalar_prefetch=2,
            grid=grid,
            in_specs=[
                pl.BlockSpec((1, 1, block_t, Dh),
                             lambda b, h, t, j, pt, st: (b, h, t, 0)),
                *kv_specs,
            ],
            out_specs=pl.BlockSpec((1, 1, block_t, Dh),
                                   lambda b, h, t, j, pt, st: (b, h, t, 0)),
            scratch_shapes=[
                pltpu.VMEM((block_t, 128), jnp.float32),
                pltpu.VMEM((block_t, 128), jnp.float32),
                pltpu.VMEM((block_t, Dh), jnp.float32),
            ],
        ),
        out_shape=jax.ShapeDtypeStruct((B, H, T, Dh), q.dtype),
        interpret=_interpret_default() if interpret is None else interpret,
    )(page_table.astype(jnp.int32), start.astype(jnp.int32),
      qh, *kv_operands)
    return out.transpose(0, 2, 1, 3).reshape(B, T, H * Dh)


# ---------------------------------------------------------------------------
# Reference jnp path (CPU tests / non-TPU backends) + attention_fn adapter
# ---------------------------------------------------------------------------

def gather_pages(layer_pages, page_table: jax.Array, max_seq: int):
    """Materialize the dense [B, KV, S(, Dh)] view from the pool —
    reference path only. Dict pools gather per leaf; the rank-4
    [P, KV, 1, page] scale plane gathers through its squeezed rank-3
    view and comes back rank-4 [B, KV, 1, S] (the dense stored form)."""
    if isinstance(layer_pages, dict):
        s = gather_pages(layer_pages["s"][:, :, 0, :], page_table, max_seq)
        return {"q": gather_pages(layer_pages["q"], page_table, max_seq),
                "s": s[:, :, None, :]}
    KV, page = layer_pages.shape[1], layer_pages.shape[2]
    NP = page_table.shape[1]
    n_pages = min(NP, (max_seq + page - 1) // page)
    picked = layer_pages[page_table[:, :n_pages]]     # [B, n, KV, page(,Dh)]
    picked = jnp.moveaxis(picked, 1, 2)               # [B, KV, n, page(,Dh)]
    seq = picked.reshape(page_table.shape[0], KV, n_pages * page,
                         *picked.shape[4:])
    return seq[:, :, :max_seq]


def dequant_gathered(d, dtype):
    """Gathered pool dict → dense float view (reference paths only; the
    Pallas kernels consume the int8 pool + scales directly). The gathered
    scale is rank-4 [B, KV, 1, S] (gather_pages owns that form); swapping
    its trailing dims broadcasts it against the [B, KV, S, Dh] values.
    THE one copy of the int8-KV dequant — the per-mesh adapters share it."""
    if isinstance(d, dict):
        return d["q"].astype(dtype) * jnp.swapaxes(
            d["s"], -1, -2).astype(dtype)
    return d


def _paged_reference_core(q, dense_k, dense_v, lengths, active, T,
                          window: int = 0):
    """Dense attention over a gathered view WITHOUT re-inserting."""
    B, H = q.shape[0], q.shape[2]
    KV, S = dense_k.shape[1], dense_k.shape[2]
    Dh = q.shape[3]
    group = H // KV
    k_all = jnp.repeat(dense_k, group, axis=1)
    v_all = jnp.repeat(dense_v, group, axis=1)
    qf = q.astype(jnp.float32)
    scores = jnp.einsum("bthd,bhsd->bhts", qf, k_all.astype(jnp.float32))
    scores = scores / jnp.sqrt(jnp.asarray(Dh, jnp.float32))
    q_pos = lengths[:, None] + jnp.arange(T)[None, :]
    s_idx = jnp.arange(S)[None, None, :]
    visible = s_idx <= q_pos[:, :, None]
    if window:
        # HF Mistral semantics: key s visible to query i iff i - s < window.
        visible = visible & (s_idx > q_pos[:, :, None] - window)
    if active is not None:
        visible = visible & active[:, None, None]
    scores = jnp.where(visible[:, None, :, :], scores, NEG_INF)
    probs = jax.nn.softmax(scores, axis=-1)
    out = jnp.einsum("bhts,bhsd->bthd", probs, v_all.astype(jnp.float32))
    return out.reshape(B, T, H * Dh).astype(q.dtype)


def make_paged_attention_fn(page_table: jax.Array, max_seq: int,
                            impl: str = "pallas",
                            block_t: int | None = None,
                            interpret: bool | None = None,
                            mesh=None, window: int = 0,
                            pages_per_block: int = 1,
                            spec: bool = False):
    """Build an ``attention_fn`` (llama.forward contract) over a paged cache.

    Constructed INSIDE the engine's jitted step function, closing over the
    traced ``page_table`` — so the model forward signature is unchanged and
    ``layer_k``/``layer_v`` are the per-layer page pools from the scanned
    ``PagedKVCache``. ``impl``: "pallas" (kernels) or "reference" (gather +
    dense jnp — exact but materializes [B, S]; CPU tests).
    ``pages_per_block``: multi-page kernel blocking (pallas impl only;
    the reference path gathers densely and ignores it) — requires the
    engine's superpage-packed allocator behind the table.

    With a multi-device ``mesh`` the kernels run under ``shard_map`` manual
    over the ``model`` axis — pages are sharded on their KV-head dim, the
    page table is replicated (it indexes the pool's unsharded page dim), and
    the insert scatter stays in XLA/GSPMD. The pool has no batch dim, so
    there is nothing to go manual over on ``data``.
    """
    from jax.sharding import PartitionSpec as P

    msize = mesh.shape.get("model", 1) if mesh is not None else 1

    _dequant_dense = dequant_gathered

    def _pool_spec(side):
        """Per-leaf shard_map spec for a per-layer pool side: the int8
        scale plane is rank-4 [P, KV, 1, page] (head dim shards like the
        value's; the trailing (1, page) dims stay whole)."""
        val = P(None, "model", None, None)
        if isinstance(side, dict):
            return {"q": val, "s": P(None, "model", None, None)}
        return val

    def attention_fn(q, k_new, v_new, layer_k, layer_v, lengths, active=None):
        # Phase marker (ISSUE 8): trace-time metadata so captures name
        # the paged kernels inside the layer's attention scope.
        with jax.named_scope("attention.paged_prefill"):
            return _attention_fn(q, k_new, v_new, layer_k, layer_v,
                                 lengths, active)

    def _attention_fn(q, k_new, v_new, layer_k, layer_v, lengths,
                      active=None):
        B, T, H, Dh = q.shape
        quant = isinstance(layer_k, dict)
        KV = (layer_k["q"] if quant else layer_k).shape[1]
        layer_k, layer_v = paged_insert_kv(layer_k, layer_v, k_new, v_new,
                                           page_table, lengths, active)
        if impl == "reference":
            dense_k = _dequant_dense(
                gather_pages(layer_k, page_table, max_seq), q.dtype)
            dense_v = _dequant_dense(
                gather_pages(layer_v, page_table, max_seq), q.dtype)
            out = _paged_reference_core(q, dense_k, dense_v, lengths,
                                        active, T, window=window)
            return out, layer_k, layer_v
        shard = msize > 1 and KV % msize == 0 and H % msize == 0
        pool = _pool_spec(layer_k)
        bt = block_t if block_t is not None else min(T & (-T), 128)
        if shard:
            f = shard_map(
                lambda q_, k_, v_, pt_, st_: paged_prefill_attention(
                    q_, k_, v_, pt_, st_, block_t=bt, window=window,
                    pages_per_block=pages_per_block, interpret=interpret),
                mesh=mesh,
                in_specs=(P(None, None, "model", None), pool, pool,
                          P(None, None), P(None)),
                out_specs=P(None, None, "model"),
                axis_names={"model"}, check_vma=False)
            out = f(q, layer_k, layer_v, page_table, lengths)
        else:
            out = paged_prefill_attention(
                q, layer_k, layer_v, page_table, lengths,
                block_t=bt, window=window,
                pages_per_block=pages_per_block, interpret=interpret)
        return out, layer_k, layer_v

    def decode(q, k_new, v_new, layer_k, layer_v, lengths, active=None):
        """Deferred-decode: stale pool + self column, no insert."""
        with jax.named_scope("attention.paged_decode"):
            return _decode(q, k_new, v_new, layer_k, layer_v, lengths,
                           active)

    def _decode(q, k_new, v_new, layer_k, layer_v, lengths, active=None):
        B, T, H, Dh = q.shape
        quant = isinstance(layer_k, dict)
        KV = (layer_k["q"] if quant else layer_k).shape[1]
        n_stale = lengths if active is None else jnp.where(active, lengths, 0)
        if impl == "reference":
            # dense_decode_attention is dict-aware: the gathered int8
            # view + scales pass through un-dequantized.
            from ..models.llama import dense_decode_attention
            dense_k = gather_pages(layer_k, page_table, max_seq)
            dense_v = gather_pages(layer_v, page_table, max_seq)
            return dense_decode_attention(q, k_new, v_new, dense_k, dense_v,
                                          n_stale, None, window=window)
        shard = msize > 1 and KV % msize == 0 and H % msize == 0
        pool = _pool_spec(layer_k)
        if shard:
            f = shard_map(
                lambda q_, kn_, vn_, k_, v_, pt_, nv_: paged_decode_attention(
                    q_, kn_, vn_, k_, v_, pt_, nv_, window=window,
                    pages_per_block=pages_per_block, interpret=interpret),
                mesh=mesh,
                in_specs=(P(None, "model", None), P(None, "model", None),
                          P(None, "model", None), pool, pool,
                          P(None, None), P(None)),
                out_specs=P(None, "model"),
                axis_names={"model"}, check_vma=False)
            out = f(q[:, 0], k_new[:, 0], v_new[:, 0], layer_k, layer_v,
                    page_table, n_stale)
        else:
            out = paged_decode_attention(
                q[:, 0], k_new[:, 0], v_new[:, 0], layer_k, layer_v,
                page_table, n_stale, window=window,
                pages_per_block=pages_per_block, interpret=interpret)
        return out[:, None, :]

    def verify(q, k_new, v_new, layer_k, layer_v, lengths, active=None):
        """Deferred speculative verify: T = k+1 draft tokens attend the
        STALE pool (gathered to a dense per-slot view) plus the causal
        self-block, no pool write inside the layer scan — the insert
        happens once via ``insert_all`` (T-generalized). Two wins over
        the chunk path it replaces: (1) exact-greedy parity under int8 —
        dense_verify_attention's mixed-precision self-block reads
        off-diagonal drafts quantize→dequantized and the diagonal at
        full precision, matching what plain decode sees, where the chunk
        path reads even the SELF token quantized; (2) no per-layer pool
        scatters through the spec burst scan (2·L serialized scatters
        per verify step — the same cost insert_kv_stacked's dense twin
        eliminates). The gather materializes [B, KV, max_seq, Dh] —
        bounded by CONTEXT, not pool capacity, i.e. the same bytes one
        decode step's attention streams anyway, amortized over k+1
        positions."""
        with jax.named_scope("attention.paged_verify"):
            from ..models.llama import dense_verify_attention
            n_stale = (lengths if active is None
                       else jnp.where(active, lengths, 0))
            dense_k = gather_pages(layer_k, page_table, max_seq)
            dense_v = gather_pages(layer_v, page_table, max_seq)
            return dense_verify_attention(q, k_new, v_new, dense_k,
                                          dense_v, n_stale, None,
                                          window=window)

    def insert_all(pool_k, pool_v, k_news, v_news, lengths, active):
        return paged_insert_all(pool_k, pool_v, k_news, v_news,
                                page_table, lengths, active)

    attention_fn.decode = decode
    attention_fn.insert_all = insert_all
    if spec:
        # Spec-only provider: a `.verify` on the SHARED provider would
        # reroute every prefill chunk (T > 1) through the deferred path;
        # the engine builds a dedicated instance for spec bursts.
        attention_fn.verify = verify
    return attention_fn


# ---------------------------------------------------------------------------
# Sequence-sharded paged attention (paged × seq composition)
# ---------------------------------------------------------------------------

def _seq_local_table(page_table: jax.Array, seq_n: int,
                     band_pages: int) -> jax.Array:
    """Translate the replicated GLOBAL page table into THIS chip's local
    ids (inside a shard_map over ``seq``). The banded allocator
    (engine/paged.py) guarantees logical page ``j`` lives in band
    ``j // (NP_slot/seq_n)``; entries outside this chip's band — and
    unallocated zeros — map to local page 0, the chip's OWN trash page
    (band base), so masked scatter redirects stay shard-local."""
    c = jax.lax.axis_index("seq")
    spb = page_table.shape[1] // seq_n            # logical pages per band
    band = jnp.arange(page_table.shape[1], dtype=jnp.int32) // spb
    local = page_table - c * band_pages
    return jnp.where((band[None, :] == c) & (local > 0)
                     & (local < band_pages), local, 0)


def _leaf_specs(side):
    """Per-leaf shard_map specs for a pool side (dict-aware: the rank-4
    [P, KV, 1, page] scale plane has the SAME rank and page-dim position
    as its value): the page dim — 0 for a per-layer side, 1 for a
    stacked [L, ...] one — rides the ``seq`` axis."""
    from jax.sharding import PartitionSpec as P
    if isinstance(side, dict):
        nd = side["q"].ndim
    else:
        nd = side.ndim
    ax = 0 if nd == 4 else 1                      # per-layer vs stacked [L,…]
    def spec(ndim):
        parts = [None] * ndim
        parts[ax] = "seq"
        return P(*parts)
    if isinstance(side, dict):
        return {"q": spec(nd), "s": spec(nd)}
    return spec(nd)


def make_seq_paged_attention_fn(page_table: jax.Array, max_seq: int, mesh):
    """attention_fn for a SEQ-SHARDED paged engine (llama.forward
    contract + the deferred ``.decode``/``.insert_all`` protocol).

    The pool's PAGE dim is sharded over the ``seq`` mesh axis and pages
    are position-banded (engine/paged.py), so each chip's slice of the
    dense view reads only LOCAL pages: a shard_map gather materializes
    the per-layer dense [B, KV, S, Dh] view S-SHARDED over ``seq`` (no
    collective — the out_spec just declares the sharding), and the
    standard dense deferred attention partitions its S-reductions under
    GSPMD exactly like the dense seq engine. Writes run a shard_map'd
    paged scatter against the chip-local table translation (out-of-band
    and masked writes land on the chip's own trash page).

    jnp/GSPMD math only (v1): correctness-complete; the paged kernels
    don't run under a seq sharding yet."""
    from jax.sharding import PartitionSpec as P

    from ..models.llama import dense_decode_attention

    seq_n = mesh.shape["seq"]

    def _gather_local(pool, tbl):
        """One chip's dense S-shard from its local pool shard."""
        lt = _seq_local_table(tbl, seq_n, _band_pages(pool))
        c = jax.lax.axis_index("seq")
        spb = tbl.shape[1] // seq_n
        cols = jax.lax.dynamic_slice_in_dim(lt, c * spb, spb, 1)  # [B, spb]

        def g(leaf):
            picked = jnp.take(leaf, cols, axis=0)   # [B, spb, KV, page(,Dh)]
            picked = jnp.moveaxis(picked, 1, 2)     # [B, KV, spb, page(,Dh)]
            B = cols.shape[0]
            KV, page = leaf.shape[1], leaf.shape[2]
            return picked.reshape(B, KV, spb * page, *leaf.shape[3:])
        if isinstance(pool, dict):
            # Scale leaf [Pl, KV, 1, page]: gather through its squeezed
            # rank-3 view, return the dense stored form [B, KV, 1, S].
            return {"q": g(pool["q"]),
                    "s": g(pool["s"][:, :, 0, :])[:, :, None, :]}
        return g(pool)

    def _band_pages(pool):
        leaf = pool["q"] if isinstance(pool, dict) else pool
        return leaf.shape[0]        # inside shard_map: the LOCAL shard size

    def gather_view(pool_layer):
        """[B, KV, S, Dh] dense view, sharded on S over ``seq``."""
        def out_spec(side):
            if isinstance(side, dict):
                return {"q": P(None, None, "seq", None),
                        "s": P(None, None, None, "seq")}
            return P(None, None, "seq", None)
        return shard_map(
            _gather_local, mesh=mesh,
            in_specs=(_leaf_specs(pool_layer), P()),
            out_specs=out_spec(pool_layer),
            axis_names={"seq"}, check_vma=False)(pool_layer, page_table)

    def _insert_local(lk, lv, kn, vn, tbl, lengths, active):
        lt = _seq_local_table(tbl, seq_n, _band_pages(lk))
        return paged_insert_kv(lk, lv, kn, vn, lt, lengths, active)

    def sharded_insert(layer_k, layer_v, k_new, v_new, lengths, active):
        act = jnp.ones(lengths.shape, bool) if active is None else active
        return shard_map(
            _insert_local, mesh=mesh,
            in_specs=(_leaf_specs(layer_k), _leaf_specs(layer_v),
                      P(), P(), P(), P(), P()),
            out_specs=(_leaf_specs(layer_k),
                       _leaf_specs(layer_v)),
            axis_names={"seq"}, check_vma=False)(
            layer_k, layer_v, k_new, v_new, page_table, lengths, act)

    def attention_fn(q, k_new, v_new, layer_k, layer_v, lengths,
                     active=None):
        """Chunk path (insert-then-attend over the gathered view; used by
        the speculative verify — seq prefill rides ring attention via the
        engine's prefill provider instead)."""
        B, T, H, Dh = q.shape
        layer_k, layer_v = sharded_insert(layer_k, layer_v, k_new, v_new,
                                          lengths, active)
        dk = gather_view(layer_k)
        dv = gather_view(layer_v)

        out = _paged_reference_core(q, dequant_gathered(dk, q.dtype),
                                    dequant_gathered(dv, q.dtype),
                                    lengths, active, T)
        return out, layer_k, layer_v

    def decode(q, k_new, v_new, layer_k, layer_v, lengths, active=None):
        """Deferred decode: gather the stale dense view (local, no
        collective), then the dict-aware dense decode attention — GSPMD
        partitions its S-reductions over the ``seq`` sharding."""
        dk = gather_view(layer_k)
        dv = gather_view(layer_v)
        n = lengths if active is None else jnp.where(active, lengths, 0)
        return dense_decode_attention(q, k_new, v_new, dk, dv, n, None)

    def _insert_all_local(pk, pv, kns, vns, tbl, lengths, active):
        lt = _seq_local_table(tbl, seq_n,
                              (pk["q"] if isinstance(pk, dict) else
                               pk).shape[1])
        return paged_insert_all(pk, pv, kns, vns, lt, lengths, active)

    def insert_all(pool_k, pool_v, k_news, v_news, lengths, active):
        act = jnp.ones(lengths.shape, bool) if active is None else active
        return shard_map(
            _insert_all_local, mesh=mesh,
            in_specs=(_leaf_specs(pool_k), _leaf_specs(pool_v),
                      P(), P(), P(), P(), P()),
            out_specs=(_leaf_specs(pool_k),
                       _leaf_specs(pool_v)),
            axis_names={"seq"}, check_vma=False)(
            pool_k, pool_v, k_news, v_news, page_table, lengths, act)

    attention_fn.decode = decode
    attention_fn.insert_all = insert_all
    attention_fn.insert = sharded_insert    # ring-prefill write hook
    return attention_fn
