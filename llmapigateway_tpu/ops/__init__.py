"""TPU-native kernels (Pallas/Mosaic) — this framework's "native tier".

The reference has no native code at all (SURVEY.md §2: 100% Python); here
the hand-written machine-code tier is Pallas kernels compiled by Mosaic for
the TPU's MXU/VPU, replacing the hot jnp attention path in models/llama.py.
"""
from .flash_attention import (
    flash_decode_attention,
    flash_prefill_attention,
    make_cache_attention_fn,
    make_sharded_cache_attention_fn,
)

__all__ = [
    "flash_decode_attention",
    "flash_prefill_attention",
    "make_cache_attention_fn",
    "make_sharded_cache_attention_fn",
]
