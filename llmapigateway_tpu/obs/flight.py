"""Scheduler flight recorder: a fixed-size ring of per-step and
per-lifecycle records emitted by the engine loop (ISSUE 7).

PR 4's span trees answer "where did request X spend its time" and the
``/metrics`` plane answers "what are the aggregates" — but the scheduler's
*decisions* (batch composition, burst depth, clamp engagements, page
pressure, admission order) were computed every step and then thrown away
into EMAs. This module keeps the last ``capacity`` of them, cheap enough
to leave on in production:

* **Preallocated, allocation-free appends.** The ring is one numpy
  structured array plus a fixed-length Python list for request-id
  references; an append is a handful of scalar stores into preexisting
  storage — no dict/list/object construction on the step path. Request
  ids are only attached to *lifecycle* records (admit/finish/shed — per
  request, not per step), and storing a reference into a preallocated
  list slot is a pointer write.
* **Single-writer, no locks.** Every append happens on the engine's
  event-loop thread (the scheduler), marked ``# guarded-by: loop`` and
  enforced by the runtime sanitizer (the recorder is on its instrumented
  class list). Readers — the ``GET /v1/api/flight`` handler and the
  stats bridge — also run on the loop, so there is no cross-thread
  access at all.
* **Sequence numbers cross-link the planes.** Every record carries a
  monotonically increasing ``seq``; a request's admit/finish seqs are
  stamped onto its GenRequest and surfaced as span attributes in the
  ``/v1/api/trace/{id}`` tree, so an operator can jump from one
  request's trace to the exact scheduler steps that served it (and
  ``tools/flight_report.py`` renders both on one Perfetto timeline).

``snapshot()`` (the read side) allocates freely — it runs per HTTP read,
not per step.
"""
from __future__ import annotations

import math
import time
from typing import Any, Callable

import numpy as np

DEFAULT_CAPACITY = 4096

# Record kinds.
STEP = 1          # one scheduler iteration that did work
ADMIT = 2         # request got a slot (queue-wait + prefix-hit accounting)
FINISH = 3        # request left its slot (any reason, incl. cancel)
SHED = 4          # admission refused on a full queue (gateway 429 path)
EVICT = 5         # prefix-cache eviction under page pressure
PROF = 6          # profiler capture start/stop (ISSUE 8): rid = trace dir
SUPERVISOR = 7    # engine lifecycle transition (ISSUE 14): flag = state

KIND_NAMES = {STEP: "step", ADMIT: "admit", FINISH: "finish",
              SHED: "shed", EVICT: "evict", PROF: "profile",
              SUPERVISOR: "supervisor"}

# SUPERVISOR flag values: index into this tuple = the state entered.
# Mirrors reliability/supervisor.py LIFECYCLE_STATES (order matters —
# the flight-report goldens pin the rendered names).
SUPERVISOR_STATES = ("starting", "serving", "draining", "restarting",
                     "failed", "stopped")

# PROF flag values (capture lifecycle).
PROF_START = 1
PROF_STOP = 2

# Pool tags (ISSUE 13, disaggregated serving): which scheduler pool
# emitted the record. 0 = the unified (single-pool) scheduler — the
# value every pre-disagg ring carries, so unified snapshots are
# byte-identical to the pre-pool format (the field is only emitted
# when nonzero).
POOL_UNIFIED = 0
POOL_PREFILL = 1
POOL_DECODE = 2
POOL_NAMES = {POOL_UNIFIED: "unified", POOL_PREFILL: "prefill",
              POOL_DECODE: "decode"}

# STEP flag bits: what the scheduler iteration actually ran.
F_PREFILL = 1     # >=1 prefill chunk dispatched
F_DECODE = 2      # a decode burst ran
F_SPEC = 4        # the burst was speculative
F_BUSY = 8        # burst depth picked under the busy (interleave) policy
F_CLAMPED = 16    # the prefill-aware TTFT clamp shortened this burst

_DTYPE = np.dtype([
    ("seq", np.int64),          # monotonically increasing record number
    ("t", np.float64),          # record END time (tracer clock domain)
    ("dur_ms", np.float32),     # covered wall time (0 for point events)
    ("kind", np.uint8),
    ("flag", np.uint8),         # STEP: F_* bits; FINISH: reason code
    ("slot", np.int16),         # lifecycle records; -1 = n/a
    ("depth", np.int16),        # decode burst depth (STEP) / group K
    ("tokens", np.int32),       # tokens emitted (STEP) / generated (FINISH)
    ("chunks", np.int16),       # prefill chunk dispatches this step
    ("active", np.int16),       # running requests after the step
    ("free_slots", np.int16),
    ("queued", np.int16),       # admission queue depth (+ parked head)
    ("free_pages", np.int32),   # paged pool headroom; -1 = dense layout
    ("fitted_ms", np.float32),  # engine's fitted per-step time (NaN unset)
    ("val", np.float32),        # kind-specific: decode-burst wall ms
                                # (STEP), queue-wait ms (ADMIT), pages
                                # evicted (EVICT)
    ("spec_acc", np.int32),     # SPEC steps: accepted draft tokens this
                                # burst (tokens - spec_acc = what a plain
                                # burst of the same depth would have made)
    ("pool", np.uint8),         # POOL_* tag; 0 = unified scheduler
])

FINISH_REASONS = ("stop", "length", "cancelled", "error")


def step_kind(flag: int) -> str:
    """The human name of a STEP record's composition."""
    pf, dc = bool(flag & F_PREFILL), bool(flag & F_DECODE)
    if pf and dc:
        return "mixed"
    if pf:
        return "prefill"
    if dc:
        return "spec" if flag & F_SPEC else "decode"
    return "idle"


class FlightRecorder:
    """Fixed-capacity ring of scheduler records. Single-writer (the engine
    loop); appended fields are all ``guarded-by: loop``."""

    def __init__(self, capacity: int = DEFAULT_CAPACITY,
                 clock: Callable[[], float] = time.monotonic):
        self.capacity = max(16, int(capacity))
        self.clock = clock
        self._buf = np.zeros(self.capacity, _DTYPE)     # guarded-by: loop
        # Column views cached once: a structured-array field lookup
        # (buf["seq"]) is a per-call dict hit + view construction — on
        # the step path that was most of the append cost. The views
        # alias _buf's memory, so snapshot() reads stay coherent.
        self._cols = {name: self._buf[name] for name in _DTYPE.names}
        # Request-id references for lifecycle records, parallel to _buf.
        # Preallocated: an append stores a reference into an existing
        # slot, never grows the list.
        self._rid = [None] * self.capacity              # guarded-by: loop
        self._seq = 0                                   # guarded-by: loop
        # Lifecycle balance counters: every admitted request must leave a
        # FINISH record (the chaos tests assert admits == finishes — a
        # "leaked" flight record is a request the scheduler lost track of).
        self._admits = 0                                # guarded-by: loop
        self._finishes = 0                              # guarded-by: loop
        self._sheds = 0                                 # guarded-by: loop

    # -- hot path (engine loop only) ----------------------------------------
    def record(self, kind: int, *, dur_ms: float = 0.0, flag: int = 0,
               slot: int = -1, depth: int = 0, tokens: int = 0,
               chunks: int = 0, active: int = 0, free_slots: int = 0,
               queued: int = 0, free_pages: int = -1,
               fitted_ms: float = math.nan, val: float = 0.0,
               spec_acc: int = 0, pool: int = 0,
               rid: str | None = None) -> int:
        """Append one record; returns its sequence number. Scalar stores
        into preallocated storage only — no per-record allocation."""
        i = self._seq % self.capacity
        cols = self._cols
        cols["seq"][i] = self._seq
        cols["t"][i] = self.clock()
        cols["dur_ms"][i] = dur_ms
        cols["kind"][i] = kind
        cols["flag"][i] = flag
        cols["slot"][i] = slot
        cols["depth"][i] = depth
        cols["tokens"][i] = tokens
        cols["chunks"][i] = chunks
        cols["active"][i] = active
        cols["free_slots"][i] = free_slots
        cols["queued"][i] = queued
        cols["free_pages"][i] = free_pages
        cols["fitted_ms"][i] = fitted_ms
        cols["val"][i] = val
        cols["spec_acc"][i] = spec_acc
        cols["pool"][i] = pool
        self._rid[i] = rid
        seq = self._seq
        self._seq += 1
        if kind == ADMIT:
            self._admits += 1
        elif kind == FINISH:
            self._finishes += 1
        elif kind == SHED:
            self._sheds += 1
        return seq

    # -- read side (also loop-thread; allocates freely) ---------------------
    @property
    def seq(self) -> int:
        """Next sequence number (== total records ever appended)."""
        return self._seq

    @property
    def evicted(self) -> int:
        """Records overwritten by ring wrap — flight loss under load."""
        return max(0, self._seq - self.capacity)

    def snapshot(self, since: int = -1) -> list[dict[str, Any]]:
        """Records with ``seq > since`` still resident, oldest first."""
        lo = max(self._seq - self.capacity, since + 1, 0)
        out: list[dict[str, Any]] = []
        for s in range(lo, self._seq):
            i = s % self.capacity
            row = self._buf[i]
            kind = int(row["kind"])
            d: dict[str, Any] = {
                "seq": int(row["seq"]),
                "t": float(row["t"]),
                "kind": KIND_NAMES.get(kind, str(kind)),
            }
            dur = float(row["dur_ms"])
            if dur:
                d["dur_ms"] = round(dur, 3)
            if kind == STEP:
                flag = int(row["flag"])
                d["step_kind"] = step_kind(flag)
                d["busy"] = bool(flag & F_BUSY)
                d["clamped"] = bool(flag & F_CLAMPED)
                if row["depth"]:
                    d["burst_depth"] = int(row["depth"])
                if row["chunks"]:
                    d["prefill_chunks"] = int(row["chunks"])
                d["tokens"] = int(row["tokens"])
                d["active"] = int(row["active"])
                d["free_slots"] = int(row["free_slots"])
                d["queued"] = int(row["queued"])
                if row["free_pages"] >= 0:
                    d["free_pages"] = int(row["free_pages"])
                if flag & F_SPEC:
                    # Accepted draft tokens this burst: the speculation
                    # win over a plain burst of the same depth.
                    d["spec_accepted"] = int(row["spec_acc"])
                dv = float(row["val"])
                if dv:
                    d["decode_wall_ms"] = round(dv, 3)
                    if row["depth"]:
                        d["measured_step_ms"] = round(
                            dv / int(row["depth"]), 3)
                fitted = float(row["fitted_ms"])
                if not math.isnan(fitted):
                    d["fitted_step_ms"] = round(fitted, 3)
            elif kind == ADMIT:
                d["slot"] = int(row["slot"])
                d["queue_wait_ms"] = round(float(row["val"]), 3)
                d["cached_tokens"] = int(row["tokens"])
                d["queued"] = int(row["queued"])
            elif kind == FINISH:
                d["slot"] = int(row["slot"])
                reason = int(row["flag"])
                d["reason"] = (FINISH_REASONS[reason]
                               if reason < len(FINISH_REASONS) else "?")
                d["tokens"] = int(row["tokens"])
            elif kind == EVICT:
                d["pages_evicted"] = int(row["val"])
                if row["free_pages"] >= 0:
                    d["free_pages"] = int(row["free_pages"])
            elif kind == PROF:
                # Profiler capture boundary (ISSUE 8): the rid carries
                # the capture's trace directory, so a Perfetto timeline
                # built from this ring cross-links to the XLA capture
                # that covered these seqs.
                d["phase"] = ("start" if int(row["flag"]) == PROF_START
                              else "stop")
            elif kind == SUPERVISOR:
                # Engine lifecycle transition (ISSUE 14): the state the
                # engine ENTERED; rid carries the transition reason so
                # an incident reads off the ring without joining logs.
                flag = int(row["flag"])
                d["state"] = (SUPERVISOR_STATES[flag]
                              if flag < len(SUPERVISOR_STATES) else "?")
            pool = int(row["pool"])
            if pool:
                # Disagg pool tag (ISSUE 13). Omitted for the unified
                # scheduler so pre-pool snapshot consumers (and the
                # flight-report goldens) see the exact old shape.
                d["pool"] = POOL_NAMES.get(pool, str(pool))
            rid = self._rid[i]
            if rid:
                # The rid slot is kind-polymorphic: SUPERVISOR records
                # store the transition reason there (no request owns a
                # lifecycle event).
                d["reason" if kind == SUPERVISOR else "request_id"] = rid
            out.append(d)
        return out

    def steps_overlapping(self, t0: float, t1: float,
                          flag_mask: int = F_DECODE) -> float:
        """Total milliseconds of resident STEP records matching
        ``flag_mask`` that overlap the window ``[t0, t1]`` — the SLO
        attribution plane's "how much of this request's prefill window
        went to decode contention" query (obs/slo.py)."""
        if t1 <= t0:
            return 0.0
        lo = max(self._seq - self.capacity, 0)
        total = 0.0
        buf = self._buf
        for s in range(lo, self._seq):
            i = s % self.capacity
            if int(buf["kind"][i]) != STEP:
                continue
            if not (int(buf["flag"][i]) & flag_mask):
                continue
            end = float(buf["t"][i])
            # The decode burst's own wall (val) when recorded — a mixed
            # step's prefill share must not count as decode contention;
            # the burst runs last in the step, so it ends ~at the record.
            width = float(buf["val"][i]) or float(buf["dur_ms"][i])
            start = end - width / 1000.0
            ov = min(end, t1) - max(start, t0)
            if ov > 0:
                total += ov * 1000.0
        return total

    def stats(self) -> dict[str, Any]:
        """Counters for the stats()/metrics bridge and the leak check."""
        return {
            "flight_seq": self._seq,
            "flight_capacity": self.capacity,
            "flight_evicted_total": self.evicted,
            "flight_admits": self._admits,
            "flight_finishes": self._finishes,
            "flight_sheds": self._sheds,
        }
