"""Device observability plane (ISSUE 8): HBM memory ledger, per-kernel
roofline cost registry, phase annotations, and XLA compile-event telemetry.

The host-side planes (PR 4 metrics/tracing, PR 7 flight recorder + SLO
attribution) stop at the dispatch boundary: the engine reported ONE
aggregate ``roofline_fraction`` and nothing said which compiled kernel is
off the HBM roof, how much HBM each subsystem actually holds, or when XLA
silently recompiled mid-serving. This module is the device-side substrate:

* :class:`HbmLedger` — static accounting of what the engine *intends* to
  hold in device memory (parameter bytes per dtype, KV-pool bytes from
  page geometry, penalty/spec auxiliaries), reconciled at scrape time
  against (a) the bytes the engine's live buffers actually occupy
  (``tracked`` — array metadata only, no device sync) and (b) the
  runtime's own ``device.memory_stats()`` where the backend provides one
  (TPU does; CPU returns None). Exported as ``gateway_engine_hbm_*``
  gauges, with a configurable headroom watermark that feeds the PR 3
  shed path so admission reacts to memory pressure, not just slots.
* :class:`KernelRegistry` — one row per compiled executable variant
  (prefill-chunk buckets, decode bursts per depth/sampler, spec bursts)
  carrying ``lower().compile().cost_analysis()`` FLOPs + bytes (resolved
  off-thread — an AOT lower can cost seconds at 8B scale) joined with
  the walls the engine measures at dispatch and with the PR 7 flight
  ring's per-step records. ``GET /v1/api/roofline`` serves the table and
  names the single worst kernel — the "pick the next kernel target"
  reading ROADMAP item 3 asks for.
* :func:`phase` — host-side ``jax.profiler.TraceAnnotation`` markers
  (``prefill`` / ``decode`` / ``spec.verify``) so on-demand captures
  from ``server/profiler_api.py`` segment by scheduler phase in
  Perfetto, plus a thread-local phase tag the compile monitor reads to
  attribute a recompile to the kernel that triggered it. (The *in-
  program* markers — ``decode.attention`` / ``decode.mlp`` /
  ``sampling`` — are ``jax.named_scope`` calls in models/ and engine/:
  pure trace-time metadata, zero runtime cost.)
* :class:`XlaCompileMonitor` — a ``jax.monitoring`` listener counting
  backend compiles and their wall time per phase, bridged to the
  ``gateway_engine_xla_compile_*`` series and recorded as ``xla.compile``
  spans on the active request trace (contextvars propagate through
  ``asyncio.to_thread``, so a mid-serving recompile lands inside the
  request that paid for it). Surprise recompiles are a silent TTFT
  killer; this makes them a reading.

Thread model: the engine's worker thread records kernel walls and phase
tags; scrape-time readers run on the event loop. ``KernelRegistry`` and
``XlaCompileMonitor`` therefore guard their state with plain locks
(scalar adds — no allocation worth worrying about); ``HbmLedger`` is
read-mostly and computes its snapshots from immutable statics plus
callables the engine provides.
"""
from __future__ import annotations

import contextlib
import logging
import math
import threading
import time
from typing import Any, Callable

logger = logging.getLogger(__name__)

__all__ = [
    "HbmLedger", "KernelRegistry", "XlaCompileMonitor", "phase",
    "current_phase", "install_compile_monitor", "compile_monitor",
    "device_memory_stats", "worst_kernel",
]


# ---------------------------------------------------------------------------
# Device memory probing
# ---------------------------------------------------------------------------

def device_memory_stats(devices: list | None = None) -> dict[str, int] | None:
    """Aggregate ``memory_stats()`` over ``devices`` (default: this
    process's addressable devices). Returns ``{bytes_in_use, peak_bytes,
    bytes_limit}`` summed across devices, or None when the backend
    exposes no allocator stats (CPU) or JAX is unavailable (proxy-only
    deployments). Never raises — a stats probe must not take down a
    scrape."""
    try:
        import jax
        devs = devices if devices is not None else jax.local_devices()
        in_use = peak = limit = 0
        seen = False
        for d in devs:
            try:
                ms = d.memory_stats()
            except Exception:
                ms = None
            if not ms:
                continue
            seen = True
            in_use += int(ms.get("bytes_in_use", 0))
            peak += int(ms.get("peak_bytes_in_use",
                               ms.get("bytes_in_use", 0)))
            limit += int(ms.get("bytes_limit", 0))
        if not seen:
            return None
        return {"bytes_in_use": in_use, "peak_bytes": peak,
                "bytes_limit": limit}
    except Exception:
        return None


class HbmLedger:
    """Static HBM accounting for one engine, reconciled against live state.

    ``weights`` / ``kv_pool`` / ``aux`` / ``spec`` are the byte totals the
    engine computes ONCE from its checkpoint dtypes and cache geometry
    (they never change after init). ``tracked_fn`` returns what the
    engine's live device buffers occupy right now (sum of array
    ``nbytes`` — metadata only); ``mem_fn`` probes the runtime allocator
    (injectable for tests and for backends without one), TTL-cached so a
    per-admission watermark check costs a clock read."""

    def __init__(self, *, weights: int, kv_pool: int, aux: int = 0,
                 spec: int = 0, page_bytes: int = 0,
                 tracked_fn: Callable[[], int] | None = None,
                 mem_fn: Callable[[], dict | None] | None = None,
                 mem_ttl_s: float = 0.5,
                 clock: Callable[[], float] = time.monotonic):
        self.weights = int(weights)
        self.kv_pool = int(kv_pool)
        self.aux = int(aux)
        self.spec = int(spec)
        self.page_bytes = int(page_bytes)   # K+V bytes of ONE physical page
        self.tracked_fn = tracked_fn
        self.mem_fn = mem_fn or device_memory_stats
        self.mem_ttl_s = mem_ttl_s
        self._clock = clock
        self._mem_cache: dict | None = None
        self._mem_stamp = -math.inf

    @property
    def static_total(self) -> int:
        return self.weights + self.kv_pool + self.aux + self.spec

    def device_memory(self) -> dict | None:
        """The runtime allocator's view, TTL-cached (the watermark check
        runs per admission)."""
        now = self._clock()
        if now - self._mem_stamp >= self.mem_ttl_s:
            try:
                self._mem_cache = self.mem_fn()
            except Exception:
                self._mem_cache = None
            self._mem_stamp = now
        return self._mem_cache

    def headroom_fraction(self) -> float | None:
        """Free fraction of the device memory limit (None when the backend
        reports no allocator stats — the watermark is inert there)."""
        mem = self.device_memory()
        if not mem or not mem.get("bytes_limit"):
            return None
        limit = mem["bytes_limit"]
        return max(0.0, (limit - mem.get("bytes_in_use", 0)) / limit)

    def snapshot(self, *, prefix_resident_pages: int = 0) -> dict[str, Any]:
        """Flat ``hbm_*`` fields for the engine's ``stats()`` dict (the
        obs collector bridges them onto ``gateway_engine_hbm_*``)."""
        out: dict[str, Any] = {
            "hbm_weights_bytes": self.weights,
            "hbm_kv_pool_bytes": self.kv_pool,
            "hbm_aux_bytes": self.aux,
            "hbm_ledger_bytes": self.static_total,
        }
        if self.spec:
            out["hbm_spec_bytes"] = self.spec
        if self.page_bytes and prefix_resident_pages:
            out["hbm_prefix_resident_bytes"] = (
                prefix_resident_pages * self.page_bytes)
        if self.tracked_fn is not None:
            try:
                out["hbm_tracked_bytes"] = int(self.tracked_fn())
            except Exception:       # a sick buffer must not break stats()
                logger.debug("hbm tracked-bytes probe failed", exc_info=True)
        mem = self.device_memory()
        if mem:
            out["hbm_device_in_use_bytes"] = mem["bytes_in_use"]
            out["hbm_device_peak_bytes"] = mem["peak_bytes"]
            if mem.get("bytes_limit"):
                out["hbm_device_limit_bytes"] = mem["bytes_limit"]
                out["hbm_headroom_ratio"] = round(
                    max(0.0, (mem["bytes_limit"] - mem["bytes_in_use"])
                        / mem["bytes_limit"]), 4)
        return out


# ---------------------------------------------------------------------------
# Per-kernel roofline cost registry
# ---------------------------------------------------------------------------

class _Kernel:
    __slots__ = ("name", "kind", "variant", "calls", "steps", "wall_ms",
                 "walled_steps", "flops", "xla_bytes", "cost_fn",
                 "cost_error")

    def __init__(self, name: str, kind: str, variant: dict | None):
        self.name = name
        self.kind = kind
        self.variant = dict(variant or {})
        self.calls = 0
        self.steps = 0
        self.wall_ms = 0.0
        self.walled_steps = 0
        self.flops: float | None = None      # per invocation (cost_analysis)
        self.xla_bytes: float | None = None  # per invocation (cost_analysis)
        self.cost_fn: Callable[[], Any] | None = None
        self.cost_error: str | None = None


def _cost_numbers(analysis: Any) -> tuple[float | None, float | None]:
    """(flops, bytes accessed) out of whatever shape ``cost_analysis()``
    returns on this backend (dict on some, list-of-dicts on others)."""
    if isinstance(analysis, (list, tuple)):
        analysis = analysis[0] if analysis else {}
    if not isinstance(analysis, dict):
        return None, None
    flops = analysis.get("flops")
    nbytes = analysis.get("bytes accessed")
    return (float(flops) if flops is not None else None,
            float(nbytes) if nbytes is not None else None)


class KernelRegistry:
    """Counts, measured walls, and static XLA costs per compiled kernel.

    The engine registers a kernel the first time it dispatches the
    variant (prefill bucket × K, decode depth × sampler, spec depth) and
    records every later dispatch with :meth:`record` — a lock-guarded
    handful of scalar adds. ``cost_fn`` closures (AOT
    ``lower().compile().cost_analysis()``) resolve ON DEMAND via
    :meth:`resolve_costs`: re-lowering an 8B program can cost seconds,
    which must never land on the step path or the event loop — the
    roofline endpoint drains pending closures in ``asyncio.to_thread``
    at read time, the bench drains synchronously after each rung. (An
    always-on background resolver was tried and reverted: a thread
    compiling XLA programs concurrently with engine churn / interpreter
    teardown segfaulted the process.)"""

    def __init__(self):
        self._lock = threading.Lock()
        self._kernels: dict[str, _Kernel] = {}      # guarded-by: _lock
        self._pending: list[str] = []               # guarded-by: _lock

    def needs(self, name: str) -> bool:
        """True when the kernel is not yet registered — the caller then
        pays the (one-time) aval-capture cost to build its cost_fn."""
        with self._lock:
            return name not in self._kernels

    def register(self, name: str, kind: str, *, variant: dict | None = None,
                 cost_fn: Callable[[], Any] | None = None) -> None:
        """Idempotent; first registration wins."""
        with self._lock:
            if name in self._kernels:
                return
            k = _Kernel(name, kind, variant)
            k.cost_fn = cost_fn
            self._kernels[name] = k
            if cost_fn is not None:
                self._pending.append(name)

    def record(self, name: str, *, steps: int = 1,
               wall_ms: float | None = None) -> None:
        """One dispatch of ``name`` covering ``steps`` device steps.
        ``wall_ms`` only when the caller measured an honest wall for this
        dispatch (lag-one pipelining makes some walls lies — those calls
        still count, they just don't contribute to the step-time
        estimate)."""
        with self._lock:
            k = self._kernels.get(name)
            if k is None:
                k = _Kernel(name, "unknown", None)
                self._kernels[name] = k
            k.calls += 1
            k.steps += steps
            if wall_ms is not None:
                k.wall_ms += wall_ms
                k.walled_steps += steps

    # -- cost resolution (on demand, caller's thread) -----------------------
    def resolve_costs(self) -> None:
        """Drain pending cost_fns synchronously. Callers keep it off hot
        paths and off the event loop (the roofline endpoint wraps it in
        ``asyncio.to_thread``); concurrent callers are safe — the queue
        pop is lock-guarded and each closure runs at most once."""
        while True:
            with self._lock:
                if not self._pending:
                    return
                name = self._pending.pop(0)
                k = self._kernels.get(name)
                fn = k.cost_fn if k is not None else None
            if fn is None:
                continue
            try:
                # Tag the resolver's own AOT compiles so the compile
                # monitor attributes them to cost analysis, not to a
                # serving phase (they are expected, not "recompiles").
                with phase("cost_analysis", annotate=False):
                    flops, nbytes = _cost_numbers(fn())
            except Exception as e:
                flops = nbytes = None
                err = f"{type(e).__name__}: {e}"[:200]
                logger.debug("cost_analysis failed for %s", name,
                             exc_info=True)
            else:
                err = None
            with self._lock:
                if k is not None:
                    k.flops, k.xla_bytes = flops, nbytes
                    k.cost_error = err
                    k.cost_fn = None        # drop the captured avals

    def costs_pending(self) -> int:
        with self._lock:
            return len(self._pending)

    # -- read side ----------------------------------------------------------
    def stats(self) -> dict[str, Any]:
        with self._lock:
            return {"kernel_variants": len(self._kernels),
                    "kernel_costs_pending": len(self._pending)}

    def table(self, *, bytes_per_step_fn: Callable[[str], int | None]
              | None = None, peak_gbps: float = 0.0,
              flight: list[dict] | None = None) -> list[dict[str, Any]]:
        """One row per kernel: invocation counts, measured walls (engine
        dispatch walls joined with flight-ring step records where the
        variant is identifiable), per-step HBM bytes (the engine's
        bytes-touched model via ``bytes_per_step_fn``, with the raw
        ``cost_analysis`` numbers alongside), achieved GB/s, and roofline
        fraction. Sorted by share of measured step time, largest first."""
        with self._lock:
            kernels = [(k.name, k.kind, dict(k.variant), k.calls, k.steps,
                        k.wall_ms, k.walled_steps, k.flops, k.xla_bytes)
                       for k in self._kernels.values()]
        fj = _flight_join(flight) if flight else {}
        rows: list[dict[str, Any]] = []
        effective: dict[str, float] = {}
        for (name, kind, variant, calls, steps, wall_ms, walled_steps,
             flops, xla_bytes) in kernels:
            row: dict[str, Any] = {
                "kernel": name, "kind": kind, "calls": calls,
                "steps": steps, "wall_ms": round(wall_ms, 3),
            }
            if variant:
                row.update({f"variant_{k}": v for k, v in variant.items()})
            step_ms = (wall_ms / walled_steps) if walled_steps else None
            # Flight join: the ring's decode walls are the authoritative
            # per-step measurement for decode/spec variants (recorded by
            # the scheduler with the same clock the SLO plane uses) —
            # engine-side lag-one walls only land on steady pairs, so a
            # variant that ran once still gets a measured wall here.
            fkey = (kind, variant.get("depth"))
            fw = fj.get(fkey)
            eff_wall = wall_ms
            if fw is not None and fw["steps"]:
                row["flight_steps"] = fw["steps"]
                row["flight_wall_ms"] = round(fw["wall_ms"], 3)
                step_ms = fw["wall_ms"] / fw["steps"]
                eff_wall = max(eff_wall, fw["wall_ms"])
            if step_ms is not None:
                row["step_ms"] = round(step_ms, 4)
            effective[name] = eff_wall
            if flops is not None:
                row["xla_flops_per_call"] = flops
            if xla_bytes is not None:
                row["xla_bytes_per_call"] = xla_bytes
            nbytes = None
            if bytes_per_step_fn is not None:
                nbytes = bytes_per_step_fn(kind)
            if nbytes is None and xla_bytes is not None and steps:
                # No engine model for this kind: fall back to the XLA
                # static analysis, per step of one invocation.
                per_call_steps = max(1, steps // max(1, calls))
                nbytes = xla_bytes / per_call_steps
            if nbytes is not None:
                row["hbm_bytes_per_step"] = int(nbytes)
                if step_ms:
                    gbps = nbytes / (step_ms / 1e3) / 1e9
                    row["achieved_gbps"] = round(gbps, 3)
                    if peak_gbps > 0:
                        row["roofline_fraction"] = round(gbps / peak_gbps, 3)
            rows.append(row)
        # Step-time shares over the EFFECTIVE walls (flight-joined where
        # available): what fraction of all measured device time each
        # kernel took — the ranking column of the worst-kernel pick.
        total_wall = sum(effective.values())
        if total_wall > 0:
            for row in rows:
                row["pct_of_step_time"] = round(
                    100.0 * effective[row["kernel"]] / total_wall, 1)
        rows.sort(key=lambda r: -r.get("pct_of_step_time", 0.0))
        return rows


def _flight_join(records: list[dict]) -> dict[tuple, dict]:
    """Aggregate flight STEP records by (kind, burst depth): decode walls
    and step counts per identifiable kernel variant. ``step_kind`` names
    from obs/flight.py; a mixed step's ``decode_wall_ms`` covers only its
    decode burst, so prefill interleave doesn't pollute the join."""
    out: dict[tuple, dict] = {}
    for r in records:
        if r.get("kind") != "step":
            continue
        depth = r.get("burst_depth")
        wall = r.get("decode_wall_ms")
        if not depth or not wall:
            continue
        kind = "spec" if r.get("step_kind") == "spec" else "decode"
        slot = out.setdefault((kind, depth), {"steps": 0, "wall_ms": 0.0})
        slot["steps"] += depth
        slot["wall_ms"] += wall
    return out


def worst_kernel(rows: list[dict], min_share_pct: float = 5.0
                 ) -> str | None:
    """The single kernel furthest below the HBM roof among those taking a
    meaningful share of step time — ROADMAP item 3's "next kernel
    target". Falls back to the worst fraction at any share."""
    scored = [r for r in rows if "roofline_fraction" in r]
    if not scored:
        return None
    major = [r for r in scored
             if r.get("pct_of_step_time", 0.0) >= min_share_pct]
    pick = min(major or scored, key=lambda r: r["roofline_fraction"])
    return pick["kernel"]


# ---------------------------------------------------------------------------
# Phase annotations
# ---------------------------------------------------------------------------

_phase_local = threading.local()


def current_phase() -> str:
    """The phase tag of the calling thread ("" outside any phase) — what
    the compile monitor stamps as a compile event's cause."""
    return getattr(_phase_local, "name", "")


@contextlib.contextmanager
def phase(name: str, annotate: bool = True):
    """Tag the calling thread with a scheduler phase and (when ``annotate``)
    emit a ``jax.profiler.TraceAnnotation`` so on-demand captures segment
    by phase in Perfetto. The tag always applies — compile attribution
    must work even with annotations off; the TraceAnnotation is the only
    part the ``profile_annotations`` knob (and the bench's annotation A/B
    rung) toggles."""
    prev = getattr(_phase_local, "name", "")
    _phase_local.name = name
    ctx = None
    if annotate:
        try:
            import jax.profiler
            ctx = jax.profiler.TraceAnnotation(name)
            ctx.__enter__()
        except Exception:       # profiler unavailable — tag still applies
            ctx = None
    try:
        yield
    finally:
        if ctx is not None:
            try:
                ctx.__exit__(None, None, None)
            except Exception:
                logger.debug("TraceAnnotation exit failed", exc_info=True)
        _phase_local.name = prev


# ---------------------------------------------------------------------------
# XLA compile-event monitor
# ---------------------------------------------------------------------------

# The jax.monitoring event fired once per backend (XLA) compile, with its
# wall seconds. Trace/lower phases fire their own events; backend compile
# is the expensive one and the only one that implies a new executable.
_COMPILE_EVENT = "/jax/core/compile/backend_compile_duration"


class XlaCompileMonitor:
    """Process-wide compile counters, by the phase tag active on the
    compiling thread. ``jax.monitoring`` listeners cannot be unregistered
    individually, so this is a singleton installed once per process;
    tests snapshot/diff the counters instead of resetting them."""

    def __init__(self, clock: Callable[[], float] = time.monotonic):
        self._lock = threading.Lock()
        self._clock = clock
        self._by_phase: dict[str, list] = {}     # guarded-by: _lock
        self._total = 0                          # guarded-by: _lock
        self._total_s = 0.0                      # guarded-by: _lock
        self._last: dict[str, Any] | None = None  # guarded-by: _lock

    def on_compile(self, dur_s: float) -> None:
        ph = current_phase() or "startup"
        with self._lock:
            slot = self._by_phase.setdefault(ph, [0, 0.0])
            slot[0] += 1
            slot[1] += dur_s
            self._total += 1
            self._total_s += dur_s
            self._last = {"phase": ph, "seconds": round(dur_s, 4),
                          "t": self._clock()}
        # A compile inside a serving phase is a RECOMPILE the request
        # paid for: attach it to the active trace (contextvars propagate
        # through asyncio.to_thread, so the engine's worker-thread
        # dispatches carry the request context) and log it — the silent
        # TTFT killer, made loud.
        if ph not in ("", "startup", "cost_analysis"):
            try:
                from .trace import record_span
                now = time.monotonic()
                record_span("xla.compile", layer="engine",
                            start=now - dur_s, end=now, phase=ph,
                            seconds=round(dur_s, 4))
            except Exception:
                logger.debug("compile-span attach failed", exc_info=True)
            logger.info("xla recompile during %s: %.2fs", ph, dur_s)

    def stats(self) -> dict[str, Any]:
        with self._lock:
            out: dict[str, Any] = {
                "xla_compile_total": self._total,
                "xla_compile_seconds": round(self._total_s, 4),
                "xla_compile_by_phase": {
                    ph: {"count": c, "seconds": round(s, 4)}
                    for ph, (c, s) in sorted(self._by_phase.items())},
            }
            if self._last is not None:
                out["xla_compile_last"] = dict(self._last)
            return out


_monitor: XlaCompileMonitor | None = None
_monitor_lock = threading.Lock()


def compile_monitor() -> XlaCompileMonitor:
    """The process-wide monitor (created lazily; install separately)."""
    global _monitor
    with _monitor_lock:
        if _monitor is None:
            _monitor = XlaCompileMonitor()
        return _monitor


_installed = False


def install_compile_monitor() -> XlaCompileMonitor:
    """Register the jax.monitoring listener once per process (listeners
    cannot be removed, so double-registration would double-count)."""
    global _installed
    mon = compile_monitor()
    with _monitor_lock:
        if _installed:
            return mon
        _installed = True
    try:
        from jax import monitoring

        def listener(name: str, dur_s: float, **kw) -> None:
            if name == _COMPILE_EVENT:
                mon.on_compile(dur_s)
        monitoring.register_event_duration_secs_listener(listener)
    except Exception:       # proxy-only deployment without JAX
        logger.debug("jax.monitoring unavailable; compile telemetry off",
                     exc_info=True)
    return mon
