"""Dependency-free metrics plane: Counter / Gauge / Histogram with labels,
Prometheus text-format exposition, and scrape-time collectors.

The gateway's telemetry was scattered — engine ``stats()`` dicts, breaker
snapshots, per-request logs — with no single scrapeable surface (ISSUE 4).
This module is the one registry every layer registers into; ``GET /metrics``
(server/obs_api.py) serves :meth:`MetricsRegistry.render`. No prometheus
client dependency: the text format is simple, and owning the encoder lets
tests pin the grammar exactly (tests/test_metrics.py).

Conventions (enforced by the graftlint ``metric-discipline`` rule):

* names are snake_case and end with a unit suffix — ``_seconds``,
  ``_bytes``, ``_total``, or ``_ratio``;
* latency histograms share :data:`LATENCY_BUCKETS_S` so dashboards can
  aggregate across layers.

Collectors bridge pull-model sources (engine ``stats()``, breaker
snapshots) into gauges at scrape time, so the existing roofline endpoint
and bench accounting keep reading the same underlying dicts unchanged.

Thread-safety: one lock guards registration, sample mutation, and
rendering — providers record from the event loop, but nothing stops an
operator thread from scraping concurrently, and a torn histogram (count
bumped, sum not yet) would fail the exposition-consistency tests.
"""
from __future__ import annotations

import logging
import math
import threading
from typing import Any, Callable, Iterable

logger = logging.getLogger(__name__)

# Shared latency ladder (seconds): spans SSE frame gaps (~ms) through the
# 300 s transport cap.
LATENCY_BUCKETS_S = (0.001, 0.0025, 0.005, 0.01, 0.025, 0.05, 0.1, 0.25,
                     0.5, 1.0, 2.5, 5.0, 10.0, 30.0, 60.0, 120.0, 300.0)


def _escape_label_value(value: str) -> str:
    return (value.replace("\\", r"\\").replace("\n", r"\n")
            .replace('"', r'\"'))


def _escape_help(text: str) -> str:
    return text.replace("\\", r"\\").replace("\n", r"\n")


def _format_value(value: float) -> str:
    if value == math.inf:
        return "+Inf"
    if value == -math.inf:
        return "-Inf"
    if value != value:                       # NaN
        return "NaN"
    if float(value).is_integer() and abs(value) < 1e15:
        return str(int(value))
    return repr(float(value))


def _format_labels(labelnames: tuple[str, ...], labelvalues: tuple[str, ...],
                   extra: tuple[tuple[str, str], ...] = ()) -> str:
    pairs = list(zip(labelnames, labelvalues)) + list(extra)
    if not pairs:
        return ""
    body = ",".join(f'{k}="{_escape_label_value(v)}"' for k, v in pairs)
    return "{" + body + "}"


class _Child:
    """One labeled sample of a metric (or the single sample of an unlabeled
    one). Mutation goes through the registry lock."""

    def __init__(self, lock: threading.Lock):
        self._lock = lock
        self._value = 0.0               # guarded-by: _lock

    def inc(self, amount: float = 1.0) -> None:
        with self._lock:
            self._value += amount

    def dec(self, amount: float = 1.0) -> None:
        self.inc(-amount)

    def set(self, value: float) -> None:
        with self._lock:
            self._value = float(value)

    @property
    def value(self) -> float:
        return self._value


class _CounterChild(_Child):
    def inc(self, amount: float = 1.0) -> None:
        if amount < 0:
            raise ValueError("counters only go up; use a gauge")
        super().inc(amount)


class _HistogramChild:
    def __init__(self, lock: threading.Lock, buckets: tuple[float, ...]):
        self._lock = lock
        self.buckets = buckets
        self._counts = [0] * (len(buckets) + 1)   # guarded-by: _lock (+Inf last)
        self._sum = 0.0                           # guarded-by: _lock
        self._count = 0                           # guarded-by: _lock

    def observe(self, value: float) -> None:
        with self._lock:
            for i, bound in enumerate(self.buckets):
                if value <= bound:
                    self._counts[i] += 1
                    break
            else:
                self._counts[-1] += 1
            self._sum += value
            self._count += 1

    def snapshot(self) -> tuple[list[int], float, int]:
        with self._lock:
            return list(self._counts), self._sum, self._count


class Metric:
    """One metric family: name, help, type, label schema, children."""

    kind = "untyped"

    def __init__(self, name: str, help: str, labelnames: tuple[str, ...],
                 lock: threading.Lock):
        self.name = name
        self.help = help
        self.labelnames = labelnames
        self._lock = lock
        self._children: dict[tuple[str, ...], Any] = {}   # guarded-by: _lock

    def _make_child(self):
        return _Child(self._lock)

    def labels(self, **labelvalues: str) -> Any:
        if set(labelvalues) != set(self.labelnames):
            raise ValueError(
                f"{self.name}: expected labels {self.labelnames}, "
                f"got {tuple(labelvalues)}")
        key = tuple(str(labelvalues[ln]) for ln in self.labelnames)
        with self._lock:
            child = self._children.get(key)
            if child is None:
                child = self._make_child()
                self._children[key] = child
        return child

    def _default_child(self):
        if self.labelnames:
            raise ValueError(f"{self.name} is labeled; call .labels() first")
        return self.labels()

    def children(self) -> list[tuple[tuple[str, ...], Any]]:
        """Snapshot of (labelvalues, child) pairs — what scrape-time
        collectors that DERIVE series (e.g. the SLO goodput ratio) read
        instead of reparsing the exposition."""
        with self._lock:
            return list(self._children.items())

    # Unlabeled convenience passthroughs.
    def inc(self, amount: float = 1.0) -> None:
        self._default_child().inc(amount)

    def dec(self, amount: float = 1.0) -> None:
        self._default_child().dec(amount)

    def set(self, value: float) -> None:
        self._default_child().set(value)

    def observe(self, value: float) -> None:
        self._default_child().observe(value)

    def samples(self) -> list[str]:
        lines = []
        with self._lock:
            children = sorted(self._children.items())
        for key, child in children:
            lines.append(f"{self.name}{_format_labels(self.labelnames, key)} "
                         f"{_format_value(child.value)}")
        return lines


class Counter(Metric):
    kind = "counter"

    def _make_child(self):
        return _CounterChild(self._lock)

    def set(self, value: float) -> None:
        raise TypeError("counters only inc(); use a gauge for set()")


class Gauge(Metric):
    kind = "gauge"


class Histogram(Metric):
    kind = "histogram"

    def __init__(self, name: str, help: str, labelnames: tuple[str, ...],
                 lock: threading.Lock,
                 buckets: tuple[float, ...] = LATENCY_BUCKETS_S):
        super().__init__(name, help, labelnames, lock)
        self.buckets = tuple(sorted(buckets))

    def _make_child(self):
        return _HistogramChild(self._lock, self.buckets)

    def samples(self) -> list[str]:
        lines = []
        with self._lock:
            children = sorted(self._children.items())
        for key, child in children:
            counts, total, count = child.snapshot()
            cumulative = 0
            for bound, n in zip(self.buckets, counts):
                cumulative += n
                le = _format_value(bound)
                lines.append(
                    f"{self.name}_bucket"
                    f"{_format_labels(self.labelnames, key, (('le', le),))} "
                    f"{cumulative}")
            cumulative += counts[-1]
            lines.append(
                f"{self.name}_bucket"
                f"{_format_labels(self.labelnames, key, (('le', '+Inf'),))} "
                f"{cumulative}")
            lines.append(f"{self.name}_sum"
                         f"{_format_labels(self.labelnames, key)} "
                         f"{_format_value(total)}")
            lines.append(f"{self.name}_count"
                         f"{_format_labels(self.labelnames, key)} {count}")
        return lines


class MetricsRegistry:
    """Instrument factory + exposition encoder.

    Re-registering an existing name returns the existing instrument when
    type and label schema match (layers register idempotently at import /
    construction time) and raises otherwise.
    """

    def __init__(self) -> None:
        self._lock = threading.Lock()
        self._metrics: dict[str, Metric] = {}        # guarded-by: _lock
        self._collectors: list[Callable[[], None]] = []   # guarded-by: _lock

    def _register(self, cls, name: str, help: str,
                  labelnames: Iterable[str], **kwargs) -> Any:
        labelnames = tuple(labelnames)
        with self._lock:
            existing = self._metrics.get(name)
            if existing is not None:
                if (type(existing) is not cls
                        or existing.labelnames != labelnames):
                    raise ValueError(
                        f"metric {name!r} already registered with a "
                        f"different type or label schema")
                return existing
            metric = cls(name, help, labelnames, self._lock, **kwargs)
            self._metrics[name] = metric
            return metric

    def counter(self, name: str, help: str,
                labelnames: Iterable[str] = ()) -> Counter:
        return self._register(Counter, name, help, labelnames)

    def gauge(self, name: str, help: str,
              labelnames: Iterable[str] = ()) -> Gauge:
        return self._register(Gauge, name, help, labelnames)

    def histogram(self, name: str, help: str,
                  labelnames: Iterable[str] = (),
                  buckets: tuple[float, ...] = LATENCY_BUCKETS_S) -> Histogram:
        return self._register(Histogram, name, help, labelnames,
                              buckets=buckets)

    # -- scrape-time collectors (engine stats / breaker snapshot bridges) ----
    def register_collector(self, fn: Callable[[], None]) -> None:
        with self._lock:
            if fn not in self._collectors:
                self._collectors.append(fn)

    def unregister_collector(self, fn: Callable[[], None]) -> None:
        with self._lock:
            if fn in self._collectors:
                self._collectors.remove(fn)

    def render(self) -> str:
        """The Prometheus text-format exposition (version 0.0.4)."""
        with self._lock:
            collectors = list(self._collectors)
        for fn in collectors:
            try:
                fn()
            except Exception:       # a sick engine must never break /metrics
                logger.debug("metrics collector failed", exc_info=True)
        with self._lock:
            metrics = sorted(self._metrics.values(), key=lambda m: m.name)
        out: list[str] = []
        for m in metrics:
            out.append(f"# HELP {m.name} {_escape_help(m.help)}")
            out.append(f"# TYPE {m.name} {m.kind}")
            out.extend(m.samples())
        return "\n".join(out) + "\n"


class GatewayMetrics:
    """Every instrument of the gateway's four layers, pre-registered so the
    exposition carries HELP/TYPE for the full schema from first scrape.
    Layers hold attribute references — no name lookups on the hot path."""

    def __init__(self, registry: MetricsRegistry | None = None):
        self.registry = registry or MetricsRegistry()
        r = self.registry

        # -- http (server/middleware.py) --------------------------------------
        self.http_requests_total = r.counter(
            "gateway_http_requests_total",
            "HTTP requests completed, by route template and final status.",
            ("method", "path", "status"))
        self.http_in_flight = r.gauge(
            "gateway_http_requests_in_flight_total",
            "HTTP requests currently being served.")
        self.http_request_duration_seconds = r.histogram(
            "gateway_http_request_duration_seconds",
            "End-to-end HTTP request wall time (streamed responses include "
            "the full stream drain).",
            ("method", "path"))

        # -- router (routing/router.py) ---------------------------------------
        self.router_attempts_total = r.counter(
            "gateway_router_attempts_total",
            "Provider attempts dispatched by the fallback state machine.",
            ("provider",))
        self.router_fallbacks_total = r.counter(
            "gateway_router_fallbacks_total",
            "Attempted targets that failed and were fallen past to a later "
            "target in the chain.")
        self.router_breaker_skips_total = r.counter(
            "gateway_router_breaker_skips_total",
            "Targets skipped instantly because their circuit breaker was "
            "open.",
            ("provider",))
        self.router_deadline_expired_total = r.counter(
            "gateway_router_deadline_expired_total",
            "Requests terminated 504 because their deadline budget ran out.")
        self.router_sheds_total = r.counter(
            "gateway_router_sheds_total",
            "Requests shed 429 because every target was overloaded or "
            "breaker-open.")

        # -- providers (recorded at the router call-site; covers remote_http
        #    and local uniformly) ---------------------------------------------
        self.provider_attempt_duration_seconds = r.histogram(
            "gateway_provider_attempt_duration_seconds",
            "Wall time of one provider attempt up to commit (remote: SSE "
            "priming; local: first token).",
            ("provider",))
        self.provider_errors_total = r.counter(
            "gateway_provider_errors_total",
            "Failed provider attempts by error kind (timeout / overload / "
            "http / error).",
            ("provider", "kind"))
        self.provider_timeouts_total = r.counter(
            "gateway_provider_timeouts_total",
            "Provider attempts that hit their deadline-capped transport "
            "timeout.",
            ("provider",))
        self.provider_breaker_open_ratio = r.gauge(
            "gateway_provider_breaker_open_ratio",
            "Circuit-breaker state per provider: 0 closed, 0.5 half-open, "
            "1 open.",
            ("provider",))
        self.provider_breaker_opens_total = r.gauge(
            "gateway_provider_breaker_opens_total",
            "Lifetime open transitions per provider breaker.",
            ("provider",))

        # -- engine (providers/local.py records; gauges bridge stats()) -------
        self.engine_ttft_seconds = r.histogram(
            "gateway_engine_ttft_seconds",
            "Local-engine time to first token (submit to first sampled "
            "token).",
            ("engine",))
        self.engine_time_between_tokens_seconds = r.histogram(
            "gateway_engine_time_between_tokens_seconds",
            "Gap between consecutive streamed deltas from the local engine.",
            ("engine",))
        self.engine_running_requests_total = r.gauge(
            "gateway_engine_running_requests_total",
            "Requests holding an engine slot.", ("engine",))
        self.engine_queued_requests_total = r.gauge(
            "gateway_engine_queued_requests_total",
            "Requests waiting for engine admission.", ("engine",))
        self.engine_free_slots_total = r.gauge(
            "gateway_engine_free_slots_total",
            "Free decode slots.", ("engine",))
        self.engine_queue_wait_seconds = r.gauge(
            "gateway_engine_queue_wait_seconds",
            "EMA of submit-to-admission wait.", ("engine",))
        self.engine_decode_step_seconds = r.gauge(
            "gateway_engine_decode_step_seconds",
            "Measured per-step decode time (EMA over steady bursts).",
            ("engine",))
        self.engine_sheds_total = r.gauge(
            "gateway_engine_sheds_total",
            "Admissions refused on a full queue (gateway mapped to 429).",
            ("engine",))
        self.engine_burst_clamps_total = r.gauge(
            "gateway_engine_burst_clamps_total",
            "Busy decode bursts clamped below decode_burst_busy by the "
            "prefill-aware TTFT cap.", ("engine",))
        self.engine_kv_free_pages_total = r.gauge(
            "gateway_engine_kv_free_pages_total",
            "Free pages in the paged-KV pool.", ("engine",))
        # Radix prefix cache (ISSUE 6). Monotonic engine-side totals are
        # bridged as gauges like engine_sheds_total (the engine owns the
        # counter; scrape-time set() keeps restarts honest).
        self.engine_prefix_cache_hit_total = r.gauge(
            "gateway_engine_prefix_cache_hit_total",
            "Admitted requests whose prompt prefix was served from the "
            "radix KV cache.", ("engine",))
        self.engine_prefix_cache_miss_total = r.gauge(
            "gateway_engine_prefix_cache_miss_total",
            "Admitted requests with no resident prompt prefix.",
            ("engine",))
        self.engine_prefix_cached_tokens_total = r.gauge(
            "gateway_engine_prefix_cached_tokens_total",
            "Prompt tokens whose prefill was skipped via the radix KV "
            "cache.", ("engine",))
        self.engine_prefix_resident_pages_total = r.gauge(
            "gateway_engine_prefix_resident_pages_total",
            "KV pages currently pinned by the radix prefix cache.",
            ("engine",))
        self.engine_prefix_pinned_refs_total = r.gauge(
            "gateway_engine_prefix_pinned_refs_total",
            "In-flight request references pinning resident prefix blocks "
            "against eviction.", ("engine",))
        self.engine_kv_occupancy_ratio = r.gauge(
            "gateway_engine_kv_occupancy_ratio",
            "Paged-KV pool occupancy (allocated / allocatable).", ("engine",))
        # Speculative-decoding acceptance telemetry (ROADMAP item 3 stub;
        # ISSUE 7 satellite): bridged from the engine's spec_proposed /
        # spec_accepted stats like the prefix-cache totals.
        self.engine_spec_proposed_total = r.gauge(
            "gateway_engine_spec_proposed_total",
            "Draft tokens proposed by speculative decoding.", ("engine",))
        self.engine_spec_accepted_total = r.gauge(
            "gateway_engine_spec_accepted_total",
            "Draft tokens accepted by the verify forward.", ("engine",))
        self.engine_spec_acceptance_ratio = r.gauge(
            "gateway_engine_spec_acceptance_ratio",
            "Accepted over proposed draft tokens (lifetime).", ("engine",))
        # Per-slot adaptive drafting (spec_acceptance_floor): how many
        # slots are currently benched, plus each measured slot's live
        # EMA-derived acceptance ratio — the quantity the floor compares
        # against ((ema - 1) / k, in [0, 1]).
        self.engine_spec_suspended_slots = r.gauge(
            "gateway_engine_spec_suspended_slots_total",
            "Slots with drafting suspended by spec_acceptance_floor.",
            ("engine",))
        self.engine_spec_slot_acceptance_ratio = r.gauge(
            "gateway_engine_spec_slot_acceptance_ratio",
            "Per-slot EMA acceptance ratio ((ema-1)/k) feeding the "
            "adaptive drafting floor.", ("engine", "slot"))
        # Flight recorder (ISSUE 7): ring position and wrap loss.
        self.engine_flight_ring_evicted_total = r.gauge(
            "gateway_engine_flight_ring_evicted_total",
            "Flight-recorder records lost to ring wrap.", ("engine",))
        # Engine supervision (ISSUE 14): lifecycle + restart telemetry.
        self.engine_supervisor_state_ratio = r.gauge(
            "gateway_engine_supervisor_state_ratio",
            "Engine lifecycle state: 0 serving, 0.25 starting, 0.5 "
            "draining, 0.75 restarting, 0.9 stopped, 1 failed.",
            ("engine",))
        self.engine_supervisor_restarts_total = r.gauge(
            "gateway_engine_supervisor_restarts_total",
            "Supervised engine restarts since the last healthy stretch "
            "(resets after sustained clean serving).", ("engine",))
        self.engine_supervisor_heartbeat_age_seconds = r.gauge(
            "gateway_engine_supervisor_heartbeat_age_seconds",
            "Seconds since the scheduler loop last stamped its "
            "heartbeat.", ("engine",))
        self.engine_supervisor_backoff_seconds = r.gauge(
            "gateway_engine_supervisor_backoff_seconds",
            "Backoff the NEXT supervised restart attempt would wait.",
            ("engine",))

        # Write-behind usage recorder (ISSUE 14; db/recorder.py).
        self.usage_recorder_queued = r.gauge(
            "gateway_usage_recorder_queued_total",
            "Usage rows waiting in the write-behind queue.")
        self.usage_recorder_flushed_total = r.gauge(
            "gateway_usage_recorder_flushed_total",
            "Usage rows flushed to the ledger by the background "
            "recorder.")
        self.usage_recorder_dropped_total = r.gauge(
            "gateway_usage_recorder_dropped_total",
            "Usage rows dropped because the write-behind queue was "
            "full.")

        # -- HBM memory ledger (ISSUE 8; obs/device.py). Static accounting
        #    from checkpoint dtypes + cache geometry, the live buffers'
        #    metadata bytes, and the runtime allocator's own view where
        #    the backend exposes one (TPU; CPU reports none). -------------
        self.engine_hbm_weights_bytes = r.gauge(
            "gateway_engine_hbm_weights_bytes",
            "Resident parameter bytes (scales included) per the ledger.",
            ("engine",))
        self.engine_hbm_kv_pool_bytes = r.gauge(
            "gateway_engine_hbm_kv_pool_bytes",
            "KV-pool bytes from page geometry × cache dtype (incl. int8 "
            "scale planes).", ("engine",))
        self.engine_hbm_aux_bytes = r.gauge(
            "gateway_engine_hbm_aux_bytes",
            "Auxiliary device buffers: penalty counts, page table.",
            ("engine",))
        self.engine_hbm_spec_bytes = r.gauge(
            "gateway_engine_hbm_spec_bytes",
            "Speculative-decoding device buffers (token-history twin).",
            ("engine",))
        self.engine_hbm_ledger_bytes = r.gauge(
            "gateway_engine_hbm_ledger_bytes",
            "Total bytes the ledger expects resident (weights + KV pool "
            "+ aux + spec).", ("engine",))
        self.engine_hbm_tracked_bytes = r.gauge(
            "gateway_engine_hbm_tracked_bytes",
            "Bytes the engine's live device buffers actually occupy "
            "(array metadata; reconciles against the ledger).",
            ("engine",))
        self.engine_hbm_prefix_resident_bytes = r.gauge(
            "gateway_engine_hbm_prefix_resident_bytes",
            "KV-pool bytes held by radix-prefix-cache resident pages.",
            ("engine",))
        self.engine_hbm_device_in_use_bytes = r.gauge(
            "gateway_engine_hbm_device_in_use_bytes",
            "Runtime allocator bytes_in_use summed over the engine's "
            "local devices.", ("engine",))
        self.engine_hbm_device_peak_bytes = r.gauge(
            "gateway_engine_hbm_device_peak_bytes",
            "Runtime allocator peak_bytes_in_use summed over the "
            "engine's local devices.", ("engine",))
        self.engine_hbm_device_limit_bytes = r.gauge(
            "gateway_engine_hbm_device_limit_bytes",
            "Runtime allocator bytes_limit summed over the engine's "
            "local devices.", ("engine",))
        self.engine_hbm_headroom_ratio = r.gauge(
            "gateway_engine_hbm_headroom_ratio",
            "Free fraction of the device memory limit (the watermark "
            "shed threshold compares against this).", ("engine",))
        self.engine_watermark_sheds_total = r.gauge(
            "gateway_engine_watermark_sheds_total",
            "Admissions shed because device memory headroom fell below "
            "the configured watermark.", ("engine",))
        # XLA compile telemetry (ISSUE 8): process-wide monitor bridged
        # at scrape time; a compile during a serving phase is a
        # recompile some request paid for.
        self.engine_xla_compile_total = r.gauge(
            "gateway_engine_xla_compile_total",
            "Backend (XLA) compiles observed in this process, by the "
            "scheduler phase that triggered them (startup = engine "
            "build / prewarm; cost_analysis = the kernel registry's own "
            "AOT lowers).", ("phase",))
        self.engine_xla_compile_seconds = r.gauge(
            "gateway_engine_xla_compile_seconds",
            "Cumulative backend-compile wall seconds, by phase.",
            ("phase",))

        # -- SLO / goodput attribution plane (ISSUE 7; obs/slo.py) ------------
        self.slo_met_total = r.counter(
            "gateway_slo_met_total",
            "Requests that met every SLO target they carried.",
            ("engine",))
        self.slo_violated_total = r.counter(
            "gateway_slo_violated_total",
            "Requests that violated an SLO target, by attributed phase "
            "(queued / prefill / decode_contention / decode).",
            ("engine", "phase"))
        self.slo_goodput_ratio = r.gauge(
            "gateway_slo_goodput_ratio",
            "Fraction of SLO-carrying requests that met their targets "
            "(the DistServe goodput numerator over its denominator).",
            ("engine",))
        self.trace_ring_evicted_total = r.gauge(
            "gateway_trace_ring_evicted_total",
            "Request traces pushed out of the trace ring buffer.")
        self.engine_step_hbm_bytes = r.gauge(
            "gateway_engine_step_hbm_bytes",
            "HBM bytes one decode step must stream (weights + live KV).",
            ("engine",))
        self.engine_hbm_bandwidth_bytes = r.gauge(
            "gateway_engine_hbm_bandwidth_bytes",
            "Achieved HBM bandwidth in bytes per second at the measured "
            "step time.", ("engine",))
        self.engine_roofline_ratio = r.gauge(
            "gateway_engine_roofline_ratio",
            "Achieved bandwidth over the configured HBM peak.", ("engine",))

        # -- disaggregated serving plane (ISSUE 13; engine/disagg.py) ---------
        self.engine_pool_slots_total = r.gauge(
            "gateway_engine_pool_slots_total",
            "Batch slots owned by a scheduler pool.", ("engine", "pool"))
        self.engine_pool_free_slots_total = r.gauge(
            "gateway_engine_pool_free_slots_total",
            "Free slots in a scheduler pool.", ("engine", "pool"))
        self.engine_pool_running_total = r.gauge(
            "gateway_engine_pool_running_total",
            "Requests resident in a scheduler pool.", ("engine", "pool"))
        self.engine_pool_admits_total = r.gauge(
            "gateway_engine_pool_admits_total",
            "Admissions placed into a scheduler pool.", ("engine", "pool"))
        self.engine_pool_sheds_total = r.gauge(
            "gateway_engine_pool_sheds_total",
            "Goodput-admission sheds attributed to a pool's predicted "
            "miss.", ("engine", "pool"))
        self.engine_pool_predicted_ttft_seconds = r.gauge(
            "gateway_engine_pool_predicted_ttft_seconds",
            "Admission controller's predicted TTFT through the prefill "
            "pool.", ("engine", "pool"))
        self.engine_pool_predicted_tpot_seconds = r.gauge(
            "gateway_engine_pool_predicted_tpot_seconds",
            "Admission controller's predicted per-token time through the "
            "decode pool.", ("engine", "pool"))
        self.engine_pool_occupancy_ratio = r.gauge(
            "gateway_engine_pool_occupancy_ratio",
            "Fraction of the occupancy window spent in the pool's "
            "dispatches (flight-ring derived).", ("engine", "pool"))
        self.engine_disagg_handoffs_total = r.gauge(
            "gateway_engine_disagg_handoffs_total",
            "Prefill-to-decode KV handoffs (zero-copy refcount "
            "transfers).", ("engine",))
        self.engine_disagg_handoff_pages_total = r.gauge(
            "gateway_engine_disagg_handoff_pages_total",
            "KV pages whose ownership moved across a handoff without a "
            "device copy.", ("engine",))
        self.engine_disagg_clamps_total = r.gauge(
            "gateway_engine_disagg_clamps_total",
            "Admissions flagged TTFT-at-risk (clamped) instead of shed.",
            ("engine",))
        self.slo_pool_met_total = r.counter(
            "gateway_slo_pool_met_total",
            "SLO-met requests by the pool that served their decode.",
            ("engine", "pool"))
        self.slo_pool_violated_total = r.counter(
            "gateway_slo_pool_violated_total",
            "SLO-violating requests by the pool that served their "
            "decode.", ("engine", "pool"))
        self.slo_pool_goodput_ratio = r.gauge(
            "gateway_slo_pool_goodput_ratio",
            "Per-pool goodput: met over (met + violated) for requests "
            "the pool decoded — the pooled-vs-unified scoreboard.",
            ("engine", "pool"))

    def render(self) -> str:
        return self.registry.render()


_default: GatewayMetrics | None = None
_default_lock = threading.Lock()


def get_metrics() -> GatewayMetrics:
    """The process-wide instrument set. Layers built outside the app wiring
    (the local provider factory) record here; GatewayApp serves it."""
    global _default
    with _default_lock:
        if _default is None:
            _default = GatewayMetrics()
        return _default
