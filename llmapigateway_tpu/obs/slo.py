"""Per-request SLO targets, outcomes, and violation attribution (ISSUE 7).

*DistServe*-style goodput routing (ROADMAP item 2) admits by per-request
TTFT/TPOT SLO instead of a single queue, and the sustained-load harness
(item 5) needs a goodput number to assert — both need the gateway to know,
per request, whether its latency targets were met and *why not* when they
weren't. This module is that substrate:

* :class:`SLOTargets` — a request's TTFT/TPOT targets, from the
  ``x-slo-ttft-ms`` / ``x-slo-tpot-ms`` headers (client ask wins) or the
  gateway model rule's ``slo_ttft_ms`` / ``slo_tpot_ms`` fields
  (config/schemas.py), mirroring the deadline-budget precedence chain.
* :func:`evaluate` — the outcome, computed at stream end from the
  GenRequest timestamps PR 4 already records (submit / admitted /
  first-token / done), with a TTFT violation *attributed* to the phase
  that actually spent the budget: ``queued`` (waiting for a slot),
  ``prefill`` (the prompt's own compute), or ``decode_contention``
  (decode bursts interleaving with the request's prefill window —
  measured from the flight recorder's step records, not guessed).

Outcomes feed three sinks: ``gateway_slo_{met,violated}_total`` counters
plus the goodput gauge on ``/metrics`` (providers/local.py records,
server/obs_api.py derives), the usage DB row (``slo_met`` /
``slo_phase`` columns), and the request's final usage frame.
"""
from __future__ import annotations

from dataclasses import dataclass
from typing import Any

from .flight import F_DECODE, FlightRecorder

# Attribution phases for a TTFT violation, in the order the budget is
# spent: slot wait, then prompt compute, with decode bursts possibly
# stealing the window in between. TPOT violations are always "decode".
PHASE_QUEUED = "queued"
PHASE_PREFILL = "prefill"
PHASE_DECODE_CONTENTION = "decode_contention"
PHASE_DECODE = "decode"

VIOLATION_PHASES = (PHASE_QUEUED, PHASE_PREFILL,
                    PHASE_DECODE_CONTENTION, PHASE_DECODE)


@dataclass(frozen=True)
class SLOTargets:
    """A request's latency targets in milliseconds; None = no target."""
    ttft_ms: float | None = None
    tpot_ms: float | None = None

    @property
    def defined(self) -> bool:
        return self.ttft_ms is not None or self.tpot_ms is not None


def _positive_ms(raw: Any) -> float | None:
    try:
        val = float(raw)
    except (TypeError, ValueError):
        return None
    return val if val > 0 else None


def slo_from_headers(headers: Any) -> SLOTargets:
    """Parse the client's SLO ask. Invalid / non-positive values are
    ignored (a malformed SLO header must not fail the request — it only
    shapes attribution, never admission)."""
    return SLOTargets(
        ttft_ms=_positive_ms(headers.get("x-slo-ttft-ms")),
        tpot_ms=_positive_ms(headers.get("x-slo-tpot-ms")))


def resolve_slo(header_slo: SLOTargets | None, rule: Any) -> SLOTargets:
    """Per-field precedence: client header > gateway-model rule config.
    ``rule`` is a ModelFallbackConfig (or None); its 0-valued fields mean
    unset, mirroring ``timeout_ms``."""
    h = header_slo or SLOTargets()
    rule_ttft = _positive_ms(getattr(rule, "slo_ttft_ms", 0) or 0)
    rule_tpot = _positive_ms(getattr(rule, "slo_tpot_ms", 0) or 0)
    return SLOTargets(ttft_ms=h.ttft_ms if h.ttft_ms is not None
                      else rule_ttft,
                      tpot_ms=h.tpot_ms if h.tpot_ms is not None
                      else rule_tpot)


def evaluate(req: Any, slo: SLOTargets,
             flight: FlightRecorder | None = None) -> dict[str, Any] | None:
    """SLO outcome for one finished engine request.

    ``req`` is a GenRequest whose lifecycle timestamps are populated
    (t_submit always; t_admitted/t_first_token/t_done when the request
    got that far). Returns None when no target is defined; otherwise a
    dict carrying the targets, the measured values, ``met``, and — on a
    violation — the attributed ``phase`` plus the per-phase breakdown
    the attribution was computed from.
    """
    if not slo.defined:
        return None
    out: dict[str, Any] = {}
    if slo.ttft_ms is not None:
        out["ttft_target_ms"] = slo.ttft_ms
    if slo.tpot_ms is not None:
        out["tpot_target_ms"] = slo.tpot_ms

    ttft_ms = None
    if req.t_first_token is not None:
        ttft_ms = 1000.0 * (req.t_first_token - req.t_submit)
        out["ttft_ms"] = round(ttft_ms, 2)
    tpot_ms = None
    n_gen = len(req.generated)
    if (req.t_first_token is not None and req.t_done is not None
            and n_gen > 1 and req.t_done > req.t_first_token):
        tpot_ms = 1000.0 * (req.t_done - req.t_first_token) / (n_gen - 1)
        out["tpot_ms"] = round(tpot_ms, 3)

    phase = None
    if slo.ttft_ms is not None and (
            ttft_ms is None or ttft_ms > slo.ttft_ms):
        # TTFT violated (a request that never produced a token counts as
        # violated — the budget was spent with nothing to show). Split
        # the window: queued = submit → admission; the admission →
        # first-token span is prefill, minus whatever of it the flight
        # recorder shows was spent inside decode bursts (the interleave
        # tax the burst clamp exists to bound).
        t_admit = req.t_admitted
        t_first = req.t_first_token
        end = t_first if t_first is not None else (
            req.t_done if req.t_done is not None else None)
        queued_ms = (1000.0 * (t_admit - req.t_submit)
                     if t_admit is not None
                     else (1000.0 * (end - req.t_submit) if end else 0.0))
        prefill_ms = (1000.0 * (end - t_admit)
                      if t_admit is not None and end is not None
                      and end > t_admit else 0.0)
        contention_ms = 0.0
        if flight is not None and t_admit is not None and end is not None:
            contention_ms = min(prefill_ms, flight.steps_overlapping(
                t_admit, end, flag_mask=F_DECODE))
        compute_ms = max(0.0, prefill_ms - contention_ms)
        shares = ((queued_ms, PHASE_QUEUED),
                  (compute_ms, PHASE_PREFILL),
                  (contention_ms, PHASE_DECODE_CONTENTION))
        phase = max(shares)[1]
        out["attribution"] = {
            "queued_ms": round(queued_ms, 2),
            "prefill_ms": round(compute_ms, 2),
            "decode_contention_ms": round(contention_ms, 2),
        }
    elif slo.tpot_ms is not None and tpot_ms is not None \
            and tpot_ms > slo.tpot_ms:
        phase = PHASE_DECODE

    out["met"] = phase is None
    if phase is not None:
        out["phase"] = phase
    return out
