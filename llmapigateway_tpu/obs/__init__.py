"""Unified observability plane (ISSUE 4): the metrics registry serving
``GET /metrics`` and the request tracer serving
``GET /v1/api/trace/{request_id}``. Dependency-free by design — importable
from every layer (middleware, router, providers, engine bridges) without
pulling in JAX or HTTP stacks."""
from .metrics import (
    LATENCY_BUCKETS_S,
    Counter,
    Gauge,
    GatewayMetrics,
    Histogram,
    MetricsRegistry,
    get_metrics,
)
from .trace import (
    Span,
    Tracer,
    current_request_id,
    current_span,
    current_trace,
    record_span,
    server_timing_header,
    span,
)

__all__ = [
    "LATENCY_BUCKETS_S", "Counter", "Gauge", "GatewayMetrics", "Histogram",
    "MetricsRegistry", "get_metrics",
    "Span", "Tracer", "current_request_id", "current_span", "current_trace",
    "record_span", "server_timing_header", "span",
]
