"""End-to-end request tracing: per-request span trees via ``contextvars``.

One request becomes one tree — gateway root → router attempt N → provider
call → engine slot phases (queued / prefill / first-token / decode /
drain) — answering "where did request X spend its 742 ms" from a single
``GET /v1/api/trace/{request_id}`` read instead of four correlated log
streams (ISSUE 4). Design:

* The logging middleware opens the root span for the request's lifetime
  (its ``finally`` closes it even when a handler raises mid-stream), and
  every layer nests under whatever span is current in its context.
* Spans are opened ONLY through the :func:`span` context manager — the
  graftlint ``metric-discipline`` rule forbids bare :func:`begin_span`
  calls outside this module, so a span cannot leak unclosed past an
  exception.
* Layers that measure time outside the request task (the engine loop)
  report post-hoc through :func:`record_span` with explicit
  ``time.monotonic`` timestamps — the default tracer clock — against a
  parent captured while their provider call was current.
* Finished (and in-flight) traces live in a bounded in-process ring
  buffer; no exporter, no sampling — the newest ``capacity`` requests are
  queryable, which is what an operator chasing a live latency anomaly
  needs.

Without an active trace every API here is a no-op, so unit tests (and the
engine bench) never pay for or depend on tracing.
"""
from __future__ import annotations

import contextvars
import re
import time
from collections import OrderedDict
from contextlib import contextmanager
from dataclasses import dataclass, field
from typing import Any, Callable, Iterator

DEFAULT_CAPACITY = 256

_SERVER_TIMING_SAFE = re.compile(r"[^A-Za-z0-9_]")
_MAX_TIMING_ENTRIES = 16


@dataclass
class Span:
    """One timed operation. ``end is None`` means still open (a finished
    trace with an open non-root span is a leak — the chaos tests assert
    there are none)."""
    name: str
    layer: str
    start: float
    attrs: dict[str, Any] = field(default_factory=dict)
    end: float | None = None
    children: list["Span"] = field(default_factory=list)
    _clock: Callable[[], float] = field(default=time.monotonic, repr=False)

    def duration_ms(self) -> float | None:
        if self.end is None:
            return None
        return (self.end - self.start) * 1000.0

    def to_dict(self, epoch: float) -> dict[str, Any]:
        d: dict[str, Any] = {
            "name": self.name, "layer": self.layer,
            "start_ms": round((self.start - epoch) * 1000.0, 3),
            "duration_ms": (round(self.duration_ms(), 3)
                            if self.end is not None else None)}
        if self.attrs:
            d["attrs"] = dict(self.attrs)
        if self.children:
            d["children"] = [c.to_dict(epoch) for c in self.children]
        return d

    def walk(self) -> Iterator["Span"]:
        yield self
        for child in self.children:
            yield from child.walk()


class RequestTrace:
    """The span tree of one request."""

    def __init__(self, request_id: str, clock: Callable[[], float]):
        self.request_id = request_id
        self.clock = clock
        self.root = Span("gateway", "gateway", clock(), _clock=clock)

    def to_dict(self) -> dict[str, Any]:
        return {"request_id": self.request_id,
                "complete": self.root.end is not None,
                "spans": self.root.to_dict(self.root.start)}


class Tracer:
    """Ring buffer of recent request traces. Event-loop confined (the
    middleware is the only writer of the buffer itself)."""

    def __init__(self, capacity: int = DEFAULT_CAPACITY,
                 clock: Callable[[], float] = time.monotonic):
        self.capacity = capacity
        self._clock = clock
        self._traces: "OrderedDict[str, RequestTrace]" = OrderedDict()
        # Traces pushed out by ring wrap — bridged to the
        # gateway_trace_ring_evicted_total series so trace loss under
        # load is a reading, not a surprise 404 (ISSUE 7 satellite).
        self.evicted_total = 0

    @contextmanager
    def trace(self, request_id: str) -> Iterator[RequestTrace]:
        """Open the root span for one request; queryable immediately (an
        in-flight request reports ``complete: false``)."""
        tr = RequestTrace(request_id, self._clock)
        self._traces[request_id] = tr
        self._traces.move_to_end(request_id)
        while len(self._traces) > self.capacity:
            self._traces.popitem(last=False)
            self.evicted_total += 1
        tok_trace = _trace_var.set(tr)
        tok_span = _span_var.set(tr.root)
        try:
            yield tr
        finally:
            tr.root.end = self._clock()
            _span_var.reset(tok_span)
            _trace_var.reset(tok_trace)

    def get(self, request_id: str) -> dict[str, Any] | None:
        tr = self._traces.get(request_id)
        return tr.to_dict() if tr is not None else None

    def __len__(self) -> int:
        return len(self._traces)


_trace_var: contextvars.ContextVar[RequestTrace | None] = \
    contextvars.ContextVar("gateway_trace", default=None)
_span_var: contextvars.ContextVar[Span | None] = \
    contextvars.ContextVar("gateway_span", default=None)


def current_trace() -> RequestTrace | None:
    return _trace_var.get()


def current_span() -> Span | None:
    return _span_var.get()


def current_request_id() -> str | None:
    """The active trace's request id — what outbound provider calls
    propagate upstream as ``x-request-id``."""
    tr = _trace_var.get()
    return tr.request_id if tr is not None else None


def begin_span(name: str, layer: str = "gateway",
               parent: Span | None = None, **attrs: Any) -> Span | None:
    """Low-level span open. Application code MUST use :func:`span` (the
    graftlint metric-discipline rule rejects bare ``begin_span(`` calls
    outside this module); this exists so the context manager and
    :func:`record_span` share one attach path."""
    tr = _trace_var.get()
    if tr is None:
        return None
    if parent is None:
        parent = _span_var.get() or tr.root
    sp = Span(name, layer, tr.clock(), dict(attrs), _clock=tr.clock)
    parent.children.append(sp)
    return sp


def end_span(sp: Span | None) -> None:
    if sp is not None and sp.end is None:
        sp.end = sp._clock()


@contextmanager
def span(name: str, layer: str = "gateway", **attrs: Any) -> Iterator[Span | None]:
    """Open a child span of the current context's span for the duration of
    the ``with`` block. No-op (yields None) without an active trace."""
    sp = begin_span(name, layer, **attrs)
    if sp is None:
        yield None
        return
    tok = _span_var.set(sp)
    try:
        yield sp
    finally:
        end_span(sp)
        _span_var.reset(tok)


def record_span(name: str, layer: str = "gateway",
                start: float | None = None, end: float | None = None,
                parent: Span | None = None, **attrs: Any) -> Span | None:
    """Attach an already-finished span (post-hoc measurement, e.g. engine
    phases timed by the scheduler loop). ``start``/``end`` are absolute
    timestamps in the tracer's clock domain (``time.monotonic`` by
    default); omitted ones default to now — so a bare call records a
    zero-length event marker."""
    tr = _trace_var.get()
    if tr is None and parent is None:
        return None
    clock = tr.clock if tr is not None else parent._clock
    now = clock()
    sp = Span(name, layer, start if start is not None else now,
              dict(attrs), end=end if end is not None else now,
              _clock=clock)
    if parent is None:
        parent = _span_var.get() or tr.root
    parent.children.append(sp)
    return sp


def server_timing_header(max_entries: int = _MAX_TIMING_ENTRIES) -> str:
    """Summarize the current trace as a ``Server-Timing``-style value for
    the ``x-gateway-timings`` response header: ``name;dur=ms`` entries in
    tree order (root first as ``total``), closed spans only."""
    tr = _trace_var.get()
    if tr is None:
        return ""
    entries = []
    root_dur = tr.root.duration_ms()
    if root_dur is None:                    # header built before root close
        root_dur = (tr.clock() - tr.root.start) * 1000.0
    entries.append(f"total;dur={root_dur:.1f}")
    for sp in tr.root.walk():
        if sp is tr.root or sp.end is None:
            continue
        name = _SERVER_TIMING_SAFE.sub("_", sp.name)
        entries.append(f"{name};dur={sp.duration_ms():.1f}")
        if len(entries) >= max_entries:
            break
    return ", ".join(entries)
