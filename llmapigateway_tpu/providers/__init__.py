from .base import (
    Provider,
    CompletionError,
    JSONCompletion,
    StreamingCompletion,
    CompletionResult,
    UsageObserver,
)
from .remote_http import RemoteHTTPProvider

__all__ = [
    "Provider",
    "CompletionError",
    "JSONCompletion",
    "StreamingCompletion",
    "CompletionResult",
    "UsageObserver",
    "RemoteHTTPProvider",
]
