"""Remote OpenAI-compatible HTTP provider over httpx.

Re-derivation of the one subtle reference algorithm worth keeping
(``services/request_handler.py:27-152``): **first-frame priming** — when
streaming, consume upstream SSE frames until the first *real* data frame
before committing to a 200 streaming response, so in-band upstream errors
(which many vendors send inside an SSE body with HTTP 200) still trigger
fallback. Differences by design:

* One pooled ``httpx.AsyncClient`` per provider (keep-alive), not a fresh
  client per call (reference ``request_handler.py:15`` — a latency tax).
* SSE frames are parsed exactly once (:class:`~..utils.sse.SSEParser`);
  usage/content capture happens via the :class:`UsageObserver` the router
  passes in, not by a second parse in middleware (SURVEY.md §3.2).
* Mid-stream error frames abort the stream and are reported to the observer;
  usage frames are captured from the same parse.
"""
from __future__ import annotations

import logging
from typing import Any, AsyncIterator

import httpx

from ..obs import trace as obs_trace
from ..reliability.deadline import Deadline
from ..utils.sse import SSE_DONE, SSEParser, format_sse, frame_error_detail
from .base import (
    CompletionError,
    CompletionRequest,
    CompletionResult,
    JSONCompletion,
    Provider,
    StreamingCompletion,
    UsageObserver,
)

logger = logging.getLogger(__name__)

# Reference timeouts: 300 s total / 60 s connect (request_handler.py:15).
DEFAULT_TIMEOUT = httpx.Timeout(300.0, connect=60.0)
MODELS_TIMEOUT = httpx.Timeout(60.0, connect=10.0)


def deadline_timeout(deadline: Deadline | None) -> httpx.Timeout:
    """The per-attempt httpx timeout, capped by the request's remaining
    deadline budget (reliability layer, ISSUE 3): an attempt may never
    outlive the end-to-end budget the client asked for. With no deadline
    the reference's 300 s / 60 s caps apply unchanged. An already-expired
    deadline gets a tiny positive timeout so httpx raises a normal
    ``TimeoutException`` (classified kind="timeout") instead of an
    assertion deep in the transport."""
    if deadline is None:
        return DEFAULT_TIMEOUT
    remaining = max(0.001, deadline.remaining())
    return httpx.Timeout(min(300.0, remaining), connect=min(60.0, remaining))


def _extract_content_delta(obj: dict[str, Any]) -> str:
    """Pull the assistant text delta out of a chat.completion(.chunk) frame
    (cf. chat_logging.py:124-133: delta.content or message.content)."""
    try:
        choices = obj.get("choices")
        if not choices:
            return ""
        ch = choices[0]
        delta = ch.get("delta") or {}
        msg = ch.get("message") or {}
        return delta.get("content") or msg.get("content") or ""
    except (AttributeError, IndexError, TypeError):
        return ""


class RemoteHTTPProvider(Provider):
    type = "remote_http"

    def __init__(self, name: str, base_url: str, api_key: str | None = None,
                 client: httpx.AsyncClient | None = None):
        self.name = name
        self.base_url = base_url.rstrip("/")
        self.api_key = api_key
        self._client = client or httpx.AsyncClient(timeout=DEFAULT_TIMEOUT)

    def _headers(self, extra: dict[str, str]) -> dict[str, str]:
        headers = {"Content-Type": "application/json"}
        if self.api_key:
            headers["Authorization"] = f"Bearer {self.api_key}"
        headers.update(extra)
        if "x-request-id" not in {k.lower() for k in headers}:
            # Propagate the gateway request id upstream (ISSUE 4). The
            # router already stamps routed attempts; this covers direct
            # provider calls (e.g. /v1/models aggregation) made while a
            # request trace is active.
            req_id = obs_trace.current_request_id()
            if req_id:
                headers["x-request-id"] = req_id
        return headers

    async def complete(self, request: CompletionRequest,
                       observer: UsageObserver) -> CompletionResult:
        url = f"{self.base_url}/chat/completions"
        headers = self._headers(request.extra_headers)
        timeout = deadline_timeout(request.deadline)
        try:
            if request.stream:
                return await self._complete_streaming(
                    url, headers, request.payload, observer, timeout)
            return await self._complete_json(
                url, headers, request.payload, observer, timeout)
        except httpx.TimeoutException as e:
            # Deadline-capped attempts land here; the router's budget check
            # decides whether this terminates the whole request (504).
            return None, CompletionError(
                f"timeout contacting {self.name}: {type(e).__name__}",
                kind="timeout")
        except httpx.HTTPError as e:
            return None, CompletionError(f"network error contacting {self.name}: {e}")
        except Exception as e:        # contract: never raise into the fallback loop
            logger.exception("unexpected provider failure (%s)", self.name)
            return None, CompletionError(f"provider {self.name} failed: {e}")

    # -- non-streaming -------------------------------------------------------
    async def _complete_json(self, url: str, headers: dict[str, str],
                             payload: dict[str, Any],
                             observer: UsageObserver,
                             timeout: httpx.Timeout) -> CompletionResult:
        resp = await self._client.post(url, json=payload, headers=headers,
                                       timeout=timeout)
        if resp.status_code >= 400:
            return None, CompletionError(
                resp.text[:2000], status=resp.status_code)
        try:
            data = resp.json()
        except ValueError:
            return None, CompletionError(
                f"non-JSON response from {self.name}: {resp.text[:500]}")
        # In-band error with HTTP 200 (request_handler.py:160-172).
        detail = frame_error_detail(data)
        if detail is not None:
            return None, CompletionError(detail, status=resp.status_code)
        observer.on_first_token()
        observer.on_content_delta(_extract_content_delta(data))
        if isinstance(data.get("usage"), dict):
            observer.on_usage(data["usage"])
        observer.on_stream_end()
        return JSONCompletion(data=data, provider=self.name,
                              model=str(payload.get("model", ""))), None

    # -- streaming -----------------------------------------------------------
    async def _complete_streaming(self, url: str, headers: dict[str, str],
                                  payload: dict[str, Any],
                                  observer: UsageObserver,
                                  timeout: httpx.Timeout) -> CompletionResult:
        req = self._client.build_request("POST", url, json=payload,
                                         headers=headers, timeout=timeout)
        resp = await self._client.send(req, stream=True)

        if resp.status_code >= 400:
            body = await resp.aread()
            await resp.aclose()
            return None, CompletionError(
                body.decode("utf-8", "replace")[:2000], status=resp.status_code)

        # Priming: pull frames until the first real data frame so we can still
        # fall back on in-band errors (request_handler.py:67-100).
        parser = SSEParser()
        primed: list[bytes] = []           # frames to re-emit once committed
        byte_iter = resp.aiter_bytes()
        committed = False
        finished = False                   # [DONE] already seen during priming
        try:
            async for chunk in byte_iter:
                for frame in parser.feed(chunk):
                    if frame.is_done:
                        if committed:
                            # Tiny response: data + [DONE] in one chunk.
                            primed.append(format_sse(SSE_DONE))
                            finished = True
                            break
                        # Stream ended before any content: treat as error.
                        await resp.aclose()
                        return None, CompletionError(
                            f"{self.name} stream ended with no data")
                    obj = frame.json
                    detail = frame_error_detail(obj) if obj is not None else None
                    if detail is not None and not committed:
                        await resp.aclose()
                        return None, CompletionError(detail)
                    if obj is None:
                        continue           # comment/keep-alive frame — drop
                    primed.append(format_sse(frame.data))
                    observer.on_first_token()
                    text = _extract_content_delta(obj)
                    if text:
                        observer.on_content_delta(text)
                    if isinstance(obj.get("usage"), dict):
                        observer.on_usage(obj["usage"])
                    committed = True
                if committed:
                    break
            if not committed:
                await resp.aclose()
                return None, CompletionError(
                    f"{self.name} closed the stream before any data frame")
        except httpx.TimeoutException as e:
            await resp.aclose()
            return None, CompletionError(
                f"timeout during {self.name} stream priming: "
                f"{type(e).__name__}", kind="timeout")
        except httpx.HTTPError as e:
            await resp.aclose()
            return None, CompletionError(f"stream setup failed: {e}")

        frames = self._relay(resp, byte_iter, parser, primed, observer,
                             finished=finished)
        return StreamingCompletion(frames=frames, provider=self.name,
                                   model=str(payload.get("model", ""))), None

    async def _relay(self, resp: httpx.Response, byte_iter: AsyncIterator[bytes],
                     parser: SSEParser, primed: list[bytes],
                     observer: UsageObserver, finished: bool = False) -> AsyncIterator[bytes]:
        """Yield primed frames then the rest of the stream, watching for
        mid-stream errors and usage (request_handler.py:102-146)."""
        error: str | None = None
        try:
            for frame_bytes in primed:
                yield frame_bytes
            if finished:
                return
            async for chunk in byte_iter:
                for frame in parser.feed(chunk):
                    if frame.is_done:
                        yield format_sse("[DONE]")
                        continue
                    obj = frame.json
                    if obj is not None:
                        detail = frame_error_detail(obj)
                        if detail is not None:
                            # Too late to fall back — surface in-band and stop.
                            error = detail
                            yield format_sse({"error": {"message": detail,
                                                        "provider": self.name}})
                            return
                        text = _extract_content_delta(obj)
                        if text:
                            observer.on_content_delta(text)
                        if isinstance(obj.get("usage"), dict):
                            observer.on_usage(obj["usage"])
                    yield format_sse(frame.data)
            for frame in parser.flush():
                if not frame.is_done:
                    yield format_sse(frame.data)
        except httpx.HTTPError as e:
            error = f"upstream stream error: {e}"
            yield format_sse({"error": {"message": error, "provider": self.name}})
        finally:
            observer.on_stream_end(error)
            await resp.aclose()

    # -- models --------------------------------------------------------------
    async def list_models(self) -> list[dict[str, Any]] | None:
        """GET {base}/models (reference: models.py:239-296), 60 s/10 s."""
        try:
            resp = await self._client.get(
                f"{self.base_url}/models",
                headers=self._headers({}), timeout=MODELS_TIMEOUT)
            if resp.status_code >= 400:
                return None
            data = resp.json()
            models = data.get("data") if isinstance(data, dict) else data
            return models if isinstance(models, list) else None
        except (httpx.HTTPError, ValueError):
            return None

    async def close(self) -> None:
        await self._client.aclose()
