"""Provider abstraction: the gateway's single most important contract.

Inherited behavioral contract (SURVEY.md §7): *one call that returns
``(response, error)`` and never raises into the fallback loop* — the property
that makes fallback, rotation, and local/remote symmetry composable
(reference: ``make_llm_request`` at ``services/request_handler.py:8``,
consumed at ``api/v1/chat.py:142``). Two implementations:

* :class:`~.remote_http.RemoteHTTPProvider` — the reference's entire job;
* ``LocalProvider`` (providers/local.py) — the in-process JAX/TPU engine.

Streaming responses commit to HTTP 200 only after the provider has produced
its first real data frame (remote: SSE priming; local: prefill admission), so
errors can still trigger fallback.
"""
from __future__ import annotations

import abc
from dataclasses import dataclass, field
from typing import TYPE_CHECKING, Any, AsyncIterator, Protocol

if TYPE_CHECKING:
    from ..obs.slo import SLOTargets
    from ..reliability.deadline import Deadline


@dataclass
class CompletionError:
    """Why a provider call failed; feeds the retry/fallback state machine.

    ``kind`` classifies failures the reliability layer treats specially:
    ``"overload"`` (engine queue full / upstream shedding — the router maps
    an all-overload chain to HTTP 429 with ``retry_after_s``) and
    ``"timeout"`` (the attempt hit its deadline-capped transport timeout —
    feeds the 504 path). ``""`` is every other failure.
    """
    detail: str
    status: int | None = None
    retryable: bool = True
    kind: str = ""                     # "" | "overload" | "timeout"
    retry_after_s: float | None = None  # backpressure hint (kind="overload")

    def __str__(self) -> str:
        return f"[{self.status}] {self.detail}" if self.status else self.detail


class UsageObserver(Protocol):
    """Single-parse usage capture: the provider calls these as it parses its
    own stream, so nothing downstream re-parses SSE (fixes the double-parse
    in the reference, SURVEY.md §3.2)."""

    def on_first_token(self) -> None: ...
    def on_content_delta(self, text: str) -> None: ...
    def on_usage(self, usage: dict[str, Any]) -> None: ...
    def on_stream_end(self, error: str | None = None) -> None: ...


@dataclass
class NullUsageObserver:
    def on_first_token(self) -> None: pass
    def on_content_delta(self, text: str) -> None: pass
    def on_usage(self, usage: dict[str, Any]) -> None: pass
    def on_stream_end(self, error: str | None = None) -> None: pass


@dataclass
class StreamingCompletion:
    """A committed streaming response: raw SSE frames ready to forward.

    ``frames`` yields complete SSE-encoded byte frames (``data: ...\\n\\n``).
    By the time a StreamingCompletion is returned, the first real frame has
    already been validated (priming), so the server may send 200.
    """
    frames: AsyncIterator[bytes]
    provider: str = ""
    model: str = ""


@dataclass
class JSONCompletion:
    """A successful non-streaming response body (OpenAI chat.completion)."""
    data: dict[str, Any]
    provider: str = ""
    model: str = ""


CompletionResult = tuple[
    "StreamingCompletion | JSONCompletion | None", "CompletionError | None"]


@dataclass
class CompletionRequest:
    """Everything a provider needs for one upstream attempt, post-routing:
    payload already rewritten to the provider-real model name with custom
    body params merged (cf. chat.py:112-123). ``deadline`` is the request's
    remaining end-to-end budget: remote providers cap their httpx timeouts
    with it, the local provider bounds its first-token wait / decode drain
    and cancels the engine slot on expiry."""
    payload: dict[str, Any]
    stream: bool
    extra_headers: dict[str, str] = field(default_factory=dict)
    deadline: "Deadline | None" = None
    # Per-request SLO targets (obs/slo.py; ISSUE 7). Unlike `deadline`
    # these never abort the attempt — the local provider computes the
    # outcome at stream end and attributes violations; remote providers
    # may ignore them.
    slo: "SLOTargets | None" = None


class Provider(abc.ABC):
    """A completion backend. Implementations must never raise from
    :meth:`complete`; all failures become ``(None, CompletionError)``."""

    name: str = ""
    type: str = ""

    @abc.abstractmethod
    async def complete(self, request: CompletionRequest,
                       observer: UsageObserver) -> CompletionResult:
        ...

    async def list_models(self) -> list[dict[str, Any]] | None:
        """Optional: the provider's /models inventory (None = unsupported)."""
        return None

    async def close(self) -> None:
        pass
