"""`local` provider: the in-process TPU engine behind the standard provider
contract.

This is the BASELINE.json north star — ``/v1/chat/completions`` answered by
an in-process JAX/XLA engine with **no remote call in the loop**, while
staying "just another entry in providers.json": same ``(response, error)``
contract as remote providers, so fallback/rotation/usage plumbing applies
unchanged, and engine overload/failure falls back to remote providers
(BASELINE config 5).

Streaming commits only after the first token exists (prefill admission +
first sample) — the local analog of the remote SSE priming trick
(SURVEY.md §7 hard part (3)).
"""
from __future__ import annotations

import asyncio
import logging
import time
import uuid
from typing import Any, AsyncIterator

from ..config.schemas import ProviderDetails
from ..obs import slo as obs_slo
from ..obs import trace as obs_trace
from ..obs.metrics import GatewayMetrics, get_metrics
from ..utils.sse import SSE_DONE, format_sse
from .base import (
    CompletionError,
    CompletionRequest,
    CompletionResult,
    JSONCompletion,
    Provider,
    StreamingCompletion,
    UsageObserver,
)

logger = logging.getLogger(__name__)


class LocalProvider(Provider):
    type = "local"

    def __init__(self, name: str, engine: "InferenceEngine",
                 metrics: GatewayMetrics | None = None):
        self.name = name
        self.engine = engine
        self._metrics = metrics or get_metrics()

    # -- engine-phase tracing --------------------------------------------------
    # The engine loop runs outside the request's task, so its phases are
    # reported post-hoc from the GenRequest's own timestamps (ISSUE 4):
    # queued (submit → slot admission), prefill (admission → first token),
    # then decode/drain recorded at stream end. `parent` is the
    # provider.call span captured while complete() was current.

    def _trace_admission(self, req, parent) -> None:
        if req.t_first_token is None:
            return
        t_admit = req.t_admitted or req.t_submit
        # The flight-recorder cross-link (ISSUE 7): the admit record's
        # sequence number, so an operator can jump from this request's
        # trace to the exact scheduler steps that served it
        # (GET /v1/api/flight / tools/flight_report.py).
        attrs = ({"flight_seq": req.flight_admit_seq}
                 if req.flight_admit_seq >= 0 else {})
        obs_trace.record_span("engine.queued", layer="engine",
                              start=req.t_submit, end=t_admit, parent=parent,
                              **attrs)
        if req.prefix_lookup_ms is not None:
            # Radix prefix lookup (ISSUE 6), ran just before admission
            # stamped t_admitted; cached_tokens is the prefill span the
            # hit skipped (0 = miss).
            obs_trace.record_span(
                "engine.prefix_lookup", layer="engine",
                start=t_admit - req.prefix_lookup_ms / 1000.0, end=t_admit,
                parent=parent, cached_tokens=req.cached_tokens)
        obs_trace.record_span("engine.prefill", layer="engine",
                              start=t_admit, end=req.t_first_token,
                              parent=parent,
                              prompt_tokens=len(req.prompt_ids))
        obs_trace.record_span("engine.first_token", layer="engine",
                              start=req.t_first_token, end=req.t_first_token,
                              parent=parent)
        self._metrics.engine_ttft_seconds.labels(engine=self.name).observe(
            max(0.0, req.t_first_token - req.t_submit))

    def _trace_decode(self, req, parent, error: str | None = None) -> None:
        if req.t_first_token is None:
            return
        end = req.t_done if req.t_done is not None else time.monotonic()
        attrs = {"tokens": len(req.generated)}
        if req.finish_reason:
            attrs["finish_reason"] = req.finish_reason
        if error:
            attrs["error"] = error[:200]
        obs_trace.record_span("engine.decode", layer="engine",
                              start=req.t_first_token, end=end,
                              parent=parent, **attrs)
        now = time.monotonic()
        if req.t_done is not None and now > req.t_done:
            # Emission drained after the engine finished (lag-one bursts +
            # stop-sequence holdback flush through here).
            obs_trace.record_span("engine.drain", layer="engine",
                                  start=req.t_done, end=now, parent=parent)

    # -- request translation ---------------------------------------------------
    def _build_genrequest(self, payload: dict[str, Any]):
        from ..engine.engine import GenRequest
        tok = self.engine.tokenizer
        messages = payload.get("messages") or []
        if not isinstance(messages, list):
            raise ValueError("'messages' must be a list")
        prompt_text = tok.apply_chat_template(messages,
                                              add_generation_prompt=True)
        prompt_ids = tok.encode(prompt_text)
        if tok.bos_id is not None and (not prompt_ids or
                                       prompt_ids[0] != tok.bos_id):
            prompt_ids = [tok.bos_id] + prompt_ids

        stop = payload.get("stop") or []
        if isinstance(stop, str):
            stop = [stop]
        max_tokens = int(payload.get("max_completion_tokens")
                         or payload.get("max_tokens")
                         or self.engine.cfg.max_tokens_default)
        # OpenAI default: temperature=1 (sampled) when omitted; an explicit
        # 0 still means greedy.
        raw_temp = payload.get("temperature")
        temperature = 1.0 if raw_temp is None else float(raw_temp)
        top_p = float(payload.get("top_p", 1.0) or 1.0)
        top_k = int(payload.get("top_k", 0) or 0)
        # OpenAI penalty fields (engine/sampling.py apply_penalties). `or 0.0`
        # also maps explicit null to the no-penalty default.
        presence = float(payload.get("presence_penalty") or 0.0)
        frequency = float(payload.get("frequency_penalty") or 0.0)
        return GenRequest(prompt_ids=prompt_ids, max_tokens=max_tokens,
                          temperature=temperature, top_p=top_p, top_k=top_k,
                          presence_penalty=presence,
                          frequency_penalty=frequency,
                          stop=[s for s in stop if s])

    def _usage(self, req) -> dict[str, Any]:
        n_gen = len(req.generated)
        usage = {"prompt_tokens": len(req.prompt_ids),
                 "completion_tokens": n_gen,
                 "total_tokens": len(req.prompt_ids) + n_gen}
        if req.cached_tokens:
            # OpenAI-compatible prefix-cache accounting: the span of the
            # prompt served from resident KV (prefill skipped). Flows into
            # the usage DB / stats UI via extract_usage_fields.
            usage["prompt_tokens_details"] = {
                "cached_tokens": req.cached_tokens}
        if req.t_first_token is not None:
            usage["ttft_ms"] = round(
                (req.t_first_token - req.t_submit) * 1000.0, 2)
            if req.t_done and n_gen > 1 and req.t_done > req.t_first_token:
                usage["tokens_per_sec"] = round(
                    (n_gen - 1) / (req.t_done - req.t_first_token), 2)
        slo_out = self._slo_outcome(req)
        if slo_out is not None:
            # SLO outcome + attribution (ISSUE 7): rides the usage object
            # into the SSE usage frame AND the usage DB row
            # (extract_usage_fields ingests met/phase).
            usage["slo"] = slo_out
        return usage

    def _slo_outcome(self, req) -> dict[str, Any] | None:
        """Evaluate + record this request's SLO outcome exactly once
        (idempotent via a stash on the request): counters on /metrics,
        violation attributed against the engine's flight recorder."""
        slo = obs_slo.SLOTargets(ttft_ms=req.slo_ttft_ms,
                                 tpot_ms=req.slo_tpot_ms)
        if not slo.defined:
            return None
        cached = getattr(req, "_slo_outcome_cache", None)
        if cached is not None:
            return cached
        engine = getattr(self, "engine", None)
        flight = getattr(engine, "flight", None)
        outcome = obs_slo.evaluate(req, slo, flight)
        if outcome["met"]:
            self._metrics.slo_met_total.labels(engine=self.name).inc()
        else:
            self._metrics.slo_violated_total.labels(
                engine=self.name, phase=outcome["phase"]).inc()
        # Per-pool SLO attribution (ISSUE 13): keyed by the pool that
        # served the request's decode (post-handoff), so a disaggregated
        # engine's goodput splits into per-pool numerators and the
        # unified engine keeps one "unified" series — the
        # pooled-vs-unified scoreboard behind
        # gateway_slo_pool_goodput_ratio.
        from ..obs.flight import POOL_NAMES
        pool = POOL_NAMES.get(getattr(req, "pool", 0), "unified")
        outcome["pool"] = pool
        if outcome["met"]:
            self._metrics.slo_pool_met_total.labels(
                engine=self.name, pool=pool).inc()
        else:
            self._metrics.slo_pool_violated_total.labels(
                engine=self.name, pool=pool).inc()
        req._slo_outcome_cache = outcome
        return outcome

    # -- the provider contract -------------------------------------------------
    async def complete(self, request: CompletionRequest,
                       observer: UsageObserver) -> CompletionResult:
        from ..engine.engine import EngineOverloaded, EngineUnavailable
        payload = request.payload
        model_name = str(payload.get("model", self.name))
        try:
            req = self._build_genrequest(payload)
        except Exception as e:
            return None, CompletionError(f"invalid request for local engine: {e}",
                                         retryable=False)
        # Gateway request id onto the engine request: the flight
        # recorder's admit/finish/shed records carry it, linking
        # scheduler timeline rows back to /v1/api/trace/{id} (ISSUE 7).
        req.request_id = obs_trace.current_request_id() or ""
        if request.slo is not None:
            req.slo_ttft_ms = request.slo.ttft_ms
            req.slo_tpot_ms = request.slo.tpot_ms
        try:
            await self.engine.submit(req)
        except EngineOverloaded as e:
            # Overload is a *failable provider* condition: the router falls
            # back to the next (e.g. remote) target — SURVEY.md §5 — and,
            # when the WHOLE chain is overloaded, sheds with HTTP 429 +
            # Retry-After from the engine's own telemetry (ISSUE 3).
            hint = None
            try:
                hint = self.engine.retry_after_hint_s()
            except Exception:       # stats must never break shedding
                logger.debug("retry-after hint unavailable; shedding "
                             "without one", exc_info=True)
            return None, CompletionError(str(e), status=503,
                                         kind="overload", retry_after_s=hint)
        except EngineUnavailable as e:
            # Engine down/draining/restarting (ISSUE 14): a retryable
            # 503 whose status feeds the breaker's failure window, so a
            # few of these open the breaker and the router skips the
            # local provider at ~0 cost until the supervisor recovers
            # the engine and the half-open probe readmits it.
            return None, CompletionError(
                str(e), status=503, kind="engine_down",
                retry_after_s=getattr(e, "retry_after_s", None))
        except Exception as e:
            logger.exception("engine submit failed")
            return None, CompletionError(f"local engine error: {e}")

        # Wait for the first delta before committing (priming analog): if the
        # engine fails before producing a token, the router can still fall
        # back. A request deadline bounds this wait: on expiry the slot is
        # cancelled (the engine stops decoding and frees it) and the attempt
        # reports kind="timeout" so the router's 504 path takes over.
        deadline = request.deadline
        parent = obs_trace.current_span()
        stream_iter = self.engine.stream(req)
        try:
            if deadline is not None:
                first_delta = await asyncio.wait_for(
                    anext(stream_iter), timeout=max(0.001, deadline.remaining()))
            else:
                first_delta = await anext(stream_iter)
        except StopAsyncIteration:
            return None, CompletionError("engine produced no output")
        except asyncio.TimeoutError:
            # The loop drops cancelled requests at its next admission /
            # decode pass — the slot (or queue position) frees itself.
            req.cancelled = True
            return None, CompletionError(
                "deadline expired before the local engine produced a token",
                kind="timeout", retryable=False)
        if first_delta.error is not None:
            return None, CompletionError(first_delta.error)

        observer.on_first_token()
        self._trace_admission(req, parent)

        if request.stream:
            frames = self._sse_frames(req, stream_iter, first_delta,
                                      model_name, observer,
                                      deadline=deadline, parent=parent)
            return StreamingCompletion(frames=frames, provider=self.name,
                                       model=model_name), None

        # Non-streaming: drain (cancel the engine work if the handler task is
        # cancelled, e.g. the client disconnected while we generate).
        text_parts = [first_delta.text]
        finish = first_delta.finish_reason
        error = first_delta.error
        try:
            if finish is None and error is None:
                async for delta in stream_iter:
                    text_parts.append(delta.text)
                    finish = delta.finish_reason
                    error = delta.error
                    if (finish is None and error is None
                            and deadline is not None and deadline.expired()):
                        # Decode cancellation on budget exhaustion: stop the
                        # slot and report timeout — the router returns 504
                        # (the client asked for a bounded wait, not a
                        # truncated answer).
                        req.cancelled = True
                        observer.on_stream_end("deadline expired")
                        self._trace_decode(req, parent,
                                           error="deadline expired")
                        self._slo_outcome(req)
                        return None, CompletionError(
                            "deadline expired during local decode",
                            kind="timeout", retryable=False)
        except asyncio.CancelledError:
            req.cancelled = True
            raise
        if error is not None:
            observer.on_stream_end(error)
            self._trace_decode(req, parent, error=error)
            self._slo_outcome(req)
            return None, CompletionError(error)
        self._trace_decode(req, parent)
        text = "".join(text_parts)
        usage = self._usage(req)
        observer.on_content_delta(text)
        observer.on_usage(usage)
        observer.on_stream_end()
        body = {
            "id": f"chatcmpl-{uuid.uuid4().hex[:24]}",
            "object": "chat.completion",
            "created": int(time.time()),
            "model": model_name,
            "choices": [{"index": 0,
                         "message": {"role": "assistant", "content": text},
                         "finish_reason": finish or "stop"}],
            "usage": usage,
        }
        return JSONCompletion(data=body, provider=self.name,
                              model=model_name), None

    async def _sse_frames(self, req, stream_iter: AsyncIterator,
                          first_delta, model_name: str,
                          observer: UsageObserver,
                          deadline=None, parent=None) -> AsyncIterator[bytes]:
        cid = f"chatcmpl-{uuid.uuid4().hex[:24]}"
        created = int(time.time())
        tbt = self._metrics.engine_time_between_tokens_seconds.labels(
            engine=self.name)

        def chunk(delta_content: str | None, finish: str | None = None,
                  role: str | None = None, usage: dict | None = None,
                  timings: str | None = None) -> bytes:
            delta: dict[str, Any] = {}
            if role:
                delta["role"] = role
            if delta_content:
                delta["content"] = delta_content
            body: dict[str, Any] = {
                "id": cid, "object": "chat.completion.chunk",
                "created": created, "model": model_name,
                "choices": [{"index": 0, "delta": delta,
                             "finish_reason": finish}]}
            if usage is not None:
                body["usage"] = usage
            if timings:
                # Streamed analog of the x-gateway-timings header (ISSUE 7
                # satellite): the FULL per-phase summary — decode included,
                # which no response-start header can carry — as the usage
                # frame's sibling field. Extra top-level keys are ignored
                # by OpenAI-protocol clients.
                body["gateway_timings"] = timings
            return format_sse(body)

        error: str | None = None
        traced = False
        last_t = time.monotonic()
        try:
            yield chunk(None, role="assistant")
            if first_delta.text:
                observer.on_content_delta(first_delta.text)
                yield chunk(first_delta.text)
            finish = first_delta.finish_reason
            if finish is None:
                async for delta in stream_iter:
                    now = time.monotonic()
                    tbt.observe(now - last_t)
                    last_t = now
                    if delta.error is not None:
                        error = delta.error
                        yield format_sse({"error": {"message": error,
                                                    "provider": self.name}})
                        return
                    if (deadline is not None and deadline.expired()
                            and delta.finish_reason is None):
                        # Budget exhausted mid-stream: stop decoding, free
                        # the slot, and end the committed stream with an
                        # in-band error frame (the 200 is long since on the
                        # wire — the 504 path only exists pre-commit).
                        error = "deadline expired mid-stream"
                        req.cancelled = True
                        yield format_sse({"error": {
                            "message": "request deadline expired mid-stream",
                            "provider": self.name, "code": 504}})
                        return
                    if delta.text:
                        observer.on_content_delta(delta.text)
                        yield chunk(delta.text)
                    if delta.finish_reason is not None:
                        finish = delta.finish_reason
            # Close the decode/drain spans BEFORE building the summary so
            # the streamed timing field covers the whole request.
            self._trace_decode(req, parent)
            traced = True
            usage = self._usage(req)
            observer.on_usage(usage)
            yield chunk(None, finish=finish or "stop", usage=usage,
                        timings=obs_trace.server_timing_header() or None)
            yield format_sse(SSE_DONE)
        finally:
            if req.finish_reason is None:
                # Client hung up mid-stream (generator closed early): tell
                # the engine to stop decoding and free the slot.
                req.cancelled = True
            observer.on_stream_end(error)
            if not traced:
                self._trace_decode(req, parent, error=error)
            # Error/disconnect exits skip the usage frame; the SLO outcome
            # must still be counted (idempotent — the success path already
            # recorded it inside _usage).
            self._slo_outcome(req)

    async def list_models(self) -> list[dict[str, Any]] | None:
        return [{"id": self.name, "object": "model", "owned_by": "local_tpu",
                 "context_length": self.engine.S}]

    async def close(self) -> None:
        await self.engine.stop()


def make_local_provider(name: str, details: ProviderDetails) -> LocalProvider:
    """Factory installed into the ProviderRegistry (server/app.py)."""
    from ..engine.engine import InferenceEngine
    assert details.engine is not None
    engine = InferenceEngine(details.engine)
    return LocalProvider(name, engine)
