from .router import Router, ProviderRegistry, RouteOutcome

__all__ = ["Router", "ProviderRegistry", "RouteOutcome"]
