"""Routing engine: rule resolution, rotation, retry/fallback state machine.

Behavior parity with the reference's routing loop — which lives inline in its
API handler (``api/v1/chat.py:41-198``) — lifted into a service object so the
HTTP layer stays thin (SURVEY.md §7 step 2). Extended with the reliability
layer (ISSUE 3): per-request deadline budgets (retry sleeps and remaining
attempts clamped; exhaustion → 504 with partial-attempt detail),
per-provider circuit breakers (open breakers are skipped instantly — a dead
upstream stops costing its timeout on every request), fast-exit on
non-retryable errors (same-target retries of a hopeless attempt are
skipped), and overload shedding (an all-overload/all-open chain → 429 with
a Retry-After the client can act on). Reference semantics preserved:

* Rule lookup by gateway model name; unknown models become a synthetic
  single-target chain on the configured fallback provider with the model name
  passed through (``chat.py:48-59``).
* Rotation: persisted per-(client-key, gateway-model) round-robin start index
  with circular reorder of the chain (``chat.py:64-78``); DB errors degrade
  to index 0. The sqlite call is offloaded, never blocking the event loop
  (the reference blocks — ``chat.py:67``).
* Per-target retry loop: ``retry_count`` extra attempts, sleeping
  ``retry_delay`` seconds when ``0 < delay < 120`` (``chat.py:127,191-194``).
* Payload build per attempt: model rewrite to the provider-real name,
  OpenRouter ``usage.include`` auto-injection, ``custom_body_params`` /
  ``custom_headers`` merge, ``HTTP-Referer``/``X-Title`` headers
  (``chat.py:103-123``); OpenRouter ``provider.order`` pinning, and the
  ``use_provider_order_as_fallback`` sub-provider loop (``chat.py:137-139,
  158-189``).
* Every attempt gets a **fresh deep-copied payload** — deliberately fixing
  the reference quirk where a failure mutates ``messages`` to ``"<REMOVED>"``
  and retries send no real messages (``chat.py:150``; SURVEY.md §2a "Quirk").
* All targets exhausted → a terminal error the server maps to HTTP 503
  (``chat.py:196-198``).
"""
from __future__ import annotations

import asyncio
import copy
import logging
import time
from dataclasses import dataclass, field
from typing import Any, Callable

from ..config.loader import ConfigLoader, resolve_api_key
from ..config.schemas import FallbackModelRule, ModelFallbackConfig, ProviderDetails
from ..db.rotation import RotationDB
from ..obs import trace as obs_trace
from ..obs.metrics import GatewayMetrics, get_metrics
from ..providers.base import (
    CompletionError,
    CompletionRequest,
    JSONCompletion,
    Provider,
    StreamingCompletion,
    UsageObserver,
)
from ..providers.remote_http import RemoteHTTPProvider
from ..reliability.breaker import BreakerRegistry, counts_as_breaker_failure
from ..reliability.deadline import Deadline

logger = logging.getLogger(__name__)

MAX_RETRY_DELAY_S = 120.0        # honored window (chat.py:191)


class ProviderRegistry:
    """Builds/caches Provider instances from the live config.

    Instances are reused until the provider's config entry changes. ``local``
    providers are constructed through a pluggable factory so the gateway can
    run (and be tested) without importing JAX.
    """

    # Grace period before closing a reconfigured provider's pooled client:
    # must outlive the longest possible in-flight request (300 s timeout).
    RETIRE_AFTER_S = 330.0

    def __init__(self, loader: ConfigLoader,
                 local_factory: Callable[[str, ProviderDetails], Provider] | None = None):
        self._loader = loader
        self._local_factory = local_factory
        # name -> (fingerprint, provider)
        self._cache: dict[str, tuple[str, Provider]] = {}   # guarded-by: _lock
        self._lock = asyncio.Lock()
        self._name_locks: dict[str, asyncio.Lock] = {}      # guarded-by: _lock
        # Retire-task bookkeeping is touched only from loop-side code
        # (create_task callbacks, close()) — never from the _build worker
        # thread; the annotation makes graftlint v2's thread-reachability
        # pass and the runtime sanitizer both enforce that.
        self._retiring: set[asyncio.Task] = set()           # guarded-by: loop
        self._closed = False

    async def get(self, name: str) -> Provider | None:
        details = self._loader.providers.get(name)
        if details is None:
            return None
        fingerprint = details.model_dump_json()
        async with self._lock:
            cached = self._cache.get(name)
            if cached and cached[0] == fingerprint:
                return cached[1]
            name_lock = self._name_locks.setdefault(name, asyncio.Lock())
        # Build outside the registry lock: a local-engine build (checkpoint
        # load + device_put) takes seconds to minutes and must not stall
        # requests to other, already-cached providers. The per-name lock
        # stops two requests double-building the same provider; the build
        # itself runs in a worker thread so the event loop keeps serving.
        async with name_lock:
            async with self._lock:
                cached = self._cache.get(name)
                if cached and cached[0] == fingerprint:
                    return cached[1]
                if cached:
                    if getattr(details, "type", None) == "local":
                        from ..parallel.multihost import is_multihost
                        if is_multihost():
                            # A multihost engine is terminal: retiring it
                            # broadcasts SHUTDOWN and the followers exit, so
                            # a rebuilt coordinator engine would hang forever
                            # in its first collective (advisor r1, medium).
                            # Keep serving with the old engine and say so —
                            # adopting the new fingerprint so this logs once
                            # and the fast path resumes, not per-request.
                            logger.error(
                                "providers.json change for local provider "
                                "%r ignored: multihost engines cannot be "
                                "rebuilt in-process (followers replay one "
                                "command stream); restart the fleet to "
                                "apply the new engine config", name)
                            self._cache[name] = (fingerprint, cached[1])
                            return cached[1]
                    # Config changed: in-flight streams may still hold the
                    # old provider's pooled client — close it only after
                    # they can possibly have finished.
                    self._retire(cached[1])
                    del self._cache[name]
            provider = await asyncio.to_thread(self._build, name, details)
            if provider is not None:
                async with self._lock:
                    if self._closed:
                        # Registry shut down while this build was in flight:
                        # don't strand a live provider in a dead cache.
                        await provider.close()
                        return None
                    self._cache[name] = (fingerprint, provider)
            return provider

    def instantiated(self) -> list[tuple[str, Provider]]:
        """Currently-built providers (without forcing any build) — for the
        observability endpoints (server/profiler_api.py)."""
        return [(name, prov) for name, (_, prov) in self._cache.items()]

    def local_providers(self) -> list[Provider]:
        """Already-built providers backed by an in-process engine — the
        drain / SIGTERM surface (ISSUE 14). Builds nothing: a provider
        that never served has nothing to drain."""
        return [prov for _, prov in self.instantiated()
                if getattr(prov, "engine", None) is not None]

    def _retire(self, provider: Provider) -> None:
        async def _close_later() -> None:
            try:
                await asyncio.sleep(self.RETIRE_AFTER_S)
                await provider.close()
            except asyncio.CancelledError:
                await provider.close()
                raise
        task = asyncio.get_running_loop().create_task(_close_later())
        self._retiring.add(task)
        task.add_done_callback(self._retiring.discard)

    def _build(self, name: str, details: ProviderDetails) -> Provider | None:
        if details.type == "local":
            if self._local_factory is None:
                logger.error("provider %s is type=local but no engine factory "
                             "is installed", name)
                return None
            return self._local_factory(name, details)
        return RemoteHTTPProvider(
            name=name, base_url=details.baseUrl or "",
            api_key=resolve_api_key(details))

    async def close(self) -> None:
        async with self._lock:
            self._closed = True
            for task in list(self._retiring):
                task.cancel()
                try:
                    await task
                except asyncio.CancelledError:
                    pass
            for _, provider in self._cache.values():
                await provider.close()
            self._cache.clear()


@dataclass
class RouteOutcome:
    """Terminal result of routing one request through the fallback chain."""
    result: StreamingCompletion | JSONCompletion | None
    error: CompletionError | None
    attempts: int = 0
    provider: str = ""
    model: str = ""
    errors: list[str] = field(default_factory=list)


class Router:
    def __init__(self, loader: ConfigLoader, registry: ProviderRegistry,
                 rotation_db: RotationDB, fallback_provider: str = "openrouter",
                 sleep: Callable[[float], Any] | None = None,
                 breakers: BreakerRegistry | None = None,
                 default_timeout_ms: float = 0.0,
                 clock: Callable[[], float] | None = None,
                 metrics: GatewayMetrics | None = None):
        self._loader = loader
        self._registry = registry
        self._rotation = rotation_db
        self._fallback_provider = fallback_provider
        self._sleep = sleep or asyncio.sleep     # injectable for tests
        self._breakers = breakers
        self._default_timeout_ms = default_timeout_ms
        self._clock = clock or time.monotonic    # injectable for tests
        self._metrics = metrics or get_metrics()

    # -- rule resolution -----------------------------------------------------
    def resolve_rule(self, gateway_model: str) -> ModelFallbackConfig:
        rule = self._loader.rules.get(gateway_model)
        if rule is not None:
            return rule
        # Unknown model → passthrough to the fallback provider (chat.py:48-59).
        return ModelFallbackConfig(
            gateway_model_name=gateway_model,
            fallback_models=[FallbackModelRule(
                provider=self._fallback_provider, model=gateway_model)],
            rotate_models=False)

    async def _ordered_targets(self, rule: ModelFallbackConfig,
                               client_key: str) -> list[FallbackModelRule]:
        targets = list(rule.fallback_models)
        if rule.rotate_models and len(targets) > 1:
            start = await self._rotation.next_index_async(
                client_key, rule.gateway_model_name, len(targets))
            targets = targets[start:] + targets[:start]
        return targets

    # -- payload/header construction ------------------------------------------
    @staticmethod
    def _build_attempt(payload: dict[str, Any], target: FallbackModelRule,
                       provider_name: str,
                       pinned_order: list[str] | None,
                       deadline: Deadline | None = None,
                       request_id: str = "",
                       slo=None) -> CompletionRequest:
        attempt = copy.deepcopy(payload)
        attempt["model"] = target.model
        if provider_name.lower() == "openrouter":
            # Ask OpenRouter to report usage/cost (chat.py:114-115).
            attempt.setdefault("usage", {"include": True})
            order = pinned_order if pinned_order is not None else target.providers_order
            if order:
                attempt["provider"] = {"order": list(order),
                                       "allow_fallbacks": False}
        if target.custom_body_params:
            attempt.update(copy.deepcopy(target.custom_body_params))
        headers = {"HTTP-Referer": "https://llmapigateway-tpu.local",
                   "X-Title": "LLM API Gateway (TPU)"}
        if request_id:
            # Propagate the gateway's request id upstream so one id
            # correlates gateway and provider logs (ISSUE 4).
            headers["x-request-id"] = request_id
        if target.custom_headers:
            headers.update(target.custom_headers)
        stream = bool(attempt.get("stream", False))
        return CompletionRequest(payload=attempt, stream=stream,
                                 extra_headers=headers, deadline=deadline,
                                 slo=slo)

    # -- the state machine -----------------------------------------------------
    def _start_deadline(self, rule: ModelFallbackConfig,
                        timeout_ms: float | None) -> Deadline | None:
        """Resolve the request's time budget: explicit client ask (header /
        body, parsed by the HTTP layer) > per-rule ``timeout_ms`` >
        gateway-wide default; 0/None at every level = unbounded."""
        budget_ms = timeout_ms or rule.timeout_ms or self._default_timeout_ms
        if not budget_ms or budget_ms <= 0:
            return None
        return Deadline(budget_ms / 1000.0, clock=self._clock)

    async def dispatch(self, payload: dict[str, Any], client_key: str,
                       observer_factory: Callable[[str, str], UsageObserver],
                       timeout_ms: float | None = None,
                       request_id: str = "",
                       slo=None) -> RouteOutcome:
        """Route one chat-completions payload through the fallback chain.

        ``observer_factory(provider, model)`` builds a fresh usage observer
        per attempt; only the successful attempt's observer sees a complete
        stream, so usage is recorded exactly once. ``timeout_ms`` is the
        client's explicit budget (x-request-timeout-ms header / timeout_ms
        body field), if any. ``request_id`` is propagated on outbound
        provider requests (and labels this request's trace spans). ``slo``
        is the client's SLO-header ask; the rule's ``slo_ttft_ms`` /
        ``slo_tpot_ms`` defaults fill unset fields (obs/slo.py), mirroring
        the deadline precedence chain.
        """
        from ..obs.slo import resolve_slo
        gateway_model = str(payload.get("model", ""))
        rule = self.resolve_rule(gateway_model)
        targets = await self._ordered_targets(rule, client_key)
        deadline = self._start_deadline(rule, timeout_ms)
        slo = resolve_slo(slo, rule)
        m = self._metrics

        outcome = RouteOutcome(result=None, error=None)
        # Terminal-status classification (ISSUE 3): 504 when the budget ran
        # out, 429 when EVERY failure was backpressure (engine/upstream
        # overload or an open breaker) so the client gets a Retry-After it
        # can act on, 503 otherwise.
        n_overload = 0
        n_other = 0
        deadline_hit = False
        retry_hints: list[float] = []

        for target_idx, target in enumerate(targets):
            if deadline is not None and deadline.expired():
                deadline_hit = True
                break
            provider = await self._registry.get(target.provider)
            if provider is None:
                outcome.errors.append(
                    f"provider {target.provider!r} unavailable")
                n_other += 1
                continue

            breaker = (self._breakers.get(target.provider)
                       if self._breakers is not None else None)
            if breaker is not None and not breaker.allow():
                # Open breaker: fall through instantly — no payload build,
                # no network, no retry sleeps for a known-dead upstream.
                cooldown = breaker.cooldown_remaining()
                outcome.errors.append(
                    f"{target.provider}/{target.model}: circuit open "
                    f"(retry in {cooldown:.1f}s)")
                retry_hints.append(cooldown)
                n_overload += 1
                m.router_breaker_skips_total.labels(
                    provider=target.provider).inc()
                obs_trace.record_span(
                    "router.breaker_skip", layer="router",
                    provider=target.provider,
                    cooldown_s=round(cooldown, 2))
                continue

            # Sub-provider fallback: gateway loops OpenRouter upstreams one at
            # a time, each pinned (chat.py:158-189). Otherwise one attempt
            # series with the whole order pinned (chat.py:137-139).
            if target.use_provider_order_as_fallback and target.providers_order:
                sub_orders: list[list[str] | None] = [
                    [sub] for sub in target.providers_order]
            else:
                sub_orders = [None]

            retries = max(0, int(target.retry_count))
            target_done = False          # non-retryable / deadline fast-exit
            target_attempted = False     # any attempt actually sent?
            for attempt_idx in range(retries + 1):
                for sub_order in sub_orders:
                    if deadline is not None and deadline.expired():
                        deadline_hit = True
                        target_done = True
                        if breaker is not None and not target_attempted:
                            # allow() may have reserved the half-open probe;
                            # we never sent it — release, don't leak.
                            breaker.release_probe()
                        break
                    request = self._build_attempt(
                        payload, target, target.provider, sub_order, deadline,
                        request_id=request_id, slo=slo)
                    observer = observer_factory(target.provider, target.model)
                    outcome.attempts += 1
                    target_attempted = True
                    m.router_attempts_total.labels(
                        provider=target.provider).inc()
                    t_attempt = self._clock()
                    with obs_trace.span(
                            "router.attempt", layer="router",
                            provider=target.provider, model=target.model,
                            attempt=outcome.attempts) as att_span:
                        with obs_trace.span(
                                "provider.call", layer="provider",
                                provider=target.provider):
                            result, error = await provider.complete(
                                request, observer)
                        if att_span is not None and error is not None:
                            att_span.attrs["error"] = str(error)[:200]
                    m.provider_attempt_duration_seconds.labels(
                        provider=target.provider).observe(
                            self._clock() - t_attempt)
                    if error is not None:
                        kind = error.kind or (
                            "http" if error.status is not None else "error")
                        m.provider_errors_total.labels(
                            provider=target.provider, kind=kind).inc()
                        if error.kind == "timeout":
                            m.provider_timeouts_total.labels(
                                provider=target.provider).inc()
                    if error is None and result is not None:
                        if breaker is not None:
                            breaker.record_success()
                        outcome.result = result
                        outcome.provider = target.provider
                        outcome.model = target.model
                        return outcome
                    breaker_opened = False
                    if breaker is not None:
                        if counts_as_breaker_failure(error):
                            breaker.record_failure()
                            # This failure tripped (or re-tripped, for a
                            # failed half-open probe) the breaker: the
                            # window has judged this target dead — burning
                            # the remaining same-target retries and sleeps
                            # would be exactly the waste breakers exist to
                            # stop.
                            breaker_opened = breaker.state == "open"
                        else:
                            # Alive-but-rejecting (plain 4xx): not evidence
                            # of an unhealthy upstream.
                            breaker.record_success()
                    if error is not None and error.kind == "overload":
                        n_overload += 1
                        if error.retry_after_s is not None:
                            retry_hints.append(error.retry_after_s)
                    else:
                        n_other += 1
                    detail = str(error) if error else "empty response"
                    sub = f" (upstream={sub_order[0]})" if sub_order else ""
                    outcome.errors.append(
                        f"{target.provider}/{target.model}{sub}: {detail}")
                    logger.warning("attempt failed: %s", outcome.errors[-1])
                    if breaker_opened or (error is not None
                                          and not error.retryable):
                        # Same-target retries of a non-retryable failure
                        # (invalid request, deadline hit) or of a target
                        # whose breaker just opened are pure waste — skip
                        # straight to the next target (ISSUE 3 satellite;
                        # previously burned the full retry loop).
                        target_done = True
                        break
                if target_done:
                    break
                if attempt_idx < retries and 0 < target.retry_delay < MAX_RETRY_DELAY_S:
                    # Clamp the backoff sleep against the remaining budget: a
                    # 119 s retry_delay must never outlive a 2 s deadline.
                    delay = (deadline.clamp(target.retry_delay)
                             if deadline is not None else target.retry_delay)
                    if delay > 0:
                        await self._sleep(delay)
            if deadline_hit:
                break
            if target_attempted and target_idx < len(targets) - 1:
                # Falling past an attempted-and-failed target to the next
                # one in the chain — the fallback-hop counter.
                m.router_fallbacks_total.inc()

        if deadline is not None and (deadline_hit or deadline.expired()):
            budget_ms = deadline.budget_s * 1000.0
            m.router_deadline_expired_total.inc()
            outcome.error = CompletionError(
                detail=(f"deadline of {budget_ms:.0f} ms exhausted after "
                        f"{outcome.attempts} attempt(s): "
                        + ("; ".join(outcome.errors[-5:]) or "no attempts made")),
                status=504, retryable=False, kind="timeout")
        elif n_overload > 0 and n_other == 0 and outcome.errors:
            m.router_sheds_total.inc()
            outcome.error = CompletionError(
                detail="all providers overloaded or shedding: "
                       + "; ".join(outcome.errors[-5:]),
                status=429, retryable=True, kind="overload",
                retry_after_s=max(retry_hints, default=1.0))
        else:
            outcome.error = CompletionError(
                detail="; ".join(outcome.errors[-5:]) or
                       f"no providers available for {gateway_model!r}",
                status=503, retryable=False)
        return outcome
