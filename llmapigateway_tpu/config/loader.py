"""json5 config loading with validation, atomic hot-reload, and raw-text access.

Behavior parity with the reference's ``ConfigLoader``
(``llm_gateway_core/config/loader.py:69-282``): load + validate both files at
startup, semantic cross-checks (every rule's provider must exist, fallback
provider must exist, warn on unresolvable API-key env vars), and
validate-then-swap hot reload that never leaves the loader holding a broken
config. Differences by design:

* Library code **raises** :class:`ConfigError` instead of ``sys.exit(1)``
  (reference: ``loader.py:74,100,164``) — the entrypoint decides process fate.
* Exactly one loader instance serves the whole app (the reference leaks a
  second import-time instance in ``api/v1/models.py:14-16`` which never sees
  hot reloads — see SURVEY.md §1).
* Providers may be ``type: local`` (in-process TPU engine) — new capability.
"""
from __future__ import annotations

import logging
import os
import threading
from pathlib import Path
from typing import Any

import json5
from pydantic import ValidationError

from .schemas import (
    ConfigError,
    FallbackModelRule,
    ModelFallbackConfig,
    ProviderDetails,
)

logger = logging.getLogger(__name__)

PROVIDERS_FILE = "providers.json"
RULES_FILE = "models_fallback_rules.json"


def parse_providers(raw: Any) -> dict[str, ProviderDetails]:
    """Validate the parsed providers document → {name: ProviderDetails}.

    Accepts the reference's shape — a list of single-key dicts — plus a plain
    mapping {name: details} for convenience.
    """
    entries: list[tuple[str, Any]] = []
    if isinstance(raw, dict):
        entries = list(raw.items())
    elif isinstance(raw, list):
        for item in raw:
            if not isinstance(item, dict) or len(item) != 1:
                raise ConfigError(
                    "each providers.json entry must be a single-key object "
                    f"{{name: details}}, got: {item!r}")
            entries.append(next(iter(item.items())))
    else:
        raise ConfigError("providers.json must be a list or object")

    providers: dict[str, ProviderDetails] = {}
    for name, details in entries:
        if name in providers:
            raise ConfigError(f"duplicate provider name {name!r}")
        try:
            pd = ProviderDetails.model_validate(details)
            pd.validate_semantics(name)
        except (ValidationError, ValueError) as e:
            raise ConfigError(f"provider {name!r} invalid: {e}") from e
        providers[name] = pd
    if not providers:
        raise ConfigError("providers.json defines no providers")
    return providers


def parse_rules(raw: Any) -> dict[str, ModelFallbackConfig]:
    """Validate the parsed rules document → {gateway_model_name: config}."""
    if not isinstance(raw, list):
        raise ConfigError("models_fallback_rules.json must be a list of rules")
    rules: dict[str, ModelFallbackConfig] = {}
    for item in raw:
        try:
            rule = ModelFallbackConfig.model_validate(item)
        except ValidationError as e:
            raise ConfigError(f"invalid fallback rule: {e}") from e
        # Last duplicate wins, matching the reference's dict-overwrite behavior
        # (loader.py:133-164 builds a dict keyed by gateway_model_name).
        rules[rule.gateway_model_name] = rule
    return rules


def cross_validate(providers: dict[str, ProviderDetails],
                   rules: dict[str, ModelFallbackConfig],
                   fallback_provider: str | None = None) -> None:
    """Semantic checks across the two files (cf. loader.py:102-122,284-314)."""
    for model_name, cfg in rules.items():
        for fm in cfg.fallback_models:
            if fm.provider not in providers:
                raise ConfigError(
                    f"rule {model_name!r} references unknown provider {fm.provider!r}")
    if fallback_provider and fallback_provider not in providers:
        raise ConfigError(
            f"FALLBACK_PROVIDER {fallback_provider!r} not in providers.json")
    for name, pd in providers.items():
        if pd.type == "remote_http" and pd.apikey and pd.apikey == pd.apikey.upper() \
                and not os.environ.get(pd.apikey) and "KEY" in pd.apikey:
            # The guard above means pd.apikey is an ALL-CAPS env-var NAME
            # (unset), not a credential — logging it is the diagnostic.
            logger.warning(
                "provider %s: apikey %r looks like an env-var name but is not set; "
                "it will be sent as a literal key", name, pd.apikey)  # graftlint: disable=secret-hygiene


class ConfigLoader:
    """Owns the validated provider map and fallback rules, with hot reload.

    Thread-safe: readers get an immutable snapshot reference; reloads build a
    complete new validated object then swap under a lock.
    """

    def __init__(self, config_dir: Path | str = ".",
                 fallback_provider: str | None = None,
                 require_files: bool = True):
        self.config_dir = Path(config_dir)
        self.fallback_provider = fallback_provider
        self._lock = threading.Lock()
        self._providers: dict[str, ProviderDetails] = {}    # guarded-by: _lock
        self._rules: dict[str, ModelFallbackConfig] = {}    # guarded-by: _lock
        # Bumped on every successful (re)load.
        self._version = 0           # guarded-by: _lock
        if require_files:
            self.load()

    # -- paths -------------------------------------------------------------
    @property
    def providers_path(self) -> Path:
        return self.config_dir / PROVIDERS_FILE

    @property
    def rules_path(self) -> Path:
        return self.config_dir / RULES_FILE

    # -- loading -----------------------------------------------------------
    def _read_json5(self, path: Path) -> Any:
        try:
            text = path.read_text()
        except OSError as e:
            raise ConfigError(f"cannot read {path}: {e}") from e
        try:
            return json5.loads(text)
        except Exception as e:
            raise ConfigError(f"{path.name} is not valid json5: {e}") from e

    def load(self) -> None:
        """Initial load of both files; raises ConfigError on any problem."""
        providers = parse_providers(self._read_json5(self.providers_path))
        rules = parse_rules(self._read_json5(self.rules_path))
        cross_validate(providers, rules, self.fallback_provider)
        with self._lock:
            self._providers = providers
            self._rules = rules
            self._version += 1
        logger.info("config loaded: %d providers, %d gateway models",
                    len(providers), len(rules))

    # -- snapshot accessors -------------------------------------------------
    @property
    def providers(self) -> dict[str, ProviderDetails]:
        with self._lock:
            return self._providers

    @property
    def rules(self) -> dict[str, ModelFallbackConfig]:
        with self._lock:
            return self._rules

    @property
    def version(self) -> int:
        with self._lock:
            return self._version

    # -- hot reload (validate-then-swap, never partial) ---------------------
    def reload_providers(self) -> tuple[bool, str | None]:
        """Re-read providers.json; on success swap and return (True, None),
        on failure keep the old config and return (False, reason).
        Mirrors reference ``reload_providers_config`` (loader.py:236-282)."""
        try:
            providers = parse_providers(self._read_json5(self.providers_path))
            cross_validate(providers, self.rules, self.fallback_provider)
        except ConfigError as e:
            logger.error("providers reload rejected: %s", e)
            return False, str(e)
        with self._lock:
            self._providers = providers
            self._version += 1
        logger.info("providers hot-reloaded: %d providers", len(providers))
        return True, None

    def reload_rules(self) -> tuple[bool, str | None]:
        """Re-read rules; validate against current providers before swapping.
        Mirrors reference ``reload_fallback_rules`` (loader.py:166-234)."""
        try:
            rules = parse_rules(self._read_json5(self.rules_path))
            cross_validate(self.providers, rules, None)
        except ConfigError as e:
            logger.error("rules reload rejected: %s", e)
            return False, str(e)
        with self._lock:
            self._rules = rules
            self._version += 1
        logger.info("rules hot-reloaded: %d gateway models", len(rules))
        return True, None

    # -- raw text for the web editor (comments preserved) --------------------
    def read_raw(self, which: str) -> str:
        path = self.providers_path if which == "providers" else self.rules_path
        return path.read_text()

    def write_raw(self, which: str, text: str) -> None:
        """Validate text, write it verbatim (preserving comments), hot-reload.
        Raises ConfigError if the text does not validate; the file is only
        written after validation passes (unlike the reference, which writes
        first and can end up with a saved-but-not-loaded file —
        rules_editor.py:80-92)."""
        parsed = json5.loads(text)      # raises on syntax error
        if which == "providers":
            providers = parse_providers(parsed)
            cross_validate(providers, self.rules, self.fallback_provider)
            self.providers_path.write_text(text)
            with self._lock:
                self._providers = providers
                self._version += 1
        elif which == "rules":
            rules = parse_rules(parsed)
            cross_validate(self.providers, rules, None)
            self.rules_path.write_text(text)
            with self._lock:
                self._rules = rules
                self._version += 1
        else:
            raise ValueError(f"unknown config file {which!r}")


def resolve_api_key(details: ProviderDetails) -> str | None:
    """Resolve the provider API key: treat ``apikey`` as an env-var name if one
    is set, else as the literal key (reference behavior, ``chat.py:96-101``)."""
    if not details.apikey:
        return None
    return os.environ.get(details.apikey) or details.apikey
