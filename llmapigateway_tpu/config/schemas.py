"""Pydantic schemas for the two json5 config files.

Schema-compatible with the reference's on-disk formats so existing configs
migrate unchanged (``providers.json``: list of single-key dicts name→details,
cf. ``llm_gateway_core/config/loader.py:14-35``; ``models_fallback_rules.json``:
list of rule objects, cf. ``loader.py:37-56``), extended with a ``type`` field
on providers so an in-process TPU engine is "just another provider":

    { "local_tpu": { "type": "local", "engine": { "model_path": ..., ... } } }
"""
from __future__ import annotations

from typing import Any

from pydantic import BaseModel, ConfigDict, Field, field_validator


class ConfigError(Exception):
    """Raised on invalid configuration; callers decide whether to exit."""


class DisaggregationConfig(BaseModel):
    """Prefill/decode disaggregation knobs (engine/disagg.py, ISSUE 13).

    When enabled, the engine's batch slots split into a prefill pool and
    a decode pool over ONE shared paged KV pool; a completed prefill
    hands its KV to the decode pool by allocator refcount transfer (zero
    device copies). Requires ``kv_layout: paged``; incompatible with
    multihost, seq/pipe sharding, speculative decoding and SWA ring mode
    (rejected at engine build).
    """
    model_config = ConfigDict(extra="forbid")

    enabled: bool = False
    # Slots reserved for the prefill pool; 0 = auto (max(1, B // 4)).
    # Must leave at least one decode slot: 0 <= prefill_slots < B.
    prefill_slots: int = Field(default=0, ge=0)
    # "goodput": predict per-pool TTFT/TPOT attainment from fitted step
    # times + flight-ring decode occupancy + queue depth, shed (429 +
    # Retry-After) when the decode pool's predicted TPOT misses the
    # request's SLO, clamp (mark-only) when only TTFT is at risk.
    # "always": admit everything the watermark allows (telemetry still
    # flows; A/B baseline for the bench's --disagg-ab rung).
    admission: str = "goodput"

    @field_validator("admission")
    @classmethod
    def _admission_known(cls, v: str) -> str:
        if v not in ("goodput", "always"):
            raise ValueError(
                f"admission must be 'goodput' or 'always', got {v!r}")
        return v


class SupervisorConfig(BaseModel):
    """Engine supervision knobs (reliability/supervisor.py, ISSUE 14).

    The scheduler loop stamps a heartbeat every step; a watchdog task
    declares the engine stalled when the heartbeat goes stale past
    ``watchdog_ms`` while work is pending, and triggers the same
    supervised restart path as a step-loop crash: bounded exponential
    backoff (``backoff_ms`` doubling per attempt up to
    ``backoff_max_ms``), at most ``max_restarts`` attempts before the
    engine parks in ``failed`` and traffic stays on the router's
    fallback chain. ``drain_deadline_ms`` bounds how long an
    administrative drain waits for in-flight decodes before
    force-cancelling stragglers.
    """
    model_config = ConfigDict(extra="forbid")

    # 0 disables the watchdog task entirely (heartbeats still stamp, so
    # stats()/health report staleness either way).
    watchdog_ms: float = Field(default=0.0, ge=0.0)
    max_restarts: int = Field(default=3, ge=0)
    backoff_ms: float = Field(default=50.0, ge=0.0)
    backoff_max_ms: float = Field(default=5000.0, ge=0.0)
    drain_deadline_ms: float = Field(default=10000.0, gt=0.0)


class LocalEngineConfig(BaseModel):
    """Engine settings for a ``type: local`` provider entry.

    No reference counterpart — the reference proxies only. These knobs shape
    the JAX serving engine: checkpoint location, mesh layout, batching and
    KV-cache geometry.
    """
    model_config = ConfigDict(extra="forbid")

    model_path: str = ""            # HF checkpoint dir (safetensors); "" → random init
    preset: str | None = None       # named config (e.g. "tinyllama-1.1b") when no checkpoint
    dtype: str = "bfloat16"
    # Mesh geometry: axis name -> size. Product must equal device count used.
    mesh: dict[str, int] = Field(default_factory=dict)   # e.g. {"data":1,"model":8}
    max_batch_size: int = 8
    max_seq_len: int = 4096
    # Paged is THE serving path since 0.19 (ISSUE 6): page-pool KV with
    # admission-reservation backpressure, superpage kernel blocking, and
    # the radix prefix cache all hang off it, and the page-size sweep
    # closed the old paged-vs-contiguous decode gap (BENCH_SELF_r5b: the
    # 256-page point beats contiguous). "contiguous" remains as a
    # test-only numerical reference.
    kv_layout: str = "paged"        # "paged" | "contiguous"
    # Page size doubles as the paged kernel's DMA block; 256 is the
    # measured optimum on v5e (2026-07-31 ladder: 1647.8 vs 1443.7
    # tok/s at 128, TinyLlama bs=8 — bench.py's paged_sweep re-measures
    # both every run so this default tracks the hardware). Smaller pages
    # trade a little DMA efficiency for finer capacity granularity in
    # the equal-HBM admission math (engine/paged.py).
    kv_page_size: int = 256
    kv_num_pages: int = 0           # 0 → derived from max_batch_size*max_seq_len
    # Multi-page kernel blocking: fetch this many CONTIGUOUS logical pages
    # per paged-kernel grid step (one pages_per_block× larger HBM→VMEM
    # DMA; the kernel grid shrinks by the same factor — the decode
    # roofline lever at target scale, ISSUE 2). >1 switches the page
    # allocator to superpage packing (aligned runs of this many physical
    # pages; up to ppb-1 pages of internal fragmentation per slot) so the
    # kernels' gather-free index maps stay valid. Falls back to 1 with a
    # warning when the pool can't be packed (seq-banded pools, the SWA
    # page ring, or non-divisible page geometry). Numerics are identical
    # for every value (bit-for-bit vs per-page kernels).
    kv_pages_per_block: int = 1
    # Radix prefix cache over the paged pool (ISSUE 6): requests whose
    # prompt prefix is resident (shared system prompts, multi-turn
    # history) map the matched KV blocks straight into their page table
    # and skip the matched span's prefill entirely; completed requests
    # index their pages back on release (insert-on-release). Eviction is
    # LRU-by-leaf under page pressure with in-flight pages refcount-
    # pinned. Reuse granularity is kv_page_size × kv_pages_per_block
    # tokens. Active on single-host, single-band, non-sliding-window
    # paged engines; everywhere else the flag is inert. Hit accounting
    # surfaces as `prompt_tokens_details.cached_tokens` in usage frames
    # and as engine_prefix_cache_* series in /metrics.
    prefix_cache: bool = True
    # Chip HBM peak (GB/s) for the engine's roofline telemetry: with this
    # set, stats()/the /v1/api/roofline endpoint report achieved GB/s as
    # a fraction of peak (v5e: 819). 0 = unknown — absolute achieved_gbps
    # still reports from the bytes-touched model × measured step time.
    hbm_peak_gbps: float = 0.0
    prefill_chunk: int = 512
    # Max queued admissions prefilled in ONE compiled call (the
    # scheduler groups same-bucket chunks and snaps the group size down
    # to a compiled K rung {1,2,4,8}). Dispatch cost dominates chunk
    # compute on a tunneled chip (measured r5: 77 ms/dispatch vs ~3 ms
    # of 1.1B chunk compute), so a K-batch fills K-fold faster; each
    # (bucket, K) pair costs one lazily-compiled program. 1 disables.
    # Multihost always runs K=1 (coordinator/follower programs must
    # stay bit-identical while followers replay per-slot frames).
    prefill_batch: int = 8
    decode_burst: int = 8           # chained decode steps per host sync
    # Burst depth while new work is waiting (prefill interleave): deep
    # enough to amortize dispatch latency, shallow enough that admission
    # never waits long. 1 = legacy fully-synchronous busy stepping.
    decode_burst_busy: int = 4
    # TTFT self-tuning (>0 enables): a dispatched decode scan cannot be
    # preempted, so a probe arriving at an IDLE-queue engine waits out
    # the in-flight deep burst before its prefill starts. With a target
    # set, the engine caps the deep depth so that exposure spends at
    # most half the target (the other half covers flush + prefill +
    # first-token sampling), using its own measured steady-state
    # step-time EMA — self-tuning across models/hardware where a fixed
    # decode_burst is only right for one step time. The cap snaps to a
    # compiled scan depth (deep, deep/2, busy) — arbitrary depths would
    # fall off the fused-scan fast path.
    ttft_target_ms: float = 0.0
    max_tokens_default: int = 1024
    # Prompt-lookup speculative decoding: draft N tokens per step from the
    # slot's own token history, verify in one T=N+1 forward (exact greedy
    # output — wrong drafts are rejected by construction). 0 = off.
    # N+1 must be a power of two (kernel blocking): N ∈ {1, 3, 7}.
    # Engages only while every active slot is greedy; while any
    # temperature>0 request is active the whole batch is served through
    # the normal (unaccelerated) decode path. Works with both KV
    # layouts and composes with seq/pipe sharding (the verify forward's
    # S-reductions partition under GSPMD / run through the staged
    # block) AND with multi-host serving (OP_SPEC command stream,
    # per-process hist mirrors) AND with kv_quant='int8' (the verify
    # self-block is mixed-precision: off-diagonal drafts go through the
    # same quantize→dequantize plain decode reads, preserving the
    # exact-greedy guarantee; only seq-sharded PAGED + int8 + spec is
    # rejected at build).
    spec_draft_len: int = 0
    # Adaptive drafting gate: a speculative step is a T=k+1 verify forward
    # (~1.2-1.3x a T=1 step's device time), so drafting only pays while
    # accepted tokens/step clears that ratio. The engine keeps a per-slot
    # acceptance EMA and falls back to NORMAL decode bursts while the
    # active batch's mean is below this threshold — so spec can stay
    # enabled in config without taxing non-repetitive traffic. While
    # gated off, one 1-step speculative PROBE runs every
    # `spec_probe_interval` decode rounds to re-measure (text often turns
    # repetitive mid-stream: quoting, code, lists). 0 disables the
    # ACCEPTANCE term only — the wall-clock term below still gates
    # unless spec_wall_gate is also off (both off = always draft).
    # New/unmeasured slots count optimistically so fresh requests get a
    # chance to establish their rate.
    spec_min_tokens_per_step: float = 1.2
    spec_probe_interval: int = 25
    # PER-SLOT adaptive drafting: suspend drafting on any slot whose
    # acceptance EMA, expressed as an acceptance RATIO ((ema_tokens/step
    # - 1) / k, i.e. the fraction of proposed drafts accepted), falls
    # below this floor. A suspended slot's drafts are masked on device
    # (deterministic 1 token/step), its EMA freezes, and it stops
    # dragging the batch-mean gate above; when EVERY active slot is
    # suspended the scheduler skips spec bursts entirely (full-width
    # normal decode). Suspended slots re-probe together every
    # `spec_probe_interval` spec rounds: one 1-step burst with all slots
    # drafting re-measures, and a slot whose fresh ratio clears the
    # floor resumes. 0 disables per-slot suspension (batch-level gates
    # above still apply).
    spec_acceptance_floor: float = 0.0
    # Wall-clock gate term: also close the gate while the MEASURED spec
    # ms-per-emitted-token (EMA over full spec bursts) exceeds the normal
    # path's. Acceptance tokens/step alone can hold a net-loss gate open
    # — a degenerate repetition loop accepts 2+ tokens/step while each
    # spec step costs several times a fused decode step (v5e ladder
    # 2026-07-31: 346.9 vs 1475.1 tok/s, acceptance gate open at 2.24).
    # Off = acceptance-only gating (the pre-r5 behavior).
    spec_wall_gate: bool = True
    # Weight quantization: "int8" stores the seven big matmul weights per
    # layer (incl. MoE expert matmuls) + lm_head as symmetric per-channel
    # int8 (activations quantize dynamically inside the step;
    # models/quant.py). Halves the weight bytes each decode step streams
    # from HBM — the decode roofline — at a small accuracy cost (W8A8).
    # "int4" packs the LAYER matmuls to 4-bit (lm_head stays int8):
    # ~45% fewer weight bytes again, at a larger quality cost users opt
    # into per-provider (W4A8; mixed s8×s4 dot_general).
    quant: str = ""                 # "" | "int8" | "int4"
    # KV-cache quantization: "int8" stores K/V as symmetric per-token
    # per-head int8 (+ fp32 scales, ~6% overhead) — halves KV bandwidth
    # AND capacity footprint, the long-context/high-concurrency lever.
    # Works with both KV layouts (a paged int8 pool packs 2x the tokens)
    # and composes with `quant`; seq/pipe sharding and speculation are
    # rejected at engine build (v1).
    kv_quant: str = ""              # "" | "int8"
    attention: str = "auto"         # "auto" | "pallas" | "reference"
    # Attention pattern for a seq-sharded mesh: "ring" rotates KV blocks over
    # ICI (works for any head count); "ulysses" all-to-alls heads<->sequence
    # (cheaper collective when n_kv_heads >= seq axis size).
    seq_attention: str = "ring"     # "ring" | "ulysses"
    tokenizer_path: str | None = None
    # Persistent XLA compilation cache: second engine init skips the 30-60 s
    # trace+compile. "" → ~/.cache/llmapigateway_tpu/xla; "off" disables.
    compilation_cache_dir: str = ""
    # Pre-compile BOTH sampler variants (greedy + general) off-thread on
    # start() so the first temperature>0 request doesn't stall mid-serving.
    # Benchmarks disable it (the compile churn competes with latency probes).
    prewarm_sampler_variants: bool = True
    # Numerics sanitizer (SURVEY.md §5 "race detection / sanitizers"): raise
    # on NaN production inside compiled programs (costs performance; debug).
    debug_nans: bool = False
    # Scheduler flight recorder (ISSUE 7): capacity of the preallocated
    # per-step/lifecycle record ring (obs/flight.py), served at
    # GET /v1/api/flight and exported by tools/flight_report.py. Appends
    # are allocation- and lock-free on the step path, so the recorder is
    # on by default; ring-wrap loss is visible as the
    # gateway_engine_flight_ring_evicted_total series. 0 disables.
    # (Same knob pattern as the gateway-level TRACE_RING_SIZE.)
    flight_ring_size: int = 4096
    # HBM headroom watermark (ISSUE 8): shed admissions (HTTP 429 with
    # the engine's Retry-After hint, the PR 3 overload path) while the
    # runtime allocator reports less than this FRACTION of device memory
    # free — admission reacts to memory pressure before the next compile
    # or fragmentation event OOMs mid-stream. 0 disables. Inert on
    # backends without allocator stats (CPU reports none); the HBM
    # ledger's gateway_engine_hbm_* gauges report the same numbers.
    hbm_headroom_watermark: float = Field(default=0.0, ge=0.0, lt=1.0)
    # Phase-annotated profiling (ISSUE 8): host-side jax.profiler
    # TraceAnnotation markers (prefill / decode / spec.verify) around
    # every compiled-program dispatch, so on-demand captures
    # (POST /v1/api/profiler/trace) segment by scheduler phase in
    # Perfetto. Cost is a few µs per dispatch (the bench's annotation
    # A/B rung pins it ≤1% on decode); the in-program named_scope
    # markers (decode.attention / decode.mlp / sampling) are trace-time
    # metadata and cannot be disabled because they cost nothing.
    profile_annotations: bool = True
    # Prefill/decode disaggregation (ISSUE 13): two pools, one paged KV
    # pool, zero-copy handoff, goodput-first admission. Default off —
    # the unified scheduler is byte-identical to pre-pool behavior.
    disaggregation: DisaggregationConfig = Field(
        default_factory=DisaggregationConfig)
    # Engine supervision (ISSUE 14): crash/stall recovery with bounded
    # backoff, graceful drain. Watchdog defaults off; crash recovery and
    # the lifecycle state machine are always on.
    supervisor: SupervisorConfig = Field(default_factory=SupervisorConfig)


class BreakerSettings(BaseModel):
    """Per-provider circuit-breaker knobs (reliability/breaker.py, ISSUE 3).

    Defaults are deliberately conservative: a provider must fail at least
    half of a 5+-request window inside 30 s before the router stops paying
    its timeouts, and gets a single half-open probe every ``cooldown_s``
    until it recovers. Set ``enabled: false`` to opt a provider out (e.g.
    a single-target chain where skipping the only target helps nobody).
    """
    model_config = ConfigDict(extra="forbid")

    enabled: bool = True
    window_s: float = Field(default=30.0, gt=0)       # sliding failure window
    min_requests: int = Field(default=5, ge=1)        # samples before judging
    failure_threshold: float = Field(default=0.5, gt=0, le=1.0)
    cooldown_s: float = Field(default=15.0, gt=0)     # open → half-open probe


class ProviderDetails(BaseModel):
    """One provider's connection/engine details.

    Reference counterpart: ``ProviderDetails`` (baseUrl, apikey) at
    ``loader.py:14-16``; the reference ignores unknown keys (e.g. the
    "multiple_models" field in its own example) — we accept extras too.
    """
    model_config = ConfigDict(extra="allow")

    type: str = "remote_http"       # "remote_http" | "local"
    baseUrl: str | None = None
    apikey: str | None = None       # env-var name, or the literal key itself
    engine: LocalEngineConfig | None = None
    breaker: BreakerSettings | None = None   # None → BreakerSettings defaults

    @field_validator("type")
    @classmethod
    def _check_type(cls, v: str) -> str:
        if v not in ("remote_http", "local"):
            raise ValueError(f"provider type must be 'remote_http' or 'local', got {v!r}")
        return v

    def validate_semantics(self, name: str) -> None:
        if self.type == "remote_http" and not self.baseUrl:
            raise ValueError(f"provider {name!r}: remote_http requires 'baseUrl'")
        if self.type == "local" and self.engine is None:
            raise ValueError(f"provider {name!r}: local provider requires 'engine' config")


class FallbackModelRule(BaseModel):
    """One target in a gateway model's fallback chain.

    Reference counterpart: ``FallbackModelRule`` at ``loader.py:37-45``.
    """
    model_config = ConfigDict(extra="forbid")

    provider: str
    model: str
    use_provider_order_as_fallback: bool = False
    providers_order: list[str] | None = None
    retry_delay: float = 0.0
    retry_count: int = 0
    custom_body_params: dict[str, Any] | None = None
    custom_headers: dict[str, str] | None = None

    @field_validator("use_provider_order_as_fallback", mode="before")
    @classmethod
    def _coerce_bool(cls, v: Any) -> Any:
        if isinstance(v, str):
            return v.strip().lower() == "true"
        return v


class ModelFallbackConfig(BaseModel):
    """A gateway model: ordered fallback chain + rotation flag.

    Reference counterpart: ``ModelFallbackConfig`` at ``loader.py:47-56``
    (including the '"true"'-string coercion for ``rotate_models``).
    """
    model_config = ConfigDict(extra="forbid")

    gateway_model_name: str
    fallback_models: list[FallbackModelRule]
    rotate_models: bool = False
    # Default end-to-end time budget (ms) for requests to this gateway
    # model when the client sends neither the `x-request-timeout-ms`
    # header nor a `timeout_ms` body field. 0 = fall through to the
    # gateway-wide DEFAULT_REQUEST_TIMEOUT_MS (which itself defaults to
    # unbounded). Exhaustion returns HTTP 504 with per-attempt detail.
    timeout_ms: float = Field(default=0.0, ge=0)
    # Default per-request SLO targets (ms) for this gateway model when
    # the client sends no `x-slo-ttft-ms` / `x-slo-tpot-ms` headers
    # (obs/slo.py; ISSUE 7). Unlike timeout_ms these never fail a
    # request — they only classify it: outcomes land on the
    # gateway_slo_{met,violated}_total /metrics series, the usage DB
    # row, and the final usage frame, with TTFT violations attributed
    # (queued / prefill / decode_contention) from the flight recorder.
    # 0 = no target.
    slo_ttft_ms: float = Field(default=0.0, ge=0)
    slo_tpot_ms: float = Field(default=0.0, ge=0)

    @field_validator("rotate_models", mode="before")
    @classmethod
    def _coerce_bool(cls, v: Any) -> Any:
        if isinstance(v, str):
            return v.strip().lower() == "true"
        return v
