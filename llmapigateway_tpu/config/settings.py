"""Environment-driven settings.

Behavior parity with the reference's env settings surface
(``llm_gateway_core/config/settings.py:10-44`` in /root/reference): gateway
API key, fallback provider, port/host, CORS origins, log limits, debug mode —
plus engine-oriented knobs the reference has no counterpart for.

Unlike the reference this is not an import-time singleton wired to dotenv
side effects: construct ``Settings()`` explicitly (reads a ``.env`` file if
present, then the process environment; env wins), or use :func:`get_settings`
for the process-wide instance.
"""
from __future__ import annotations

import os
from dataclasses import dataclass, field
from pathlib import Path


def _load_dotenv(path: Path) -> dict[str, str]:
    """Minimal .env parser: KEY=VALUE lines, '#' comments, optional quotes."""
    out: dict[str, str] = {}
    try:
        text = path.read_text()
    except OSError:
        return out
    for line in text.splitlines():
        line = line.strip()
        if not line or line.startswith("#") or "=" not in line:
            continue
        key, _, val = line.partition("=")
        key = key.strip()
        val = val.strip()
        if len(val) >= 2 and val[0] == val[-1] and val[0] in "\"'":
            val = val[1:-1]
        if key:
            out[key] = val
    return out


def _as_bool(val: str | None, default: bool = False) -> bool:
    if val is None:
        return default
    return val.strip().lower() in ("1", "true", "yes", "on")


@dataclass
class Settings:
    """Resolved gateway settings. All fields overridable via environment."""

    gateway_api_key: str | None = None
    fallback_provider: str = "openrouter"
    gateway_host: str = "0.0.0.0"
    gateway_port: int = 9100
    allowed_origins: list[str] = field(default_factory=lambda: ["*"])
    log_file_limit: int = 15
    log_chat_messages: bool = False
    usage_retention_days: int = 180
    log_level: str = "INFO"
    debug_mode: bool = False
    # Gateway-wide default request time budget in ms (reliability layer,
    # ISSUE 3). Per-request header/body and per-rule `timeout_ms` take
    # precedence; 0 = unbounded (each attempt still bounded by the
    # transport's 300 s cap).
    default_request_timeout_ms: float = 0.0
    # Capacity of the request-trace ring buffer (obs/trace.py): the most
    # recent N requests stay queryable at /v1/api/trace/{id}. Loss under
    # load is visible as gateway_trace_ring_evicted_total (ISSUE 7).
    trace_ring_size: int = 256
    # Directories (relative to base_dir unless absolute)
    base_dir: Path = field(default_factory=Path.cwd)
    config_dir: Path | None = None
    db_dir: Path | None = None
    logs_dir: Path | None = None

    @classmethod
    def from_env(cls, base_dir: Path | None = None,
                 env: dict[str, str] | None = None) -> "Settings":
        base = Path(base_dir) if base_dir else Path.cwd()
        merged = _load_dotenv(base / ".env")
        merged.update(os.environ if env is None else env)

        origins_raw = merged.get("ALLOWED_ORIGINS", "*")
        origins = [o.strip() for o in origins_raw.split(",") if o.strip()] or ["*"]

        def _path(key: str, default: str) -> Path:
            p = Path(merged.get(key, default))
            return p if p.is_absolute() else base / p

        return cls(
            gateway_api_key=merged.get("GATEWAY_API_KEY") or None,
            fallback_provider=merged.get("FALLBACK_PROVIDER", "openrouter"),
            gateway_host=merged.get("GATEWAY_HOST", "0.0.0.0"),
            gateway_port=int(merged.get("GATEWAY_PORT", "9100")),
            allowed_origins=origins,
            log_file_limit=int(merged.get("LOG_FILE_LIMIT", "15")),
            usage_retention_days=int(merged.get("USAGE_RETENTION_DAYS", "180")),
            log_chat_messages=_as_bool(merged.get("LOG_CHAT_MESSAGES"), False),
            log_level=merged.get("LOG_LEVEL", "INFO").upper(),
            debug_mode=_as_bool(merged.get("DEBUG_MODE"), False),
            default_request_timeout_ms=float(
                merged.get("DEFAULT_REQUEST_TIMEOUT_MS", "0") or 0),
            trace_ring_size=int(merged.get("TRACE_RING_SIZE", "256") or 256),
            base_dir=base,
            config_dir=_path("CONFIG_DIR", "."),
            db_dir=_path("DB_DIR", "db"),
            logs_dir=_path("LOGS_DIR", "logs"),
        )


_settings: Settings | None = None


def get_settings() -> Settings:
    global _settings
    if _settings is None:
        _settings = Settings.from_env()
    return _settings


def set_settings(s: Settings) -> None:
    global _settings
    _settings = s
