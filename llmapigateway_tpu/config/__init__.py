from .settings import Settings, get_settings
from .schemas import (
    ProviderDetails,
    FallbackModelRule,
    ModelFallbackConfig,
    LocalEngineConfig,
    ConfigError,
)
from .loader import ConfigLoader

__all__ = [
    "Settings",
    "get_settings",
    "ProviderDetails",
    "FallbackModelRule",
    "ModelFallbackConfig",
    "LocalEngineConfig",
    "ConfigError",
    "ConfigLoader",
]
