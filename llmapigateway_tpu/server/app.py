"""Application composition: wire config, DBs, router, providers, HTTP app.

Counterpart of the reference's ``main.py`` (app bootstrap, lifespan state,
middleware order, router mounting, static files, ``/health``, ``/`` redirect
— ``main.py:30-116``), built on aiohttp. One ``GatewayApp`` owns exactly one
ConfigLoader / UsageDB / RotationDB (the reference accidentally creates
duplicates at import time — SURVEY.md §1 "layering reality").
"""
from __future__ import annotations

import asyncio
import logging
from pathlib import Path
from typing import Callable

from aiohttp import web

from ..config.loader import ConfigLoader
from ..config.settings import Settings
from ..db.recorder import UsageRecorder
from ..db.rotation import RotationDB
from ..db.usage import UsageDB
from ..obs.metrics import GatewayMetrics, get_metrics
from ..obs.trace import Tracer
from ..providers.base import Provider
from ..reliability.breaker import BreakerRegistry
from ..routing.router import ProviderRegistry, Router
from . import chat, config_api, models_api, obs_api, profiler_api, stats_api
from .middleware import (
    auth_middleware,
    cors_middleware,
    request_id_header_middleware,
    request_logging_middleware,
)

logger = logging.getLogger(__name__)

STATIC_DIR = Path(__file__).resolve().parent.parent / "static"


class GatewayApp:
    """Holds the gateway's singletons; attached to the aiohttp app as
    ``app["gateway"]``."""

    def __init__(self, settings: Settings, loader: ConfigLoader,
                 local_factory: Callable[..., Provider] | None = None,
                 metrics: GatewayMetrics | None = None,
                 tracer: Tracer | None = None):
        self.settings = settings
        self.loader = loader
        self.usage_db = UsageDB(settings.db_dir or "db")
        # Write-behind usage recording (ISSUE 14): stream-end observers
        # enqueue; one background flusher owns the SQLite writes. The
        # recorder duck-types UsageDB.insert, so chat.py hands it to
        # UsageCollector unchanged; close() drains before the DB closes
        # so process exit never loses completed requests' rows.
        self.usage_recorder = UsageRecorder(self.usage_db)
        self.rotation_db = RotationDB(settings.db_dir or "db")
        self.registry = ProviderRegistry(loader, local_factory=local_factory)
        self.breakers = BreakerRegistry(loader)
        # Observability plane (ISSUE 4): the process-global metrics set by
        # default (the local-provider factory records into it too) and a
        # per-app trace ring buffer.
        self.metrics = metrics or get_metrics()
        self.tracer = tracer or Tracer(
            capacity=max(1, settings.trace_ring_size))
        self.router = Router(
            loader, self.registry, self.rotation_db,
            fallback_provider=settings.fallback_provider,
            breakers=self.breakers,
            default_timeout_ms=settings.default_request_timeout_ms,
            metrics=self.metrics)
        self._stats_collector = obs_api.make_stats_collector(self)
        self.metrics.registry.register_collector(self._stats_collector)

    async def close(self) -> None:
        self.metrics.registry.unregister_collector(self._stats_collector)
        await self.registry.close()
        # Recorder before DB: drain the write-behind queue while the
        # connection is still open (flush-on-shutdown contract).
        await asyncio.to_thread(self.usage_recorder.close)
        self.usage_db.close()
        self.rotation_db.close()

    async def drain_local_engines(self, *, restart: bool = False) -> list:
        """Administrative drain of every local provider's engine
        (ISSUE 14): planned restart / SIGTERM path. Flushes the usage
        recorder afterwards so interrupted streams' partial rows are
        durable before the caller exits or reloads."""
        results = []
        for provider in self.registry.local_providers():
            engine = getattr(provider, "engine", None)
            if engine is None:
                continue
            try:
                results.append(await engine.drain(restart=restart))
            except Exception:
                logger.exception("drain failed for provider %r",
                                 getattr(provider, "name", "?"))
        await asyncio.to_thread(self.usage_recorder.flush)
        return results


async def _health(request: web.Request) -> web.Response:
    return web.json_response({"status": "ok"})


async def _root_redirect(request: web.Request) -> web.Response:
    raise web.HTTPFound("/v1/ui/rules-editor")


def _static_page(filename: str):
    async def handler(request: web.Request) -> web.Response:
        path = STATIC_DIR / filename
        if not path.exists():
            return web.json_response({"detail": f"{filename} not found"}, status=404)
        text = await asyncio.to_thread(path.read_text)
        return web.Response(text=text, content_type="text/html")
    return handler


def build_app(settings: Settings | None = None,
              loader: ConfigLoader | None = None,
              local_factory: Callable[..., Provider] | None = None,
              gateway: GatewayApp | None = None) -> web.Application:
    """Build the aiohttp application. All dependencies injectable for tests."""
    settings = settings or Settings.from_env()
    if loader is None:
        loader = ConfigLoader(settings.config_dir or ".",
                              fallback_provider=settings.fallback_provider)
    gw = gateway or GatewayApp(settings, loader, local_factory=local_factory)

    app = web.Application(middlewares=[
        cors_middleware(settings.allowed_origins),
        request_id_header_middleware(),
        request_logging_middleware(metrics=gw.metrics, tracer=gw.tracer),
        auth_middleware(settings.gateway_api_key),
    ])
    app["gateway"] = gw

    app.router.add_get("/health", _health)
    # Unified metrics plane: every layer's instruments in one Prometheus
    # text-format scrape (ISSUE 4).
    app.router.add_get("/metrics", obs_api.get_metrics_text)
    app.router.add_get("/", _root_redirect)

    # Core OpenAI-compatible API
    app.router.add_post("/v1/chat/completions", chat.chat_completions)
    app.router.add_get("/v1/models", models_api.get_models)
    app.router.add_get("/v1/models/AsOpenCodeFormat",
                       models_api.get_models_as_opencode)
    app.router.add_get("/v1/models/AsGitHubCopilotFormat",
                       models_api.get_models_as_github_copilot)

    # Config editor API (+ UI pages)
    app.router.add_get("/v1/config/models-rules", config_api.get_rules_text)
    app.router.add_post("/v1/config/models-rules", config_api.save_rules)
    app.router.add_get("/v1/config/providers", config_api.get_providers_text)
    app.router.add_post("/v1/config/providers", config_api.save_providers)
    app.router.add_get("/v1/ui/rules-editor", _static_page("rules-editor.html"))
    app.router.add_get("/v1/ui/usage-stats", _static_page("usage-stats.html"))

    # Stats API
    app.router.add_get("/v1/api/usage-stats/{period}", stats_api.get_usage_stats)
    app.router.add_get("/v1/api/usage-records", stats_api.get_usage_records)
    # Reliability: live circuit-breaker state per provider (ISSUE 3)
    app.router.add_get("/v1/api/health/providers", stats_api.get_provider_health)

    # Observability: engine stats + on-demand device trace capture
    app.router.add_get("/v1/api/engine-stats", profiler_api.get_engine_stats)
    app.router.add_get("/v1/api/roofline", profiler_api.get_roofline)
    app.router.add_post("/v1/api/profiler/trace", profiler_api.capture_trace)
    # End-to-end request traces (router → provider → engine span trees).
    app.router.add_get("/v1/api/trace/{request_id}", obs_api.get_trace)
    # Scheduler flight recorder: per-step/lifecycle records (ISSUE 7).
    app.router.add_get("/v1/api/flight", obs_api.get_flight)

    if STATIC_DIR.exists():
        app.router.add_static("/static", STATIC_DIR)

    async def _on_startup(app: web.Application) -> None:
        # Daily retention sweep — the reference defines a 180-day cleanup but
        # never calls it (tokens_usage_db.py:164); here it's actually wired.
        import asyncio

        # Graceful drain on SIGTERM (ISSUE 14): stop engine admissions,
        # let in-flight decodes finish under the drain deadline, flush
        # the usage recorder, then let aiohttp's own shutdown proceed.
        # Best-effort: non-main-thread loops (tests) can't install
        # signal handlers and don't need them.
        import signal

        def _on_sigterm() -> None:
            logger.info("SIGTERM: draining local engines before exit")
            asyncio.get_running_loop().create_task(_drain_and_exit())

        async def _drain_and_exit() -> None:
            try:
                await gw.drain_local_engines(restart=False)
            finally:
                # GracefulExit is a SystemExit: raised from a plain loop
                # callback it propagates out of run_forever and stops
                # web.run_app (a task would swallow it into its result).
                def _exit() -> None:
                    raise web.GracefulExit()
                asyncio.get_running_loop().call_soon(_exit)

        try:
            asyncio.get_running_loop().add_signal_handler(
                signal.SIGTERM, _on_sigterm)
        except (NotImplementedError, RuntimeError, ValueError):
            logger.debug("SIGTERM drain handler not installed",
                         exc_info=True)

        async def _retention_loop() -> None:
            while True:
                removed = await asyncio.to_thread(
                    gw.usage_db.cleanup_old_records, settings.usage_retention_days)
                if removed:
                    logger.info("usage retention: removed %d old rows", removed)
                await asyncio.sleep(24 * 3600)
        app["retention_task"] = asyncio.get_running_loop().create_task(
            _retention_loop())

    async def _on_cleanup(app: web.Application) -> None:
        task = app.get("retention_task")
        if task:
            task.cancel()
        await gw.close()

    app.on_startup.append(_on_startup)
    app.on_cleanup.append(_on_cleanup)
    return app


def run(settings: Settings | None = None) -> None:
    settings = settings or Settings.from_env()
    from ..utils.logging_setup import configure_logging
    configure_logging(settings.logs_dir or "logs", settings.log_level)
    if _maybe_run_follower(settings):
        return
    try:
        app = build_app(settings, local_factory=_default_local_factory())
    except Exception as e:
        logger.error("startup failed: %s", e)
        raise SystemExit(1)
    web.run_app(app, host=settings.gateway_host, port=settings.gateway_port,
                access_log=None)


def _maybe_run_follower(settings: Settings) -> bool:
    """Multi-host deployment (JAX_COORDINATOR_ADDRESS set): process 0 runs
    the HTTP frontend; every other process builds the SAME local engine and
    replays the coordinator's compiled-program calls over DCN until
    shutdown (SURVEY.md §7 hard part (4); parallel/multihost.py)."""
    import os
    if not os.environ.get("JAX_COORDINATOR_ADDRESS"):
        return False
    from ..parallel.mesh import init_distributed
    from ..parallel import multihost as mh
    init_distributed()
    if not mh.is_multihost() or mh.is_coordinator():
        return False         # coordinator serves HTTP as usual
    from ..config.loader import ConfigLoader
    from ..engine.engine import InferenceEngine
    loader = ConfigLoader(settings.config_dir or ".",
                          fallback_provider=settings.fallback_provider)
    local = [(name, d) for name, d in loader.providers.items()
             if d.type == "local" and d.engine is not None]
    if len(local) != 1:
        raise SystemExit(
            f"multihost follower needs exactly one local provider in "
            f"providers.json, found {len(local)}")
    import jax
    name, details = local[0]
    logger.info("follower %s: building engine for provider %r",
                jax.process_index(), name)
    engine = InferenceEngine(details.engine)
    engine.run_follower()
    return True


def _default_local_factory():
    """Lazily import the TPU engine provider factory (keeps JAX optional for
    proxy-only deployments)."""
    try:
        from ..providers.local import make_local_provider
        return make_local_provider
    except Exception:
        logger.warning("local TPU engine unavailable; type=local providers "
                       "will be rejected", exc_info=True)
        return None
