"""Usage statistics + provider-health API.

Parity with the reference's stats router (``api/v1/stats.py``):
``/v1/api/usage-stats/{period}`` with period ∈ {hour, day, week, month} over
windows of 24 h / 2 w / 15 w / 365 d (``stats.py:41-56``), and paginated
``/v1/api/usage-records`` (``stats.py:65-83``). Extended with avg TTFT and
tok/s columns from the extended usage schema, and (ISSUE 3) with
``/v1/api/health/providers`` — the live circuit-breaker view per provider:
state, windowed failure rate, cooldown remaining, lifetime opens, and the
last state transition. Configured providers with no traffic yet report as
implicitly closed so the operator sees the full roster, not just the
troubled part of it.
"""
from __future__ import annotations

import datetime as dt

from aiohttp import web

_WINDOWS = {
    "hour": dt.timedelta(hours=24),
    "day": dt.timedelta(weeks=2),
    "week": dt.timedelta(weeks=15),
    "month": dt.timedelta(days=365),
}


async def get_usage_stats(request: web.Request) -> web.Response:
    gw = request.app["gateway"]
    period = request.match_info["period"]
    window = _WINDOWS.get(period)
    if window is None:
        return web.json_response(
            {"detail": f"period must be one of {sorted(_WINDOWS)}"}, status=400)
    now = dt.datetime.now()
    start = (now - window).strftime("%Y-%m-%d %H:%M:%S")
    end = now.strftime("%Y-%m-%d %H:%M:%S")
    rows = await gw.usage_db.aggregated_async(period, start, end)
    return web.json_response({"period": period, "data": rows})


async def get_usage_records(request: web.Request) -> web.Response:
    gw = request.app["gateway"]
    try:
        limit = max(1, min(200, int(request.query.get("limit", "25"))))
        offset = max(0, int(request.query.get("offset", "0")))
    except ValueError:
        return web.json_response({"detail": "limit/offset must be ints"}, status=400)
    rows = await gw.usage_db.latest_async(limit, offset)
    total = await gw.usage_db.total_count_async()
    return web.json_response({"records": rows, "total": total,
                              "limit": limit, "offset": offset})


async def get_provider_health(request: web.Request) -> web.Response:
    """GET /v1/api/health/providers — breaker state per provider."""
    gw = request.app["gateway"]
    snapshot = gw.breakers.snapshot() if gw.breakers is not None else {}
    providers = {}
    for name, details in sorted(gw.loader.providers.items()):
        entry = snapshot.pop(name, None) or {
            "state": "closed", "state_code": 0.0,
            "failure_rate": 0.0, "window_requests": 0,
            "cooldown_remaining_s": 0.0, "opens": 0, "last_transition": None,
            "enabled": (details.breaker.enabled
                        if details.breaker is not None else True),
        }
        entry["type"] = details.type
        if details.type == "local":
            # Engine supervisor block (ISSUE 14): lifecycle state,
            # restart budget, heartbeat age — only for providers whose
            # engine is actually built (building one here would block
            # a health probe on a checkpoint load).
            for prov in gw.registry.instantiated():
                if prov[0] != name:
                    continue
                engine = getattr(prov[1], "engine", None)
                sup = getattr(engine, "supervisor", None)
                if sup is not None:
                    entry["supervisor"] = sup.stats()
                break
        providers[name] = entry
    # Breakers for providers since removed from config still report until
    # their registry entry ages out — visibility beats tidiness here.
    for name, entry in snapshot.items():
        entry["type"] = "removed"
        providers[name] = entry
    return web.json_response({"providers": providers})
