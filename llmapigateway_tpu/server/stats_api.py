"""Usage statistics API.

Parity with the reference's stats router (``api/v1/stats.py``):
``/v1/api/usage-stats/{period}`` with period ∈ {hour, day, week, month} over
windows of 24 h / 2 w / 15 w / 365 d (``stats.py:41-56``), and paginated
``/v1/api/usage-records`` (``stats.py:65-83``). Extended with avg TTFT and
tok/s columns from the extended usage schema.
"""
from __future__ import annotations

import datetime as dt

from aiohttp import web

_WINDOWS = {
    "hour": dt.timedelta(hours=24),
    "day": dt.timedelta(weeks=2),
    "week": dt.timedelta(weeks=15),
    "month": dt.timedelta(days=365),
}


async def get_usage_stats(request: web.Request) -> web.Response:
    gw = request.app["gateway"]
    period = request.match_info["period"]
    window = _WINDOWS.get(period)
    if window is None:
        return web.json_response(
            {"detail": f"period must be one of {sorted(_WINDOWS)}"}, status=400)
    now = dt.datetime.now()
    start = (now - window).strftime("%Y-%m-%d %H:%M:%S")
    end = now.strftime("%Y-%m-%d %H:%M:%S")
    rows = await gw.usage_db.aggregated_async(period, start, end)
    return web.json_response({"period": period, "data": rows})


async def get_usage_records(request: web.Request) -> web.Response:
    gw = request.app["gateway"]
    try:
        limit = max(1, min(200, int(request.query.get("limit", "25"))))
        offset = max(0, int(request.query.get("offset", "0")))
    except ValueError:
        return web.json_response({"detail": "limit/offset must be ints"}, status=400)
    rows = await gw.usage_db.latest_async(limit, offset)
    total = await gw.usage_db.total_count_async()
    return web.json_response({"records": rows, "total": total,
                              "limit": limit, "offset": offset})
