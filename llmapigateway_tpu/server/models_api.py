"""GET /v1/models and the agent-integration format transforms.

Parity with the reference's models router (``api/v1/models.py``):

* ``/v1/models`` — union of gateway rule models (``owned_by: "llmgateway"``,
  listed first) and the fallback provider's live model list; degrades
  gracefully when the upstream fetch fails (``models.py:224-312``). Unlike
  the reference — which snapshots rules at import time and never sees hot
  reloads (``models.py:14-16``, SURVEY.md §1) — this reads the live loader.
* ``/v1/models/AsOpenCodeFormat`` — opencode.json provider block: context/
  output limits, modality remap (``file``→``pdf``), reasoning-effort
  variants (``models.py:89-144``).
* ``/v1/models/AsGitHubCopilotFormat`` — chatLanguageModels.json entries:
  toolCalling always on, vision from input modalities, reasoning variants;
  gateway-local models forced vision+reasoning (``models.py:146-222``).
"""
from __future__ import annotations

import logging
import time
from typing import Any

from aiohttp import web

logger = logging.getLogger(__name__)

REASONING_VARIANTS = ["none", "minimal", "low", "medium", "high", "xhigh"]
# Defaults the reference hardcodes when upstream metadata is missing.
OPENCODE_DEFAULT_CONTEXT, OPENCODE_DEFAULT_OUTPUT = 200_000, 32_000
COPILOT_DEFAULT_CONTEXT, COPILOT_DEFAULT_OUTPUT = 400_000, 60_000


async def _gateway_models(gw) -> list[dict[str, Any]]:
    created = int(time.time())
    return [{"id": name, "object": "model", "created": created,
             "owned_by": "llmgateway"}
            for name in gw.loader.rules]


async def _upstream_models(gw) -> list[dict[str, Any]]:
    provider = await gw.registry.get(gw.settings.fallback_provider)
    if provider is None:
        return []
    models = await provider.list_models()
    return models or []


async def get_models(request: web.Request) -> web.Response:
    gw = request.app["gateway"]
    gateway_models = await _gateway_models(gw)
    include_fallback = request.query.get("includefallbackmodels", "true") \
        .lower() != "false"
    upstream = await _upstream_models(gw) if include_fallback else []
    seen = {m["id"] for m in gateway_models}
    merged = gateway_models + [m for m in upstream
                               if isinstance(m, dict) and m.get("id") not in seen]
    return web.json_response({"object": "list", "data": merged})


def _extract_modalities(model: dict[str, Any]) -> tuple[list[str], list[str]]:
    arch = model.get("architecture") or {}
    inputs = arch.get("input_modalities") or ["text"]
    outputs = arch.get("output_modalities") or ["text"]
    # Reference remaps "file" → "pdf" for opencode (models.py:36-66).
    inputs = ["pdf" if m == "file" else m for m in inputs]
    return inputs, outputs


def _reasoning_variants(model: dict[str, Any]) -> list[str]:
    supported = model.get("supported_parameters") or []
    if "reasoning" in supported or "include_reasoning" in supported:
        return REASONING_VARIANTS
    return []


async def get_models_as_opencode(request: web.Request) -> web.Response:
    gw = request.app["gateway"]
    gateway_models = await _gateway_models(gw)
    include_fallback = request.query.get("includefallbackmodels", "true") \
        .lower() != "false"
    upstream = await _upstream_models(gw) if include_fallback else []
    upstream_by_id = {m.get("id"): m for m in upstream if isinstance(m, dict)}

    models_block: dict[str, Any] = {}
    for m in gateway_models + [u for i, u in upstream_by_id.items()
                               if i not in {g["id"] for g in gateway_models}]:
        mid = m["id"]
        meta = upstream_by_id.get(mid, m)
        top = meta.get("top_provider") or {}
        context = top.get("context_length") or meta.get("context_length") \
            or OPENCODE_DEFAULT_CONTEXT
        output = top.get("max_completion_tokens") or OPENCODE_DEFAULT_OUTPUT
        inputs, _ = _extract_modalities(meta)
        entry: dict[str, Any] = {
            "name": meta.get("name", mid),
            "limit": {"context": context, "output": output},
            "modalities": {"input": inputs, "output": ["text"]},
        }
        variants = _reasoning_variants(meta)
        if variants or m.get("owned_by") == "llmgateway":
            entry["variants"] = {
                v: {"reasoning_effort": v} for v in (variants or REASONING_VARIANTS)
                if v != "none"}
        models_block[mid] = entry

    host = request.host or f"localhost:{gw.settings.gateway_port}"
    block = {
        "llmgateway": {
            "npm": "@ai-sdk/openai-compatible",
            "name": "LLM Gateway (TPU)",
            "options": {
                "baseURL": f"http://{host}/v1",
                "apiKey": "{env:GATEWAY_API_KEY}",
            },
            "models": models_block,
        }
    }
    return web.json_response(block)


async def get_models_as_github_copilot(request: web.Request) -> web.Response:
    gw = request.app["gateway"]
    gateway_models = await _gateway_models(gw)
    include_fallback = request.query.get("includefallbackmodels", "true") \
        .lower() != "false"
    upstream = await _upstream_models(gw) if include_fallback else []
    upstream_by_id = {m.get("id"): m for m in upstream if isinstance(m, dict)}
    gateway_ids = {g["id"] for g in gateway_models}

    out: list[dict[str, Any]] = []
    for m in gateway_models + [u for i, u in upstream_by_id.items()
                               if i not in gateway_ids]:
        mid = m["id"]
        meta = upstream_by_id.get(mid, m)
        is_local = m.get("owned_by") == "llmgateway"
        inputs, _ = _extract_modalities(meta)
        vision = "image" in inputs or is_local
        top = meta.get("top_provider") or {}
        entry = {
            "id": mid,
            "name": meta.get("name", mid),
            "toolCalling": True,
            "vision": vision,
            "maxInputTokens": top.get("context_length")
                or meta.get("context_length") or COPILOT_DEFAULT_CONTEXT,
            "maxOutputTokens": top.get("max_completion_tokens")
                or COPILOT_DEFAULT_OUTPUT,
        }
        variants = _reasoning_variants(meta)
        if variants or is_local:
            entry["reasoningEfforts"] = [v for v in REASONING_VARIANTS if v != "none"]
        out.append(entry)
    return web.json_response(out)
