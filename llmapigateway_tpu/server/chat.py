"""POST /v1/chat/completions — the gateway's core endpoint.

Thin HTTP shim over the routing engine (unlike the reference, whose handler
contains the whole fallback loop — ``api/v1/chat.py:41-198``). Body is parsed
as json5 for parity with the reference's lenient parsing (``chat.py:41``).
Streaming responses are committed (200, SSE headers) only after routing has
produced a primed stream, so upstream failures still fell back.

Reliability mapping (ISSUE 3): the client's ``x-request-timeout-ms`` header
(or ``timeout_ms`` body field) becomes the request's deadline budget;
exhaustion returns **504** with the partial-attempt log, an all-overloaded /
all-breaker-open chain returns **429** with a numeric ``Retry-After`` from
the engine's telemetry or the breakers' cooldowns, and everything else
keeps the reference's **503**.
"""
from __future__ import annotations

import functools
import logging
import math

import json5
from aiohttp import web

from ..obs import trace as obs_trace
from ..obs.slo import slo_from_headers
from ..providers.base import JSONCompletion, StreamingCompletion
from ..reliability.deadline import budget_ms_from_request
from ..server.usage_capture import UsageCollector
from .middleware import client_api_key

logger = logging.getLogger(__name__)


async def chat_completions(request: web.Request) -> web.StreamResponse:
    gw = request.app["gateway"]
    try:
        body = await request.text()
        payload = json5.loads(body)
        if not isinstance(payload, dict):
            raise ValueError("body must be a JSON object")
    except Exception as e:
        return web.json_response(
            {"error": {"message": f"invalid request body: {e}", "code": 400}},
            status=400)

    if "model" not in payload:
        return web.json_response(
            {"error": {"message": "missing required field 'model'", "code": 400}},
            status=400)

    timeout_ms = budget_ms_from_request(request.headers, payload)
    # Per-request SLO ask (ISSUE 7): x-slo-ttft-ms / x-slo-tpot-ms.
    # Rule-level defaults fill unset fields inside dispatch; the outcome
    # (met / violated+attributed) lands on /metrics and the usage row.
    slo = slo_from_headers(request.headers)

    observer_factory = functools.partial(
        _make_collector, payload=payload, gw=gw)

    outcome = await gw.router.dispatch(
        payload, client_api_key(request), observer_factory,
        timeout_ms=timeout_ms, request_id=request.get("request_id", ""),
        slo=slo)

    if outcome.error is not None or outcome.result is None:
        err = outcome.error
        detail = str(err) if err else "no providers succeeded"
        status = err.status if err and err.status in (429, 504) else 503
        headers = {}
        timings = obs_trace.server_timing_header()
        if timings:
            headers["x-gateway-timings"] = timings
        if status == 429:
            # Numeric Retry-After (RFC 9110 delay-seconds) from the engine's
            # step-time/queue-wait telemetry or the breakers' cooldowns.
            headers["Retry-After"] = str(
                max(1, math.ceil(err.retry_after_s or 1.0)))
        message = {
            429: f"Gateway overloaded. {detail}",
            504: f"Request deadline exceeded. {detail}",
        }.get(status, f"All fallback models failed. Last error: {detail}")
        return web.json_response(
            {"error": {"message": message, "code": status,
                       "attempts": outcome.attempts}},
            status=status, headers=headers)

    result = outcome.result
    if isinstance(result, JSONCompletion):
        # Per-phase latency summary for the client (Server-Timing style).
        # Non-streamed only: a streamed response's headers are on the wire
        # before the phases being summarized have happened.
        headers = {}
        timings = obs_trace.server_timing_header()
        if timings:
            headers["x-gateway-timings"] = timings
        return web.json_response(result.data, headers=headers)

    assert isinstance(result, StreamingCompletion)
    headers = {"Content-Type": "text/event-stream",
               "Cache-Control": "no-cache",
               "X-Accel-Buffering": "no",
               "Connection": "keep-alive"}
    # Streamed requests get the timing summary too (ISSUE 7 satellite):
    # the phases known at commit time (routing, provider attempts, the
    # engine's queued/prefill spans — everything up to first token) go in
    # a response-start header; the local provider additionally emits the
    # FULL summary, decode included, as the final usage frame's sibling
    # `gateway_timings` field, where post-commit phases exist.
    timings = obs_trace.server_timing_header()
    if timings:
        headers["x-gateway-timings"] = timings
    # Prepared responses bypass the header middleware; attach the id here.
    if request.get("request_id"):
        headers["x-request-id"] = request["request_id"]
    resp = web.StreamResponse(status=200, headers=headers)
    # The on-wire status for the request-end log, should the stream die
    # mid-flight (the middleware can't see it from a raised exception).
    request["prepared_status"] = 200
    await resp.prepare(request)
    with obs_trace.span("gateway.stream_drain", layer="gateway"):
        try:
            async for frame in result.frames:
                await resp.write(frame)
            await resp.write_eof()
        except ConnectionResetError:
            # Client hung up mid-stream; the provider generator's finally
            # block still fires (usage gets recorded with what was
            # streamed).
            logger.info("client disconnected mid-stream")
            await result.frames.aclose()
    return resp


def _make_collector(provider: str, model: str, *, payload: dict, gw) -> UsageCollector:
    settings = gw.settings
    # The write-behind recorder (ISSUE 14) duck-types UsageDB.insert:
    # stream-end observers enqueue instead of fsyncing SQLite inline.
    # Test-built GatewayApp stand-ins without a recorder fall through
    # to the raw DB.
    return UsageCollector(
        provider=provider, model=model,
        usage_db=getattr(gw, "usage_recorder", None) or gw.usage_db,
        request_payload=payload if settings.log_chat_messages else {},
        logs_dir=settings.logs_dir,
        log_chat_messages=settings.log_chat_messages,
        log_file_limit=settings.log_file_limit)
