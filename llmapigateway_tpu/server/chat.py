"""POST /v1/chat/completions — the gateway's core endpoint.

Thin HTTP shim over the routing engine (unlike the reference, whose handler
contains the whole fallback loop — ``api/v1/chat.py:41-198``). Body is parsed
as json5 for parity with the reference's lenient parsing (``chat.py:41``).
Streaming responses are committed (200, SSE headers) only after routing has
produced a primed stream, so upstream failures still fell back.
"""
from __future__ import annotations

import functools
import logging

import json5
from aiohttp import web

from ..providers.base import JSONCompletion, StreamingCompletion
from ..server.usage_capture import UsageCollector
from .middleware import client_api_key

logger = logging.getLogger(__name__)


async def chat_completions(request: web.Request) -> web.StreamResponse:
    gw = request.app["gateway"]
    try:
        body = await request.text()
        payload = json5.loads(body)
        if not isinstance(payload, dict):
            raise ValueError("body must be a JSON object")
    except Exception as e:
        return web.json_response(
            {"error": {"message": f"invalid request body: {e}", "code": 400}},
            status=400)

    if "model" not in payload:
        return web.json_response(
            {"error": {"message": "missing required field 'model'", "code": 400}},
            status=400)

    observer_factory = functools.partial(
        _make_collector, payload=payload, gw=gw)

    outcome = await gw.router.dispatch(
        payload, client_api_key(request), observer_factory)

    if outcome.error is not None or outcome.result is None:
        detail = str(outcome.error) if outcome.error else "no providers succeeded"
        return web.json_response(
            {"error": {"message": f"All fallback models failed. Last error: {detail}",
                       "code": 503, "attempts": outcome.attempts}},
            status=503)

    result = outcome.result
    if isinstance(result, JSONCompletion):
        return web.json_response(result.data)

    assert isinstance(result, StreamingCompletion)
    headers = {"Content-Type": "text/event-stream",
               "Cache-Control": "no-cache",
               "X-Accel-Buffering": "no",
               "Connection": "keep-alive"}
    # Prepared responses bypass the header middleware; attach the id here.
    if request.get("request_id"):
        headers["x-request-id"] = request["request_id"]
    resp = web.StreamResponse(status=200, headers=headers)
    await resp.prepare(request)
    try:
        async for frame in result.frames:
            await resp.write(frame)
        await resp.write_eof()
    except ConnectionResetError:
        # Client hung up mid-stream; the provider generator's finally block
        # still fires (usage gets recorded with what was streamed).
        logger.info("client disconnected mid-stream")
        await result.frames.aclose()
    return resp


def _make_collector(provider: str, model: str, *, payload: dict, gw) -> UsageCollector:
    settings = gw.settings
    return UsageCollector(
        provider=provider, model=model,
        usage_db=gw.usage_db,
        request_payload=payload if settings.log_chat_messages else {},
        logs_dir=settings.logs_dir,
        log_chat_messages=settings.log_chat_messages,
        log_file_limit=settings.log_file_limit)
