from .app import build_app, GatewayApp

__all__ = ["build_app", "GatewayApp"]
