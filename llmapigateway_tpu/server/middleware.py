"""aiohttp middlewares: CORS, request logging, bearer auth.

Parity targets:
* CORS — reference wires CORSMiddleware with configured origins
  (``main.py:69-75``).
* Request logging — per-request UUID, method/path/client, masked headers,
  duration + status, ``x-request-id`` response header, ``/health`` excluded
  (``middleware/request_logging.py:17-90``).
* Auth — bearer-token check against the gateway key. The reference *intends*
  to guard chat completions but its path check has a typo and never matches
  (``middleware/auth.py:17`` — ``/chat/completion`` without the final "s");
  here the **intended** behavior is implemented: all ``/v1/*`` endpoints are
  protected except health; open when no key is configured (``auth.py:37-42``).
"""
from __future__ import annotations

import logging
import time
import uuid

from aiohttp import web

from ..utils.logging_setup import mask_headers

logger = logging.getLogger("gateway.request")

UNPROTECTED_PATHS = frozenset(("/health", "/", "/favicon.ico"))


def cors_middleware(allowed_origins: list[str]):
    allow_all = "*" in allowed_origins

    @web.middleware
    async def middleware(request: web.Request, handler):
        origin = request.headers.get("Origin")
        preflight = (request.method == "OPTIONS" and origin
                     and "Access-Control-Request-Method" in request.headers)
        if preflight:
            # Only genuine CORS preflights short-circuit routing; a plain
            # OPTIONS to an unknown route still 404s through the router.
            resp = web.Response(status=204)
        else:
            resp = await handler(request)
        if origin and (allow_all or origin in allowed_origins):
            resp.headers["Access-Control-Allow-Origin"] = "*" if allow_all else origin
            resp.headers["Access-Control-Allow-Methods"] = "GET, POST, OPTIONS"
            resp.headers["Access-Control-Allow-Headers"] = "Authorization, Content-Type"
        if not allow_all:
            # EVERY response varies by requester origin (including ones to
            # no-Origin or disallowed-Origin requests — a cache storing
            # those unkeyed would serve them, CORS-headerless, to allowed
            # origins). Append, never clobber a handler's own Vary.
            vary = resp.headers.get("Vary")
            if vary is None:
                resp.headers["Vary"] = "Origin"
            elif "origin" not in vary.lower():
                resp.headers["Vary"] = vary + ", Origin"
        return resp

    return middleware


def _redacted_payload(raw: bytes) -> dict | None:
    """Parse a chat-completions POST body and redact message/tool contents —
    the reference logs payloads this way (request_logging.py:49-61): shape
    and params are diagnostic, contents are private."""
    import json
    try:
        payload = json.loads(raw)
    except Exception:
        return None
    if not isinstance(payload, dict):
        return None
    for key in ("messages", "tools"):
        val = payload.get(key)
        if isinstance(val, list):
            payload[key] = f"<redacted: {len(val)} {key}>"
        elif val is not None:
            payload[key] = "<redacted>"
    return payload


def request_logging_middleware():
    @web.middleware
    async def middleware(request: web.Request, handler):
        if request.path == "/health":
            return await handler(request)
        req_id = uuid.uuid4().hex[:16]
        request["request_id"] = req_id
        start = time.monotonic()
        log_extra = {
            "request_id": req_id, "method": request.method,
            "path": request.path, "client": request.remote,
            "headers": mask_headers(dict(request.headers))}
        if (request.method == "POST"
                and request.path.endswith("/chat/completions")):
            # aiohttp caches the body, so the handler can re-read it.
            payload = _redacted_payload(await request.read())
            if payload is not None:
                log_extra["payload"] = payload
        logger.info("request start", extra=log_extra)
        try:
            resp = await handler(request)
            status = resp.status
            return resp
        except web.HTTPException as e:
            status = e.status
            raise
        except Exception:
            status = 500
            raise
        finally:
            duration_ms = (time.monotonic() - start) * 1000.0
            logger.info("request end", extra={
                "request_id": req_id, "status": status,
                "duration_ms": round(duration_ms, 2)})

    return middleware


def request_id_header_middleware():
    @web.middleware
    async def middleware(request: web.Request, handler):
        resp = await handler(request)
        req_id = request.get("request_id")
        # Streaming responses are already prepared (headers on the wire) by
        # the time the handler returns; those set the header themselves.
        if req_id and not resp.prepared:
            resp.headers["x-request-id"] = req_id
        return resp

    return middleware


def auth_middleware(gateway_api_key: str | None):
    @web.middleware
    async def middleware(request: web.Request, handler):
        # UI pages (/v1/ui/*) are plain HTML a browser navigates to directly —
        # it cannot attach a Bearer header, so they stay open; the data APIs
        # they call (/v1/api/*, /v1/config/*) remain protected and the pages'
        # JS sends the key the operator enters.
        if not gateway_api_key or request.path in UNPROTECTED_PATHS \
                or request.path.startswith("/static") \
                or request.path.startswith("/v1/ui/") \
                or request.method == "OPTIONS":
            return await handler(request)
        auth = request.headers.get("Authorization", "")
        if not auth.startswith("Bearer "):
            return web.json_response(
                {"error": {"message": "Missing bearer token", "code": 401}},
                status=401)
        if auth[len("Bearer "):].strip() != gateway_api_key:
            return web.json_response(
                {"error": {"message": "Invalid API key", "code": 403}},
                status=403)
        return await handler(request)

    return middleware


def client_api_key(request: web.Request) -> str:
    """The client's bearer token (used as the rotation identity,
    cf. chat.py:66)."""
    auth = request.headers.get("Authorization", "")
    if auth.startswith("Bearer "):
        return auth[len("Bearer "):].strip()
    return "anonymous"
