"""aiohttp middlewares: CORS, request logging + observability, bearer auth.

Parity targets:
* CORS — reference wires CORSMiddleware with configured origins
  (``main.py:69-75``).
* Request logging — per-request UUID, method/path/client, masked headers,
  duration + status, ``x-request-id`` response header, ``/health`` excluded
  (``middleware/request_logging.py:17-90``).
* Auth — bearer-token check against the gateway key. The reference *intends*
  to guard chat completions but its path check has a typo and never matches
  (``middleware/auth.py:17`` — ``/chat/completion`` without the final "s");
  here the **intended** behavior is implemented: all ``/v1/*`` endpoints are
  protected except health; open when no key is configured (``auth.py:37-42``).

Observability (ISSUE 4): the logging middleware is also the HTTP layer's
instrumentation point — it owns the request id (honoring a valid
client-supplied ``x-request-id``), opens the request's root trace span,
and records the ``gateway_http_*`` metrics (in-flight, duration by route
template, completions by status).
"""
from __future__ import annotations

import logging
import re
import time
import uuid

from aiohttp import web

from ..obs import metrics as obs_metrics
from ..obs import trace as obs_trace
from ..utils.logging_setup import mask_headers

logger = logging.getLogger("gateway.request")

UNPROTECTED_PATHS = frozenset(("/health", "/metrics", "/", "/favicon.ico"))

# Paths excluded from per-request logging/metrics/tracing: health probes
# and the metrics scrape itself poll every few seconds — logging them
# drowns the signal, and a scrape-counts-scrapes loop helps nobody.
UNOBSERVED_PATHS = frozenset(("/health", "/metrics"))

# A client-supplied x-request-id is honored only in this shape; anything
# else (too long, exotic characters that would corrupt logs or upstream
# headers) falls back to a generated id.
_REQUEST_ID_RE = re.compile(r"[A-Za-z0-9_-]{1,64}")


def resolve_request_id(request: web.Request) -> str:
    supplied = request.headers.get("x-request-id", "")
    if supplied and _REQUEST_ID_RE.fullmatch(supplied):
        return supplied
    return uuid.uuid4().hex[:16]


def _route_template(request: web.Request) -> str:
    """The matched route's template (``/v1/api/trace/{request_id}``) — a
    bounded metrics label, unlike the raw path."""
    try:
        resource = request.match_info.route.resource
        canonical = resource.canonical if resource is not None else None
    except AttributeError:
        canonical = None
    return canonical or "unmatched"


def cors_middleware(allowed_origins: list[str]):
    allow_all = "*" in allowed_origins

    @web.middleware
    async def middleware(request: web.Request, handler):
        origin = request.headers.get("Origin")
        preflight = (request.method == "OPTIONS" and origin
                     and "Access-Control-Request-Method" in request.headers)
        if preflight:
            # Only genuine CORS preflights short-circuit routing; a plain
            # OPTIONS to an unknown route still 404s through the router.
            resp = web.Response(status=204)
        else:
            resp = await handler(request)
        if origin and (allow_all or origin in allowed_origins):
            resp.headers["Access-Control-Allow-Origin"] = "*" if allow_all else origin
            resp.headers["Access-Control-Allow-Methods"] = "GET, POST, OPTIONS"
            resp.headers["Access-Control-Allow-Headers"] = "Authorization, Content-Type"
        if not allow_all:
            # EVERY response varies by requester origin (including ones to
            # no-Origin or disallowed-Origin requests — a cache storing
            # those unkeyed would serve them, CORS-headerless, to allowed
            # origins). Append, never clobber a handler's own Vary.
            vary = resp.headers.get("Vary")
            if vary is None:
                resp.headers["Vary"] = "Origin"
            elif "origin" not in vary.lower():
                resp.headers["Vary"] = vary + ", Origin"
        return resp

    return middleware


def _redacted_payload(raw: bytes) -> dict | None:
    """Parse a chat-completions POST body and redact message/tool contents —
    the reference logs payloads this way (request_logging.py:49-61): shape
    and params are diagnostic, contents are private."""
    import json
    try:
        payload = json.loads(raw)
    except Exception:
        return None
    if not isinstance(payload, dict):
        return None
    for key in ("messages", "tools"):
        val = payload.get(key)
        if isinstance(val, list):
            payload[key] = f"<redacted: {len(val)} {key}>"
        elif val is not None:
            payload[key] = "<redacted>"
    return payload


def request_logging_middleware(metrics: "obs_metrics.GatewayMetrics | None" = None,
                               tracer: "obs_trace.Tracer | None" = None,
                               clock=time.monotonic):
    """Request logging + the HTTP layer's metrics and trace root.

    ``metrics``/``tracer`` default to None (pure logging) so existing
    embedders keep working; server/app.py passes the gateway's instances.
    """
    @web.middleware
    async def middleware(request: web.Request, handler):
        if request.path in UNOBSERVED_PATHS:
            return await handler(request)
        req_id = resolve_request_id(request)
        request["request_id"] = req_id
        start = clock()
        route = _route_template(request)
        log_extra = {
            "request_id": req_id, "method": request.method,
            "path": request.path, "client": request.remote,
            "headers": mask_headers(dict(request.headers))}
        if (request.method == "POST"
                and request.path.endswith("/chat/completions")):
            # aiohttp caches the body, so the handler can re-read it.
            payload = _redacted_payload(await request.read())
            if payload is not None:
                log_extra["payload"] = payload
        logger.info("request start", extra=log_extra)
        if metrics is not None:
            metrics.http_in_flight.inc()
        stream_error = False
        try:
            if tracer is not None:
                with tracer.trace(req_id) as tr:
                    tr.root.attrs["method"] = request.method
                    tr.root.attrs["path"] = request.path
                    resp = await handler(request)
                    status = resp.status
                    tr.root.attrs["status"] = status
                    return resp
            resp = await handler(request)
            status = resp.status
            return resp
        except web.HTTPException as e:
            status = e.status
            raise
        except Exception:
            # A streaming handler that raised after committing already put
            # its status on the wire — record what's known (the prepared
            # status + the fact the stream died), not a fictitious 500.
            prepared = request.get("prepared_status")
            stream_error = prepared is not None
            status = prepared if prepared is not None else 500
            raise
        finally:
            duration_s = clock() - start
            # End lines must be greppable on their own: method/path ride
            # along with the status (ISSUE 4 satellite — previously only
            # request_id/status/duration).
            end_extra = {
                "request_id": req_id, "method": request.method,
                "path": request.path, "status": status,
                "duration_ms": round(duration_s * 1000.0, 2)}
            if stream_error:
                end_extra["stream_error"] = True
            logger.info("request end", extra=end_extra)
            if metrics is not None:
                metrics.http_in_flight.dec()
                metrics.http_requests_total.labels(
                    method=request.method, path=route,
                    status=str(status)).inc()
                metrics.http_request_duration_seconds.labels(
                    method=request.method, path=route).observe(duration_s)

    return middleware


def request_id_header_middleware():
    @web.middleware
    async def middleware(request: web.Request, handler):
        resp = await handler(request)
        req_id = request.get("request_id")
        # Streaming responses are already prepared (headers on the wire) by
        # the time the handler returns; those set the header themselves.
        if req_id and not resp.prepared:
            resp.headers["x-request-id"] = req_id
        return resp

    return middleware


def auth_middleware(gateway_api_key: str | None):
    @web.middleware
    async def middleware(request: web.Request, handler):
        # UI pages (/v1/ui/*) are plain HTML a browser navigates to directly —
        # it cannot attach a Bearer header, so they stay open; the data APIs
        # they call (/v1/api/*, /v1/config/*) remain protected and the pages'
        # JS sends the key the operator enters.
        if not gateway_api_key or request.path in UNPROTECTED_PATHS \
                or request.path.startswith("/static") \
                or request.path.startswith("/v1/ui/") \
                or request.method == "OPTIONS":
            return await handler(request)
        auth = request.headers.get("Authorization", "")
        if not auth.startswith("Bearer "):
            return web.json_response(
                {"error": {"message": "Missing bearer token", "code": 401}},
                status=401)
        if auth[len("Bearer "):].strip() != gateway_api_key:
            return web.json_response(
                {"error": {"message": "Invalid API key", "code": 403}},
                status=403)
        return await handler(request)

    return middleware


def client_api_key(request: web.Request) -> str:
    """The client's bearer token (used as the rotation identity,
    cf. chat.py:66)."""
    auth = request.headers.get("Authorization", "")
    if auth.startswith("Bearer "):
        return auth[len("Bearer "):].strip()
    return "anonymous"
