"""Observability API: engine stats and on-demand device trace capture.

The reference has no tracing/profiling beyond a per-request UUID and
duration log (SURVEY.md §5 "Tracing / profiling" — ``request_logging.py:23``,
``:85-86``); the TPU build adds the device-side story the reference never
needed: ``jax.profiler`` trace capture (viewable in TensorBoard/Perfetto)
plus live serving-engine stats (slots, queue depth, paged-KV occupancy),
since TTFT/throughput are north-star metrics here (BASELINE.md).

Endpoints (wired in server/app.py):

* ``GET  /v1/api/engine-stats`` — per-local-provider engine stats + device
  inventory. Cheap; safe to poll.
* ``GET  /v1/api/roofline`` — the roofline slice of those stats (achieved
  GB/s from the engine's bytes-touched model × measured step time, burst
  depth / prefill-aware clamp counters, queue wait), one row per local
  engine — what the bench ladder and the stats UI read to track the
  0.478→1.0 HBM-roofline trajectory (ISSUE 2). Since ISSUE 8 each engine
  block also carries the PER-KERNEL cost table (one row per compiled
  executable variant: calls, measured step walls joined from the flight
  ring, cost_analysis FLOPs/bytes, achieved GB/s, roofline fraction),
  the name of the single worst kernel, and the HBM ledger. Cheap; safe
  to poll.
* ``POST /v1/api/profiler/trace?duration_ms=N`` — capture a profiler trace
  of the next N ms of live traffic into ``<logs_dir>/profiles/<name>``;
  returns the directory path. SINGLE-FLIGHT: a concurrent capture gets
  409 immediately (``jax.profiler`` state is process-global — a second
  ``start_trace`` would corrupt the first). Capture boundaries are
  stamped into each engine's flight ring (``profile`` records), so a
  Perfetto view of the capture cross-links to the exact scheduler seqs
  it covered; old trace dirs are pruned to ``MAX_TRACE_DIRS``.
"""
from __future__ import annotations

import asyncio
import logging
import shutil
import threading
import time
from pathlib import Path
from typing import Any

from aiohttp import web

logger = logging.getLogger(__name__)

_trace_lock = asyncio.Lock()

MAX_TRACE_MS = 30_000
DEFAULT_TRACE_MS = 2_000
# Bounded retention: a capture can be hundreds of MB; keep the newest N
# trace dirs and delete the rest after each successful capture.
MAX_TRACE_DIRS = 8

# Device-inventory probe state. jax.devices() initializes the backend on
# first call — seconds normally, but through a DEAD remote-TPU tunnel it
# hangs FOREVER (observed: the axon relay dies for hours and the init never
# returns). A stats poll must never inherit that fate: exactly ONE daemon
# thread probes, requests wait a bounded time, and an unfinished probe is
# reported as status "initializing" instead of hanging the endpoint.
_dev_state: dict[str, Any] = {"status": "unprobed", "devices": [],
                              "probe_started": 0.0}
_dev_lock = threading.Lock()

DEVICE_PROBE_WAIT_S = 5.0


def _start_device_probe() -> None:
    with _dev_lock:
        # "ok" is cached for the process lifetime; "initializing" means a
        # probe thread is still out (possibly hung — never stack more).
        # An "unavailable" FAILURE is retried on the next poll: transient
        # causes (another process briefly holding the TPU runtime) heal.
        if _dev_state["status"] in ("initializing", "ok"):
            return
        _dev_state["status"] = "initializing"
        _dev_state["probe_started"] = time.monotonic()

    def work():
        try:
            import jax
            devs = [{"id": d.id, "platform": d.platform,
                     "kind": d.device_kind} for d in jax.devices()]
            with _dev_lock:
                _dev_state.update(status="ok", devices=devs)
        except Exception as e:      # proxy-only deployment without JAX
            with _dev_lock:
                _dev_state.update(status=f"unavailable: {e!r:.120}",
                                  devices=[])
    threading.Thread(target=work, daemon=True,
                     name="engine-stats-device-probe").start()


def _local_engines(gw) -> list[tuple[str, Any]]:
    out = []
    for name, prov in gw.registry.instantiated():
        engine = getattr(prov, "engine", None)
        if engine is not None:
            out.append((name, engine))
    return out


async def get_engine_stats(request: web.Request) -> web.Response:
    gw = request.app["gateway"]
    engines = {name: eng.stats() for name, eng in _local_engines(gw)}
    _start_device_probe()
    # Wait only while the probe is *young*: a thread that has been out
    # longer than the wait budget is presumed hung on a dead tunnel, and
    # every subsequent poll returns "initializing" immediately instead of
    # each burning the full 5 s. (.get: tests monkeypatch _dev_state.)
    deadline = _dev_state.get("probe_started",
                              time.monotonic()) + DEVICE_PROBE_WAIT_S
    while (_dev_state["status"] == "initializing"
           and time.monotonic() < deadline):
        await asyncio.sleep(0.05)
    return web.json_response({
        "engines": engines,
        "devices": _dev_state["devices"],
        "device_status": _dev_state["status"],
    })


# The roofline slice of an engine's stats() dict: bandwidth model,
# step-time gauge, burst-depth controller, and admission-wait counters.
ROOFLINE_KEYS = (
    "achieved_gbps", "roofline_fraction", "hbm_bytes_per_step",
    "decode_ms_per_step", "decode_tok_s",
    "burst_depth_last", "burst_busy_clamps", "burst_depth_hist",
    "burst_step_ms_fit", "burst_fixed_cost_ms", "burst_walls_ms",
    "queue_wait_ms_ema", "queue_wait_ms_max", "queue_waits",
    "running", "queued", "pages_per_block",
)


async def get_roofline(request: web.Request) -> web.Response:
    """Per-engine roofline/scheduler counters — stats() filtered to the
    fields an operator (or the bench ladder) plots over time — plus the
    ISSUE 8 per-kernel table: which compiled executable is furthest off
    the HBM roof, with how much of the step time. The decode/spec rows'
    ``hbm_bytes_per_step`` use the same bytes-touched model as the
    aggregate, so the table and the aggregate reconcile by
    construction; the ``xla_*`` columns carry the raw cost_analysis."""
    gw = request.app["gateway"]
    engines = {}
    for name, eng in _local_engines(gw):
        s = eng.stats()
        block = {k: s[k] for k in ROOFLINE_KEYS if k in s}
        if hasattr(eng, "kernel_table"):
            from ..obs.device import worst_kernel
            kernels = getattr(eng, "kernels", None)
            if kernels is not None and kernels.costs_pending():
                # AOT lower+compile for cost_analysis can cost seconds
                # at 8B scale — pay it off-loop, once per new variant,
                # at read time (this endpoint is on-demand diagnostics).
                await asyncio.to_thread(kernels.resolve_costs)
            rows = eng.kernel_table()
            block["kernels"] = rows
            worst = worst_kernel(rows)
            if worst is not None:
                block["worst_kernel"] = worst
        block["hbm"] = {k: v for k, v in s.items()
                        if k.startswith("hbm_")}
        engines[name] = block
    return web.json_response({"engines": engines})


def _prune_trace_dirs(profiles_dir: Path,
                      keep: int = MAX_TRACE_DIRS) -> list[str]:
    """Delete all but the newest ``keep`` capture dirs (names sort
    chronologically). Synchronous — called via ``asyncio.to_thread``."""
    try:
        dirs = sorted((d for d in profiles_dir.iterdir() if d.is_dir()),
                      key=lambda d: d.name)
    except OSError:
        return []
    deleted: list[str] = []
    for d in (dirs[:-keep] if keep > 0 else dirs):
        try:
            shutil.rmtree(d)
            deleted.append(d.name)
        except OSError:
            logger.warning("failed to prune trace dir %s", d)
    return deleted


def _stamp_flight(gw, flag: int, rid: str) -> dict[str, int]:
    """Record a PROF capture-boundary into every local engine's flight
    ring and return engine → seq. Runs on the event loop — the ring's
    single-writer thread for an in-process gateway — so a capture's
    covered seq window is readable from ``GET /v1/api/flight``."""
    from ..obs.flight import PROF
    seqs: dict[str, int] = {}
    for name, eng in _local_engines(gw):
        rec = getattr(eng, "flight", None)
        if rec is not None:
            seqs[name] = rec.record(PROF, flag=flag, rid=rid)
    return seqs


async def capture_trace(request: web.Request) -> web.Response:
    try:
        import jax
    except Exception:
        return web.json_response(
            {"detail": "jax unavailable in this deployment"}, status=501)

    try:
        duration_ms = int(request.query.get("duration_ms", DEFAULT_TRACE_MS))
    except ValueError:
        return web.json_response({"detail": "duration_ms must be an integer"},
                                 status=400)
    duration_ms = max(100, min(duration_ms, MAX_TRACE_MS))

    # Single-flight guard: ``jax.profiler`` trace state is process-global,
    # so a second concurrent capture must 409 instead of queueing behind
    # the lock (the caller asked for a capture of NOW, not of whenever
    # the current one ends — and a queued start_trace against a profiler
    # mid-teardown has corrupted global state in practice). No awaits
    # between the check and the acquire, so two handlers cannot both
    # pass; acquire() on an uncontended asyncio.Lock is synchronous.
    if _trace_lock.locked():
        return web.json_response(
            {"detail": "a trace capture is already running"}, status=409)
    await _trace_lock.acquire()
    try:
        gw = request.app["gateway"]
        logs_dir = Path(gw.settings.logs_dir or "logs")
        profiles_dir = logs_dir / "profiles"
        out_dir = profiles_dir / time.strftime("trace-%Y%m%d-%H%M%S")
        out_dir.mkdir(parents=True, exist_ok=True)
        logger.info("profiler: capturing %d ms trace to %s",
                    duration_ms, out_dir)
        # start/stop_trace do blocking work (stop serializes the whole
        # device trace to disk — can be hundreds of MB) — keep it off the
        # event loop so in-flight SSE streams don't stall.
        try:
            await asyncio.to_thread(jax.profiler.start_trace, str(out_dir))
        except Exception as e:
            # Profiler already active outside this endpoint (an operator's
            # manual start_trace, or a crashed capture) — surface it as a
            # conflict instead of corrupting that session's state.
            logger.warning("profiler start failed: %r", e)
            return web.json_response(
                {"detail": f"profiler start failed: {e!r:.200}"},
                status=409)
        # Capture boundaries into the flight rings (ISSUE 8): the seqs
        # returned here bracket exactly the scheduler records the XLA
        # capture covers — the Perfetto cross-link between planes.
        from ..obs.flight import PROF_START, PROF_STOP
        start_seqs = _stamp_flight(gw, PROF_START, out_dir.name)
        try:
            # Sleep while live traffic runs under the trace; the engine
            # loop and in-flight requests keep executing on the loop.
            await asyncio.sleep(duration_ms / 1000.0)
        finally:
            stop_seqs = _stamp_flight(gw, PROF_STOP, out_dir.name)
            await asyncio.to_thread(jax.profiler.stop_trace)
        pruned = await asyncio.to_thread(_prune_trace_dirs, profiles_dir)
    finally:
        _trace_lock.release()

    return web.json_response({
        "trace_dir": str(out_dir),
        "duration_ms": duration_ms,
        # Per-engine [start_seq, stop_seq] windows into /v1/api/flight.
        "flight_seqs": {name: [start_seqs.get(name), stop_seqs.get(name)]
                        for name in set(start_seqs) | set(stop_seqs)},
        "pruned_trace_dirs": pruned,
        "hint": "view with: tensorboard --logdir <trace_dir> "
                "(Profile tab) or upload to ui.perfetto.dev; "
                "flight_seqs bracket the scheduler records the capture "
                "covers (tools/flight_report.py renders both planes)",
    })
