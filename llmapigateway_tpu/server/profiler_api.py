"""Observability API: engine stats and on-demand device trace capture.

The reference has no tracing/profiling beyond a per-request UUID and
duration log (SURVEY.md §5 "Tracing / profiling" — ``request_logging.py:23``,
``:85-86``); the TPU build adds the device-side story the reference never
needed: ``jax.profiler`` trace capture (viewable in TensorBoard/Perfetto)
plus live serving-engine stats (slots, queue depth, paged-KV occupancy),
since TTFT/throughput are north-star metrics here (BASELINE.md).

Endpoints (wired in server/app.py):

* ``GET  /v1/api/engine-stats`` — per-local-provider engine stats + device
  inventory. Cheap; safe to poll.
* ``GET  /v1/api/roofline`` — the roofline slice of those stats (achieved
  GB/s from the engine's bytes-touched model × measured step time, burst
  depth / prefill-aware clamp counters, queue wait), one row per local
  engine — what the bench ladder and the stats UI read to track the
  0.478→1.0 HBM-roofline trajectory (ISSUE 2). Cheap; safe to poll.
* ``POST /v1/api/profiler/trace?duration_ms=N`` — capture a profiler trace
  of the next N ms of live traffic into ``<logs_dir>/profiles/<name>``;
  returns the directory path. One capture at a time.
"""
from __future__ import annotations

import asyncio
import logging
import threading
import time
from pathlib import Path
from typing import Any

from aiohttp import web

logger = logging.getLogger(__name__)

_trace_lock = asyncio.Lock()

MAX_TRACE_MS = 30_000
DEFAULT_TRACE_MS = 2_000

# Device-inventory probe state. jax.devices() initializes the backend on
# first call — seconds normally, but through a DEAD remote-TPU tunnel it
# hangs FOREVER (observed: the axon relay dies for hours and the init never
# returns). A stats poll must never inherit that fate: exactly ONE daemon
# thread probes, requests wait a bounded time, and an unfinished probe is
# reported as status "initializing" instead of hanging the endpoint.
_dev_state: dict[str, Any] = {"status": "unprobed", "devices": [],
                              "probe_started": 0.0}
_dev_lock = threading.Lock()

DEVICE_PROBE_WAIT_S = 5.0


def _start_device_probe() -> None:
    with _dev_lock:
        # "ok" is cached for the process lifetime; "initializing" means a
        # probe thread is still out (possibly hung — never stack more).
        # An "unavailable" FAILURE is retried on the next poll: transient
        # causes (another process briefly holding the TPU runtime) heal.
        if _dev_state["status"] in ("initializing", "ok"):
            return
        _dev_state["status"] = "initializing"
        _dev_state["probe_started"] = time.monotonic()

    def work():
        try:
            import jax
            devs = [{"id": d.id, "platform": d.platform,
                     "kind": d.device_kind} for d in jax.devices()]
            with _dev_lock:
                _dev_state.update(status="ok", devices=devs)
        except Exception as e:      # proxy-only deployment without JAX
            with _dev_lock:
                _dev_state.update(status=f"unavailable: {e!r:.120}",
                                  devices=[])
    threading.Thread(target=work, daemon=True,
                     name="engine-stats-device-probe").start()


def _local_engines(gw) -> list[tuple[str, Any]]:
    out = []
    for name, prov in gw.registry.instantiated():
        engine = getattr(prov, "engine", None)
        if engine is not None:
            out.append((name, engine))
    return out


async def get_engine_stats(request: web.Request) -> web.Response:
    gw = request.app["gateway"]
    engines = {name: eng.stats() for name, eng in _local_engines(gw)}
    _start_device_probe()
    # Wait only while the probe is *young*: a thread that has been out
    # longer than the wait budget is presumed hung on a dead tunnel, and
    # every subsequent poll returns "initializing" immediately instead of
    # each burning the full 5 s. (.get: tests monkeypatch _dev_state.)
    deadline = _dev_state.get("probe_started",
                              time.monotonic()) + DEVICE_PROBE_WAIT_S
    while (_dev_state["status"] == "initializing"
           and time.monotonic() < deadline):
        await asyncio.sleep(0.05)
    return web.json_response({
        "engines": engines,
        "devices": _dev_state["devices"],
        "device_status": _dev_state["status"],
    })


# The roofline slice of an engine's stats() dict: bandwidth model,
# step-time gauge, burst-depth controller, and admission-wait counters.
ROOFLINE_KEYS = (
    "achieved_gbps", "roofline_fraction", "hbm_bytes_per_step",
    "decode_ms_per_step", "decode_tok_s",
    "burst_depth_last", "burst_busy_clamps", "burst_depth_hist",
    "burst_step_ms_fit", "burst_fixed_cost_ms", "burst_walls_ms",
    "queue_wait_ms_ema", "queue_wait_ms_max", "queue_waits",
    "running", "queued", "pages_per_block",
)


async def get_roofline(request: web.Request) -> web.Response:
    """Per-engine roofline/scheduler counters — stats() filtered to the
    fields an operator (or the bench ladder) plots over time."""
    gw = request.app["gateway"]
    engines = {}
    for name, eng in _local_engines(gw):
        s = eng.stats()
        engines[name] = {k: s[k] for k in ROOFLINE_KEYS if k in s}
    return web.json_response({"engines": engines})


async def capture_trace(request: web.Request) -> web.Response:
    try:
        import jax
    except Exception:
        return web.json_response(
            {"detail": "jax unavailable in this deployment"}, status=501)

    try:
        duration_ms = int(request.query.get("duration_ms", DEFAULT_TRACE_MS))
    except ValueError:
        return web.json_response({"detail": "duration_ms must be an integer"},
                                 status=400)
    duration_ms = max(100, min(duration_ms, MAX_TRACE_MS))

    if _trace_lock.locked():
        return web.json_response(
            {"detail": "a trace capture is already running"}, status=409)

    gw = request.app["gateway"]
    logs_dir = Path(gw.settings.logs_dir or "logs")
    out_dir = logs_dir / "profiles" / time.strftime("trace-%Y%m%d-%H%M%S")
    out_dir.mkdir(parents=True, exist_ok=True)

    async with _trace_lock:
        logger.info("profiler: capturing %d ms trace to %s",
                    duration_ms, out_dir)
        # start/stop_trace do blocking work (stop serializes the whole
        # device trace to disk — can be hundreds of MB) — keep it off the
        # event loop so in-flight SSE streams don't stall.
        await asyncio.to_thread(jax.profiler.start_trace, str(out_dir))
        try:
            # Sleep while live traffic runs under the trace; the engine loop
            # and any in-flight requests keep executing on the event loop.
            await asyncio.sleep(duration_ms / 1000.0)
        finally:
            await asyncio.to_thread(jax.profiler.stop_trace)

    return web.json_response({
        "trace_dir": str(out_dir),
        "duration_ms": duration_ms,
        "hint": "view with: tensorboard --logdir <trace_dir> "
                "(Profile tab) or upload to ui.perfetto.dev",
    })
