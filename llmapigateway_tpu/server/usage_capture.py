"""Per-request usage capture: transcript files + usage ledger + TTFT/tok/s.

Replaces the reference's tee-middleware + re-parsing background thread
(``middleware/chat_logging.py``): providers parse their own stream once and
feed this observer directly (SURVEY.md §3.2 fix). Behavior kept:

* per-request transcript files ``logs/YYYY-MM-DD_HH-MM-SS.mmm.txt`` with a
  token/cost header block (``chat_logging.py:22-67``), only when
  ``LOG_CHAT_MESSAGES`` is enabled; pruned beyond ``LOG_FILE_LIMIT``
  (``chat_logging.py:59-65``);
* usage extraction incl. reasoning/cached token details and cost, with
  reasoning subtracted from completion (``chat_logging.py:233-272``);
* ledger inserts that never break serving.

Extended: wall-clock TTFT and decode tokens/sec are recorded per request —
the BASELINE north-star metrics.
"""
from __future__ import annotations

import asyncio
import logging
import time
from dataclasses import dataclass, field
from pathlib import Path
from typing import Any

from ..db.usage import UsageDB, UsageRecord

logger = logging.getLogger(__name__)


def extract_usage_fields(usage: dict[str, Any]) -> dict[str, Any]:
    """Normalize an OpenAI-style usage object (cf. chat_logging.py:233-272)."""
    prompt = int(usage.get("prompt_tokens") or 0)
    completion = int(usage.get("completion_tokens") or 0)
    total = int(usage.get("total_tokens") or (prompt + completion))
    details = usage.get("completion_tokens_details") or {}
    reasoning = int(details.get("reasoning_tokens") or
                    usage.get("reasoning_tokens") or 0)
    pdetails = usage.get("prompt_tokens_details") or {}
    cached = int(pdetails.get("cached_tokens") or usage.get("cached_tokens") or 0)
    cost = float(usage.get("cost") or usage.get("total_cost") or 0.0)
    # Reference reports completion net of reasoning (chat_logging.py:262-263).
    completion = max(0, completion - reasoning)
    # SLO outcome block (providers/local.py, ISSUE 7): persisted so the
    # usage ledger can answer "which requests missed their SLO and why"
    # after the /metrics counters have aggregated the detail away.
    slo = usage.get("slo")
    slo_met = slo_phase = None
    if isinstance(slo, dict) and "met" in slo:
        slo_met = 1 if slo.get("met") else 0
        slo_phase = slo.get("phase")
    return {"prompt_tokens": prompt, "completion_tokens": completion,
            "total_tokens": total, "reasoning_tokens": reasoning,
            "cached_tokens": cached, "cost": cost,
            "slo_met": slo_met, "slo_phase": slo_phase}


def write_transcript(logs_dir: Path, limit: int, request_payload: dict[str, Any],
                     response_text: str, meta: dict[str, Any]) -> None:
    """Write one transcript file and prune beyond `limit` (blocking; callers
    offload to a thread)."""
    try:
        logs_dir.mkdir(parents=True, exist_ok=True)
        now = time.time()
        stamp = time.strftime("%Y-%m-%d_%H-%M-%S", time.localtime(now))
        name = f"{stamp}.{int((now % 1) * 1000):03d}.txt"
        lines = ["=== LLM Gateway chat transcript ==="]
        for k, v in meta.items():
            lines.append(f"{k}: {v}")
        lines.append("\n--- request messages ---")
        for msg in request_payload.get("messages", []) or []:
            role = msg.get("role", "?") if isinstance(msg, dict) else "?"
            content = msg.get("content", "") if isinstance(msg, dict) else str(msg)
            lines.append(f"[{role}] {content}")
        lines.append("\n--- assistant response ---")
        lines.append(response_text)
        (logs_dir / name).write_text("\n".join(lines))
        # Prune oldest transcripts beyond the cap (chat_logging.py:59-65).
        transcripts = sorted(p for p in logs_dir.glob("*.txt"))
        for p in transcripts[:-limit] if limit > 0 else []:
            p.unlink(missing_ok=True)
    except OSError:
        logger.exception("transcript write failed (ignored)")


@dataclass
class UsageCollector:
    """One attempt's observer. Only a completed stream records usage."""
    provider: str
    model: str
    usage_db: UsageDB | None = None
    request_payload: dict[str, Any] = field(default_factory=dict)
    logs_dir: Path | None = None
    log_chat_messages: bool = False
    log_file_limit: int = 15
    loop: asyncio.AbstractEventLoop | None = None

    _t_start: float = field(default_factory=time.monotonic)
    _t_first: float | None = None
    _t_end: float | None = None
    _text: list[str] = field(default_factory=list)
    _usage: dict[str, Any] | None = None
    _ended: bool = False

    # -- observer protocol ----------------------------------------------------
    def on_first_token(self) -> None:
        if self._t_first is None:
            self._t_first = time.monotonic()

    def on_content_delta(self, text: str) -> None:
        if text:
            self._text.append(text)

    def on_usage(self, usage: dict[str, Any]) -> None:
        self._usage = usage

    def on_stream_end(self, error: str | None = None) -> None:
        if self._ended:
            return
        self._ended = True
        self._t_end = time.monotonic()
        # SQLite fsync + transcript write/prune are blocking I/O; offload so a
        # stream's finally-block never stalls the event loop.
        try:
            loop = asyncio.get_running_loop()
        except RuntimeError:
            loop = None
        if loop is not None:
            loop.run_in_executor(None, self._record_safe, error)
        else:
            self._record_safe(error)

    # -- recording ------------------------------------------------------------
    def _record_safe(self, error: str | None) -> None:
        try:
            self._record(error)
        except Exception:
            logger.exception("usage record failed (ignored)")

    @property
    def ttft_ms(self) -> float | None:
        if self._t_first is None:
            return None
        return (self._t_first - self._t_start) * 1000.0

    def _record(self, error: str | None) -> None:
        fields = extract_usage_fields(self._usage or {})
        completion_tokens = fields["completion_tokens"] + fields["reasoning_tokens"]
        tps = None
        if self._t_first is not None and self._t_end is not None \
                and completion_tokens > 1 and self._t_end > self._t_first:
            tps = (completion_tokens - 1) / (self._t_end - self._t_first)

        rec = UsageRecord(model=self.model, provider=self.provider,
                          ttft_ms=self.ttft_ms, tokens_per_sec=tps, **fields)
        if self.usage_db is not None and (self._usage or self._text):
            self.usage_db.insert(rec)

        if self.log_chat_messages and self.logs_dir is not None:
            meta = {"provider": self.provider, "model": self.model,
                    "prompt_tokens": fields["prompt_tokens"],
                    "completion_tokens": fields["completion_tokens"],
                    "reasoning_tokens": fields["reasoning_tokens"],
                    "cached_tokens": fields["cached_tokens"],
                    "cost": fields["cost"],
                    "ttft_ms": self.ttft_ms, "tokens_per_sec": tps,
                    "error": error or ""}
            write_transcript(self.logs_dir, self.log_file_limit,
                             self.request_payload, "".join(self._text), meta)
