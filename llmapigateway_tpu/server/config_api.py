"""Config editor API: raw-text GET/POST of the two json5 files + hot reload.

Parity with the reference's rules-editor router (``api/v1/rules_editor.py``):
raw text is served/saved verbatim so comments survive; saves are validated
(json5 parse + pydantic + cross-checks) before the file is written — stricter
than the reference, which writes first and can end with a saved-but-unloaded
file (``rules_editor.py:80-92``). Validation failures return a structured
400 ``{detail, errors}`` the editor UI renders.
"""
from __future__ import annotations

import asyncio
import logging

from aiohttp import web

from ..config.schemas import ConfigError

logger = logging.getLogger(__name__)

# Raw config reads/writes go through asyncio.to_thread: ConfigLoader's
# read_raw/write_raw are synchronous file I/O (+ json5 parse on save) and
# would otherwise stall every in-flight SSE stream — graftlint v2's
# transitive async-blocking pass chases exactly this chain.


async def get_rules_text(request: web.Request) -> web.Response:
    gw = request.app["gateway"]
    try:
        text = await asyncio.to_thread(gw.loader.read_raw, "rules")
        return web.Response(text=text, content_type="text/plain")
    except OSError as e:
        return web.json_response({"detail": str(e)}, status=404)


async def get_providers_text(request: web.Request) -> web.Response:
    gw = request.app["gateway"]
    try:
        text = await asyncio.to_thread(gw.loader.read_raw, "providers")
        return web.Response(text=text, content_type="text/plain")
    except OSError as e:
        return web.json_response({"detail": str(e)}, status=404)


async def _save(request: web.Request, which: str) -> web.Response:
    gw = request.app["gateway"]
    text = await request.text()
    try:
        await asyncio.to_thread(gw.loader.write_raw, which, text)
    except ConfigError as e:
        return web.json_response(
            {"detail": f"validation failed; file not saved", "errors": [str(e)]},
            status=400)
    except ValueError as e:      # json5 syntax error
        return web.json_response(
            {"detail": "invalid json5; file not saved", "errors": [str(e)]},
            status=400)
    except OSError as e:
        return web.json_response({"detail": f"write failed: {e}"}, status=500)
    return web.json_response({"status": "ok", "reloaded": True,
                              "config_version": gw.loader.version})


async def save_rules(request: web.Request) -> web.Response:
    return await _save(request, "rules")


async def save_providers(request: web.Request) -> web.Response:
    return await _save(request, "providers")
