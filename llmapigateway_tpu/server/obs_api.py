"""Unified observability endpoints (ISSUE 4).

* ``GET /metrics`` — the whole gateway in Prometheus text format: HTTP
  middleware, router, providers (incl. breaker state), and engine series
  in one scrape. Unauthenticated (like ``/health``): scrapers cannot
  attach bearer headers, and nothing here carries payload data.
* ``GET /v1/api/trace/{request_id}`` — one request's span tree from the
  tracer's ring buffer (gateway → router attempt N → provider call →
  engine phases). Flatten with ``tools/trace_report.py``.

The engine/breaker bridge lives here too: a scrape-time collector maps
each instantiated local engine's existing ``stats()`` dict (and each
breaker's snapshot) onto gauges — the roofline endpoint, bench, and
health endpoint keep reading the same sources unchanged.
"""
from __future__ import annotations

import logging

from aiohttp import web

from ..obs.metrics import GatewayMetrics

logger = logging.getLogger(__name__)

# stats() key → GatewayMetrics attribute (plus a unit transform).
_ENGINE_GAUGES = (
    # (stats key, metrics attr, scale)
    ("running", "engine_running_requests_total", 1.0),
    ("queued", "engine_queued_requests_total", 1.0),
    ("free_slots", "engine_free_slots_total", 1.0),
    ("shed_total", "engine_sheds_total", 1.0),
    ("burst_busy_clamps", "engine_burst_clamps_total", 1.0),
    ("free_pages", "engine_kv_free_pages_total", 1.0),
    ("prefix_hits_total", "engine_prefix_cache_hit_total", 1.0),
    ("prefix_misses_total", "engine_prefix_cache_miss_total", 1.0),
    ("prefix_cached_tokens_total", "engine_prefix_cached_tokens_total", 1.0),
    ("prefix_resident_pages", "engine_prefix_resident_pages_total", 1.0),
    ("prefix_pinned_refs", "engine_prefix_pinned_refs_total", 1.0),
    ("hbm_bytes_per_step", "engine_step_hbm_bytes", 1.0),
    ("roofline_fraction", "engine_roofline_ratio", 1.0),
    ("queue_wait_ms_ema", "engine_queue_wait_seconds", 1e-3),
    ("decode_ms_per_step", "engine_decode_step_seconds", 1e-3),
    ("achieved_gbps", "engine_hbm_bandwidth_bytes", 1e9),
    # Speculative acceptance telemetry + flight-recorder loss (ISSUE 7).
    ("spec_proposed", "engine_spec_proposed_total", 1.0),
    ("spec_accepted", "engine_spec_accepted_total", 1.0),
    ("spec_suspended_slots", "engine_spec_suspended_slots", 1.0),
    ("flight_evicted_total", "engine_flight_ring_evicted_total", 1.0),
    # HBM memory ledger (ISSUE 8): static accounting, live buffer bytes,
    # and the runtime allocator's view (device_* keys only exist where
    # the backend exposes memory_stats — TPU yes, CPU no).
    ("hbm_weights_bytes", "engine_hbm_weights_bytes", 1.0),
    ("hbm_kv_pool_bytes", "engine_hbm_kv_pool_bytes", 1.0),
    ("hbm_aux_bytes", "engine_hbm_aux_bytes", 1.0),
    ("hbm_spec_bytes", "engine_hbm_spec_bytes", 1.0),
    ("hbm_ledger_bytes", "engine_hbm_ledger_bytes", 1.0),
    ("hbm_tracked_bytes", "engine_hbm_tracked_bytes", 1.0),
    ("hbm_prefix_resident_bytes", "engine_hbm_prefix_resident_bytes", 1.0),
    ("hbm_device_in_use_bytes", "engine_hbm_device_in_use_bytes", 1.0),
    ("hbm_device_peak_bytes", "engine_hbm_device_peak_bytes", 1.0),
    ("hbm_device_limit_bytes", "engine_hbm_device_limit_bytes", 1.0),
    ("hbm_headroom_ratio", "engine_hbm_headroom_ratio", 1.0),
    ("watermark_sheds", "engine_watermark_sheds_total", 1.0),
    # Disaggregated serving (ISSUE 13): engine-level handoff/clamp
    # counters; the per-pool block fans out via _POOL_GAUGES below.
    ("disagg_handoffs", "engine_disagg_handoffs_total", 1.0),
    ("disagg_handoff_pages", "engine_disagg_handoff_pages_total", 1.0),
    ("disagg_clamps", "engine_disagg_clamps_total", 1.0),
    # Engine supervision (ISSUE 14): lifecycle state + restart budget.
    ("supervisor_state_code", "engine_supervisor_state_ratio", 1.0),
    ("supervisor_restarts_total", "engine_supervisor_restarts_total", 1.0),
    ("supervisor_heartbeat_age_seconds",
     "engine_supervisor_heartbeat_age_seconds", 1.0),
    ("supervisor_backoff_seconds", "engine_supervisor_backoff_seconds", 1.0),
)

# stats()["pools"][pool] key → GatewayMetrics attribute (plus scale),
# one series per (engine, pool) label pair.
_POOL_GAUGES = (
    ("slots", "engine_pool_slots_total", 1.0),
    ("free_slots", "engine_pool_free_slots_total", 1.0),
    ("running", "engine_pool_running_total", 1.0),
    ("admits", "engine_pool_admits_total", 1.0),
    ("sheds", "engine_pool_sheds_total", 1.0),
    ("predicted_ttft_ms", "engine_pool_predicted_ttft_seconds", 1e-3),
    ("predicted_tpot_ms", "engine_pool_predicted_tpot_seconds", 1e-3),
    ("occupancy_ratio", "engine_pool_occupancy_ratio", 1.0),
)


def make_stats_collector(gw) -> "callable":
    """The scrape-time bridge from pull-model telemetry (engine ``stats()``
    dicts, breaker snapshots) into the metrics plane. Registered by
    GatewayApp; unregistered on close so test apps don't stack up."""
    metrics: GatewayMetrics = gw.metrics

    def collect() -> None:
        for name, prov in gw.registry.instantiated():
            engine = getattr(prov, "engine", None)
            if engine is None:
                continue
            try:
                stats = engine.stats()
            except Exception:
                logger.debug("engine stats() failed for %s", name,
                             exc_info=True)
                continue
            for key, attr, scale in _ENGINE_GAUGES:
                val = stats.get(key)
                if isinstance(val, (int, float)):
                    getattr(metrics, attr).labels(engine=name).set(
                        val * scale)
            pools = stats.get("pools")
            if isinstance(pools, dict):
                for pool_name, pstats in pools.items():
                    if not isinstance(pstats, dict):
                        continue
                    for key, attr, scale in _POOL_GAUGES:
                        val = pstats.get(key)
                        if isinstance(val, (int, float)):
                            getattr(metrics, attr).labels(
                                engine=name, pool=pool_name).set(
                                    val * scale)
            total = stats.get("total_pages")
            free = stats.get("free_pages")
            if isinstance(total, (int, float)) and total > 0 \
                    and isinstance(free, (int, float)):
                metrics.engine_kv_occupancy_ratio.labels(engine=name).set(
                    max(0.0, 1.0 - free / total))
            proposed = stats.get("spec_proposed")
            accepted = stats.get("spec_accepted")
            if isinstance(proposed, (int, float)) and proposed > 0 \
                    and isinstance(accepted, (int, float)):
                metrics.engine_spec_acceptance_ratio.labels(
                    engine=name).set(accepted / proposed)
            # Per-slot adaptive drafting: each measured slot's live EMA
            # ratio (the floor's comparand), keyed by slot label.
            ratios = stats.get("spec_slot_acceptance")
            if isinstance(ratios, dict):
                for slot, ratio in ratios.items():
                    if isinstance(ratio, (int, float)):
                        metrics.engine_spec_slot_acceptance_ratio.labels(
                            engine=name, slot=str(slot)).set(ratio)
        # SLO goodput (ISSUE 7): met / (met + violated) per engine,
        # derived at scrape time from the counters the local provider
        # increments at stream end — the violated side sums across its
        # attribution phases.
        met_by_engine = {key[0]: child.value
                         for key, child in metrics.slo_met_total.children()}
        violated_by_engine: dict[str, float] = {}
        for key, child in metrics.slo_violated_total.children():
            violated_by_engine[key[0]] = (
                violated_by_engine.get(key[0], 0.0) + child.value)
        for eng in set(met_by_engine) | set(violated_by_engine):
            met = met_by_engine.get(eng, 0.0)
            tot = met + violated_by_engine.get(eng, 0.0)
            if tot > 0:
                metrics.slo_goodput_ratio.labels(engine=eng).set(met / tot)
        # Per-pool goodput (ISSUE 13): same derivation keyed by the pool
        # that served the request's decode — the pooled-vs-unified
        # scoreboard the disagg A/B reads.
        pool_met = {key: child.value
                    for key, child in metrics.slo_pool_met_total.children()}
        pool_violated = {
            key: child.value
            for key, child in metrics.slo_pool_violated_total.children()}
        for key in set(pool_met) | set(pool_violated):
            met = pool_met.get(key, 0.0)
            tot = met + pool_violated.get(key, 0.0)
            if tot > 0:
                metrics.slo_pool_goodput_ratio.labels(
                    engine=key[0], pool=key[1]).set(met / tot)
        metrics.trace_ring_evicted_total.set(gw.tracer.evicted_total)
        # XLA compile telemetry (ISSUE 8): process-wide monitor, one
        # series per triggering phase — a non-startup phase here is a
        # recompile some live request paid for.
        try:
            from ..obs.device import compile_monitor
            cm = compile_monitor().stats()
            for ph, slot in cm.get("xla_compile_by_phase", {}).items():
                metrics.engine_xla_compile_total.labels(phase=ph).set(
                    slot["count"])
                metrics.engine_xla_compile_seconds.labels(phase=ph).set(
                    slot["seconds"])
        except Exception:
            logger.debug("xla compile bridge failed", exc_info=True)
        if gw.breakers is not None:
            for name, snap in gw.breakers.snapshot().items():
                metrics.provider_breaker_open_ratio.labels(
                    provider=name).set(snap.get("state_code", 0.0))
                metrics.provider_breaker_opens_total.labels(
                    provider=name).set(snap.get("opens", 0))
        # Write-behind usage recorder (ISSUE 14): queue depth + drop
        # counter — a nonzero drop rate means the ledger is lossy under
        # the current incident load.
        recorder = getattr(gw, "usage_recorder", None)
        if recorder is not None:
            rstats = recorder.stats()
            metrics.usage_recorder_queued.set(
                rstats["usage_recorder_queued"])
            metrics.usage_recorder_flushed_total.set(
                rstats["usage_recorder_flushed_total"])
            metrics.usage_recorder_dropped_total.set(
                rstats["usage_recorder_dropped_total"])

    return collect


async def get_metrics_text(request: web.Request) -> web.Response:
    gw = request.app["gateway"]
    text = gw.metrics.render()
    return web.Response(
        text=text,
        headers={"Content-Type":
                 "text/plain; version=0.0.4; charset=utf-8"})


async def get_flight(request: web.Request) -> web.Response:
    """``GET /v1/api/flight?since=<seq>`` — the scheduler flight
    recorder's resident records, per local engine (ISSUE 7). ``since``
    returns only records newer than that sequence number, so a poller
    tails the ring without re-reading it; each engine block carries its
    ring counters (seq / capacity / evicted) so loss is visible. Convert
    to a Perfetto-loadable Chrome trace with ``tools/flight_report.py``."""
    gw = request.app["gateway"]
    try:
        since = int(request.query.get("since", -1))
    except ValueError:
        return web.json_response(
            {"detail": "since must be an integer sequence number"},
            status=400)
    engines = {}
    for name, prov in gw.registry.instantiated():
        engine = getattr(prov, "engine", None)
        recorder = getattr(engine, "flight", None)
        if recorder is None:
            continue
        engines[name] = {"records": recorder.snapshot(since),
                         **recorder.stats()}
    if not engines:
        return web.json_response(
            {"detail": "no local engine with an active flight recorder "
                       "(flight_ring_size 0, or no local provider "
                       "instantiated yet)"},
            status=404)
    return web.json_response({"engines": engines})


async def get_trace(request: web.Request) -> web.Response:
    gw = request.app["gateway"]
    request_id = request.match_info["request_id"]
    doc = gw.tracer.get(request_id)
    if doc is None:
        return web.json_response(
            {"detail": f"no trace for request id {request_id!r} (ring "
                       f"buffer holds the most recent "
                       f"{gw.tracer.capacity} requests)"},
            status=404)
    return web.json_response(doc)
