"""Int8/int4 weight quantization for the serving engine (W8A8 / W4A8).

No reference counterpart — the reference proxies HTTP and never touches
weights (SURVEY.md §2: no model execution anywhere). This is a TPU-native
performance feature: steady-state decode is HBM-bandwidth-bound (every
weight byte is read once per token), so storing matmul weights as int8
halves the traffic that sets the decode roofline, and the int8×int8
``dot_general`` runs on the MXU's native int8 path (v5e: 394 int8 TOPS vs
197 bf16 TFLOPS).

Scheme (standard dynamic W8A8, no calibration data needed):

* **Weights**: symmetric per-output-channel int8. For a projection
  ``w [D, F]`` (contract over D) the scale is ``s [F] = max|w[:, f]|/127``
  stored fp32; a quantized weight is the sub-dict ``{"q": int8, "s": fp32}``
  in the params tree (a plain pytree — ``lax.scan`` over stacked layers,
  GSPMD sharding, and multihost broadcast all see ordinary leaves).
* **Activations**: symmetric per-row dynamic int8, computed inside the
  compiled step (``max|x|`` over the contraction dim — XLA fuses this with
  the surrounding elementwise work). Row scales commute with the matmul, so
  the result is exact int32 arithmetic rescaled once:
  ``y = (xq @ wq) * xs * s``. Under tensor parallelism the int32 partial
  sums are summed exactly (integer psum) before the fp32 rescale.
* RMSNorm, rotary, embedding gather, KV cache, and logits stay in their
  usual dtypes — only the seven big matmuls per layer (wq/wk/wv/wo and
  wg/wu/wd) and the lm_head are quantized; those carry ~99% of the weight
  bytes of a llama-family model.

``mm``/``head_matmul`` are the single dispatch points: they accept either a
plain array or a quantized dict, so model code (models/llama.py) is layout-
agnostic and a checkpoint loaded with ``quant: "int8"`` streams through the
same forward as a bf16 one.

``quant: "int4"`` (W4A8) stores the layer matmuls as **int4** (levels
±7, same per-channel scheme) while the lm_head stays int8. The dots run
as mixed s8×s4 ``dot_general`` — XLA contracts the int4 operand
directly, and on TPU the packed-int4 HBM layout is what matters: decode
is weight-bandwidth-bound, so int4 MLP/attention weights cut the
per-step stream ~45% past int8 at a quality cost users opt into
per-provider.
"""
from __future__ import annotations

from typing import Any

import jax
import jax.numpy as jnp
import numpy as np

from .config import ModelConfig

# Layer-stacked weights that quantize (contract dim 1 of [L, D_in, D_out]).
QUANT_LAYER_KEYS = frozenset({"wq", "wk", "wv", "wo", "wg", "wu", "wd"})
# Top-level weights that quantize ([V, D], contract over D → scale per V).
QUANT_TOP_KEYS = frozenset({"lm_head"})

QUANT_MODES = ("", "int8", "int4")


def weight_bits(mode: str, path: str) -> int:
    """Bit width for a quantizable path under a quant mode. ``int4``
    applies to the stacked layer matmuls (wq/wk/wv/wo/wg/wu/wd — they
    carry ~90% of a llama-family model's weight bytes and tolerate 4-bit
    per-channel rounding); the lm_head stays int8 in int4 mode — the
    logits matmul decides every sampled token and is the one projection
    where 4-bit rounding moves argmax measurably, for ~6% of the bytes."""
    if mode == "int4" and path not in QUANT_TOP_KEYS:
        return 4
    return 8


def is_quantized(w: Any) -> bool:
    return isinstance(w, dict) and "q" in w and "s" in w


def _np_quantize(arr: np.ndarray, contract_axis: int,
                 bits: int = 8) -> dict[str, np.ndarray]:
    """Host-side symmetric per-channel quantization (checkpoint load path —
    the int8/int4 copy, not the bf16 original, is what crosses PCIe/DCN)."""
    from ml_dtypes import int4
    levels = (1 << (bits - 1)) - 1          # 127 (int8) / 7 (int4)
    f = np.asarray(arr, np.float32)
    amax = np.max(np.abs(f), axis=contract_axis, keepdims=True)
    scale = np.maximum(amax, 1e-30) / levels
    q = np.clip(np.rint(f / scale), -levels, levels) \
        .astype(np.int8 if bits == 8 else int4)
    return {"q": q, "s": np.squeeze(scale, axis=contract_axis)}


def quantize_array(w: jax.Array, contract_axis: int,
                   bits: int = 8) -> dict[str, jax.Array]:
    """Device-side twin of :func:`_np_quantize` (random-init path)."""
    levels = (1 << (bits - 1)) - 1
    f = w.astype(jnp.float32)
    amax = jnp.max(jnp.abs(f), axis=contract_axis, keepdims=True)
    scale = jnp.maximum(amax, 1e-30) / levels
    q = jnp.clip(jnp.round(f / scale), -levels, levels) \
        .astype(jnp.int8 if bits == 8 else jnp.int4)
    return {"q": q, "s": jnp.squeeze(scale, axis=contract_axis)}


def quantizes(path: str) -> bool:
    """Whether a param path participates in int8 quantization: the
    llama-family stacked layer matmuls, MoE expert matmuls, and lm_head
    (norms, biases, router, and the embed table stay full precision)."""
    if path in QUANT_TOP_KEYS:
        return True
    return (path.startswith("layers.")
            and path.split(".", 1)[1] in QUANT_LAYER_KEYS)


def contract_axis_for(path: str, ndim: int) -> int | None:
    """Which axis a quantized *stacked* weight contracts over, or None if
    the param doesn't quantize. Paths follow parallel/sharding.py's dot-key
    scheme."""
    if not quantizes(path):
        return None
    if ndim == 4:   # MoE expert [L, E, D_in, D_out] → per-(e, out) scale
        return 2
    return 1        # lm_head [V, D] → per-V; layers [L, D_in, D_out] → dim 1


def quantize_tree(params: dict, config: ModelConfig,
                  mode: str = "int8") -> dict:
    """Replace every quantizable leaf of a params tree with its
    ``{"q", "s"}`` dict (random-init path; checkpoint load quantizes
    per-parameter on the host instead — engine/checkpoint.py put hook).

    Tied-embedding models (qwen2/gemma families) have no ``lm_head`` leaf;
    the embed table stays full precision (the gather path reads only B
    rows/step), but the HEAD read — the full ``[V, D]`` matrix every step,
    ~25% of gemma-2b's weight bytes — gets its own int8 copy under
    ``lm_head_q8``. +0.5× embed bytes of storage buys a 2× smaller
    per-step head read, which is the bandwidth that matters at decode."""
    out: dict = {}
    for key, val in params.items():
        if key == "layers":
            out[key] = {
                k: (quantize_array(v, contract_axis_for(f"layers.{k}", v.ndim),
                                   bits=weight_bits(mode, f"layers.{k}"))
                    if contract_axis_for(f"layers.{k}", v.ndim) is not None
                    else v)
                for k, v in val.items()
            }
        elif contract_axis_for(key, getattr(val, "ndim", 0)) is not None:
            out[key] = quantize_array(val, contract_axis_for(key, val.ndim),
                                      bits=weight_bits(mode, key))
        else:
            out[key] = val
    if config.tie_embeddings and "lm_head" not in params:
        out["lm_head_q8"] = quantize_array(params["embed"], 1)
    return out


def _dynamic_int8(x: jax.Array) -> tuple[jax.Array, jax.Array]:
    """Per-row symmetric int8 quantization of activations over the last dim.
    Returns (xq int8, xs fp32 with a keepdims-1 trailing axis)."""
    xf = x.astype(jnp.float32)
    amax = jnp.max(jnp.abs(xf), axis=-1, keepdims=True)
    xs = jnp.maximum(amax, 1e-30) / 127.0
    xq = jnp.clip(jnp.round(xf / xs), -127, 127).astype(jnp.int8)
    return xq, xs


def mm(x: jax.Array, w: Any) -> jax.Array:
    """``x [..., D] @ w [D, F]`` where ``w`` is a plain array or a quantized
    ``{"q", "s"}`` dict. Result in ``x.dtype`` either way."""
    if not is_quantized(w):
        return x @ w
    xq, xs = _dynamic_int8(x)
    acc = jax.lax.dot_general(
        xq, w["q"], (((x.ndim - 1,), (0,)), ((), ())),
        preferred_element_type=jnp.int32)
    y = acc.astype(jnp.float32) * xs * w["s"]
    return y.astype(x.dtype)


def moe_mm_dense(x: jax.Array, w: Any) -> jax.Array:
    """All-experts projection: ``x [N, D] × w [E, D, F] → [E, N, F]``
    (mixtral's dense-routing form), plain or int8 ``{"q","s"}`` (scale
    ``s [E, F]``). Activations quantize once per row, shared by all E."""
    if not is_quantized(w):
        return jnp.einsum("nd,edf->enf", x, w)
    xq, xs = _dynamic_int8(x)                       # [N, D], [N, 1]
    acc = jax.lax.dot_general(
        xq, w["q"], (((1,), (1,)), ((), ())),
        preferred_element_type=jnp.int32)           # [N, E, F]
    y = acc.astype(jnp.float32) * xs[:, :, None] * w["s"][None]
    return y.transpose(1, 0, 2).astype(x.dtype)


def moe_mm_batched(x: jax.Array, w: Any) -> jax.Array:
    """Expert-batched projection: ``x [E, C, Din] × w [E, Din, Dout] →
    [E, C, Dout]`` (mixtral's capacity-dispatch form and both down
    projections), plain or int8 (scale ``s [E, Dout]``)."""
    if not is_quantized(w):
        return jnp.einsum("ecd,edf->ecf", x, w)
    xq, xs = _dynamic_int8(x)                       # [E, C, Din], [E, C, 1]
    acc = jax.lax.dot_general(
        xq, w["q"], (((2,), (1,)), ((0,), (0,))),
        preferred_element_type=jnp.int32)           # [E, C, Dout]
    y = acc.astype(jnp.float32) * xs * w["s"][:, None, :]
    return y.astype(x.dtype)


def head_matmul(x: jax.Array, head: Any) -> jax.Array:
    """Logits: ``x [B, T, D] · head [V, D] → [B, T, V]`` fp32. Plain head
    keeps the bf16-read / fp32-accumulate einsum; a quantized head contracts
    int8 against dim 1 directly (no transposed copy materializes)."""
    if not is_quantized(head):
        return jnp.einsum("btd,vd->btv", x, head,
                          preferred_element_type=jnp.float32)
    xq, xs = _dynamic_int8(x)
    acc = jax.lax.dot_general(
        xq, head["q"], (((x.ndim - 1,), (1,)), ((), ())),
        preferred_element_type=jnp.int32)
    return acc.astype(jnp.float32) * xs * head["s"]
