"""Model architecture configs and named presets.

Presets cover the BASELINE.md ladder: tiny-test (CI), TinyLlama-1.1B
(config 1), Llama-3-8B (configs 2-3), Mixtral-8x7B (config 4, MoE),
Llama-3-70B (config 5, multi-host).
"""
from __future__ import annotations

from dataclasses import dataclass, field


@dataclass(frozen=True)
class RopeScaling:
    """RoPE frequency scaling (HF ``rope_scaling`` block).

    ``llama3`` — Llama-3.1-style per-frequency-band scaling (long
    wavelengths divided by ``factor``, short ones untouched, smooth
    interpolation between ``low_freq_factor``/``high_freq_factor`` bands of
    the ``original_max_seq`` context). ``linear`` — uniform position
    interpolation (every frequency divided by ``factor``).
    """
    rope_type: str = "llama3"      # "llama3" | "linear"
    factor: float = 8.0
    low_freq_factor: float = 1.0
    high_freq_factor: float = 4.0
    original_max_seq: int = 8192

    def __post_init__(self):
        if self.rope_type not in ("llama3", "linear"):
            raise ValueError(
                f"unsupported rope_scaling type {self.rope_type!r}; "
                f"supported: llama3, linear")


@dataclass(frozen=True)
class ModelConfig:
    family: str = "llama"          # "llama" | "qwen2" | "gemma" | "mixtral"
    vocab_size: int = 32000
    d_model: int = 2048
    n_layers: int = 22
    n_heads: int = 32
    n_kv_heads: int = 4
    d_ff: int = 5632
    rope_theta: float = 10000.0
    rope_scaling: RopeScaling | None = None
    rms_eps: float = 1e-5
    max_seq_len: int = 4096
    tie_embeddings: bool = False
    # QKV projection bias (Qwen2-family); the rest of the block is llama.
    attn_bias: bool = False
    # Gemma-family block variations (all config-driven — the llama forward
    # is the single implementation):
    act: str = "silu"              # MLP gate activation: "silu" | "gelu_tanh"
    rms_offset: float = 0.0        # RMSNorm weight offset: x * (offset + w)
    scale_embed: bool = False      # multiply embeddings by sqrt(d_model)
    # Explicit head dim for families where H * Dh != d_model (Gemma-7B:
    # 16 heads x 256 vs d_model 3072). 0 = derive d_model // n_heads.
    head_dim_override: int = 0
    # Sliding-window attention (mistral-family): position i attends keys
    # j with i - j < window (self included) — HF Mistral semantics. 0 =
    # full causal attention. v1 masks only (the linear cache keeps every
    # token; windowed KV eviction is a capacity optimization, not a
    # correctness requirement).
    sliding_window: int = 0
    # MoE (mixtral) fields
    n_experts: int = 0             # 0 → dense
    experts_per_token: int = 2

    @property
    def head_dim(self) -> int:
        return self.head_dim_override or self.d_model // self.n_heads

    @property
    def is_moe(self) -> bool:
        return self.n_experts > 0


PRESETS: dict[str, ModelConfig] = {
    # Tiny model for tests: fast to init/compile on CPU devices.
    "tiny-test": ModelConfig(
        vocab_size=512, d_model=64, n_layers=2, n_heads=4, n_kv_heads=2,
        d_ff=128, max_seq_len=256),
    # Same CI-scale geometry with a 1k context: the shared-prefix bench
    # rung needs room for a >=512-token common prefix plus tails on CPU.
    "tiny-test-1k": ModelConfig(
        vocab_size=512, d_model=64, n_layers=2, n_heads=4, n_kv_heads=2,
        d_ff=128, max_seq_len=1024),
    "tiny-qwen-test": ModelConfig(
        family="qwen2", vocab_size=512, d_model=64, n_layers=2, n_heads=4,
        n_kv_heads=2, d_ff=128, max_seq_len=256, tie_embeddings=True,
        attn_bias=True),
    "tiny-gemma-test": ModelConfig(
        family="gemma", vocab_size=512, d_model=64, n_layers=2, n_heads=4,
        n_kv_heads=1, d_ff=128, max_seq_len=256, tie_embeddings=True,
        act="gelu_tanh", rms_offset=1.0, scale_embed=True,
        head_dim_override=16, rms_eps=1e-6),
    "tiny-moe-test": ModelConfig(
        family="mixtral", vocab_size=512, d_model=64, n_layers=2, n_heads=4,
        n_kv_heads=2, d_ff=128, max_seq_len=256, n_experts=4,
        experts_per_token=2),
    # TinyLlama-1.1B (HF: TinyLlama/TinyLlama-1.1B-Chat-v1.0).
    "tinyllama-1.1b": ModelConfig(
        vocab_size=32000, d_model=2048, n_layers=22, n_heads=32, n_kv_heads=4,
        d_ff=5632, rope_theta=10000.0, max_seq_len=2048),
    # Qwen2-0.5B (HF: Qwen/Qwen2-0.5B-Instruct) — llama block + QKV bias,
    # tied embeddings.
    "qwen2-0.5b": ModelConfig(
        family="qwen2", vocab_size=151936, d_model=896, n_layers=24,
        n_heads=14, n_kv_heads=2, d_ff=4864, rope_theta=1000000.0,
        rms_eps=1e-6, max_seq_len=32768, tie_embeddings=True,
        attn_bias=True),
    # ~3B-class llama geometry (TPU-friendly head_dim=128, GQA 24/8):
    # ~3.2B params ≈ 6.4 GB bf16 — the largest preset that comfortably
    # fits one 16 GB v5e chip with a bs=8 KV cache. The bench ladder's mid
    # rung between TinyLlama and 8B (higher arithmetic intensity; shows
    # whether MFU scales with model width).
    "llama-3b-class": ModelConfig(
        vocab_size=32000, d_model=3072, n_layers=28, n_heads=24,
        n_kv_heads=8, d_ff=8192, rope_theta=10000.0, max_seq_len=2048),
    # Mistral-7B-v0.1 (HF: mistralai/Mistral-7B-Instruct-v0.1): llama
    # block + 4096-token sliding-window attention over a 32k context.
    "mistral-7b": ModelConfig(
        vocab_size=32000, d_model=4096, n_layers=32, n_heads=32,
        n_kv_heads=8, d_ff=14336, rope_theta=10000.0, max_seq_len=32768,
        sliding_window=4096),
    # Phi-3-mini-4k (HF: microsoft/Phi-3-mini-4k-instruct): llama block,
    # MHA, sliding window 2047; the HF checkpoint ships qkv/gate_up
    # FUSED (engine/checkpoint.py splits them at load).
    "phi-3-mini": ModelConfig(
        vocab_size=32064, d_model=3072, n_layers=32, n_heads=32,
        n_kv_heads=32, d_ff=8192, rope_theta=10000.0, max_seq_len=4096,
        sliding_window=2047),
    # Tiny sliding-window model for tests (window << max_seq).
    "tiny-mistral-test": ModelConfig(
        vocab_size=512, d_model=64, n_layers=2, n_heads=4, n_kv_heads=2,
        d_ff=128, max_seq_len=256, sliding_window=16),
    # Llama-3-8B (HF: meta-llama/Meta-Llama-3-8B-Instruct).
    "llama-3-8b": ModelConfig(
        vocab_size=128256, d_model=4096, n_layers=32, n_heads=32,
        n_kv_heads=8, d_ff=14336, rope_theta=500000.0, max_seq_len=8192),
    # Llama-3-70B.
    "llama-3-70b": ModelConfig(
        vocab_size=128256, d_model=8192, n_layers=80, n_heads=64,
        n_kv_heads=8, d_ff=28672, rope_theta=500000.0, max_seq_len=8192),
    # Gemma-2B (HF: google/gemma-2b): MQA (1 KV head), head_dim 256,
    # GeGLU MLP, (1+w) RMSNorm, sqrt(D)-scaled tied embeddings.
    "gemma-2b": ModelConfig(
        family="gemma", vocab_size=256000, d_model=2048, n_layers=18,
        n_heads=8, n_kv_heads=1, d_ff=16384, rope_theta=10000.0,
        rms_eps=1e-6, max_seq_len=8192, tie_embeddings=True,
        act="gelu_tanh", rms_offset=1.0, scale_embed=True,
        head_dim_override=256),
    # Gemma-7B (HF: google/gemma-7b): 16 heads x 256 > d_model 3072.
    "gemma-7b": ModelConfig(
        family="gemma", vocab_size=256000, d_model=3072, n_layers=28,
        n_heads=16, n_kv_heads=16, d_ff=24576, rope_theta=10000.0,
        rms_eps=1e-6, max_seq_len=8192, tie_embeddings=True,
        act="gelu_tanh", rms_offset=1.0, scale_embed=True,
        head_dim_override=256),
    # Mixtral-8x7B (HF: mistralai/Mixtral-8x7B-Instruct-v0.1).
    "mixtral-8x7b": ModelConfig(
        family="mixtral", vocab_size=32000, d_model=4096, n_layers=32,
        n_heads=32, n_kv_heads=8, d_ff=14336, rope_theta=1000000.0,
        max_seq_len=32768, n_experts=8, experts_per_token=2),
}


def get_preset(name: str) -> ModelConfig:
    if name not in PRESETS:
        raise KeyError(f"unknown model preset {name!r}; known: {sorted(PRESETS)}")
    return PRESETS[name]
