"""Llama-family decoder as pure JAX functions.

TPU-first design decisions (not a port of any torch module structure):

* **Stacked layer parameters + ``lax.scan`` over layers** — one compiled
  layer body regardless of depth, keeping compile time flat for 80-layer
  models and letting GSPMD treat every layer identically.
* **One forward for prefill and decode** — tokens ``[B, T]`` with ``T`` the
  prefill chunk (or 1 for decode) against a fixed-shape KV cache, so XLA
  compiles exactly two programs (per bucket) and shapes never depend on data.
* **Pluggable attention** — the cache-attention inner op is an argument, so
  the reference jnp implementation and the Pallas paged kernel interchange
  without touching model code.
* bfloat16 params/activations by default (MXU-native), fp32 for RMSNorm
  accumulation, rotary tables, and logits.

Covers Llama 1/2/3 and TinyLlama (GQA via ``n_kv_heads``), and provides the
attention/norm blocks Mixtral reuses (models/mixtral.py).
"""
from __future__ import annotations

from functools import lru_cache, partial
from typing import Any, Callable, NamedTuple

import jax
import jax.numpy as jnp

from .config import ModelConfig
from .quant import head_matmul, mm

Params = dict[str, Any]


# ---------------------------------------------------------------------------
# Initialization
# ---------------------------------------------------------------------------

def init_params(config: ModelConfig, key: jax.Array,
                dtype: jnp.dtype = jnp.bfloat16) -> Params:
    """Random-init params in the stacked-layer layout.

    Layout (leaf shapes; L = n_layers, D = d_model, H/KV = heads, Dh = head
    dim, F = d_ff, V = vocab):
      embed [V, D]; final_norm [D]; lm_head [V, D] (absent if tied)
      layers/{attn_norm [L,D], wq [L,D,H*Dh], wk [L,D,KV*Dh], wv [L,D,KV*Dh],
              wo [L,H*Dh,D], mlp_norm [L,D], wg [L,D,F], wu [L,D,F], wd [L,F,D]}
    """
    c = config
    keys = jax.random.split(key, 10)
    dh = c.head_dim

    def norm_init(*shape):
        return jnp.ones(shape, dtype=dtype)

    def dense_init(k, *shape):
        fan_in = shape[-2] if len(shape) >= 2 else shape[-1]
        scale = 1.0 / jnp.sqrt(fan_in)
        return (jax.random.normal(k, shape, dtype=jnp.float32) * scale).astype(dtype)

    params: Params = {
        "embed": dense_init(keys[0], c.vocab_size, c.d_model),
        "final_norm": norm_init(c.d_model),
        "layers": {
            "attn_norm": norm_init(c.n_layers, c.d_model),
            "wq": dense_init(keys[1], c.n_layers, c.d_model, c.n_heads * dh),
            "wk": dense_init(keys[2], c.n_layers, c.d_model, c.n_kv_heads * dh),
            "wv": dense_init(keys[3], c.n_layers, c.d_model, c.n_kv_heads * dh),
            "wo": dense_init(keys[4], c.n_layers, c.n_heads * dh, c.d_model),
            "mlp_norm": norm_init(c.n_layers, c.d_model),
            "wg": dense_init(keys[5], c.n_layers, c.d_model, c.d_ff),
            "wu": dense_init(keys[6], c.n_layers, c.d_model, c.d_ff),
            "wd": dense_init(keys[7], c.n_layers, c.d_ff, c.d_model),
        },
    }
    if c.attn_bias:
        # Qwen2-family QKV bias. Random (not zero) init so random-weight
        # tests exercise the bias path end to end.
        bkeys = jax.random.split(keys[9], 3)
        params["layers"]["bq"] = dense_init(
            bkeys[0], c.n_layers, c.n_heads * dh)
        params["layers"]["bk"] = dense_init(
            bkeys[1], c.n_layers, c.n_kv_heads * dh)
        params["layers"]["bv"] = dense_init(
            bkeys[2], c.n_layers, c.n_kv_heads * dh)
    if not c.tie_embeddings:
        params["lm_head"] = dense_init(keys[8], c.vocab_size, c.d_model)
    return params


# ---------------------------------------------------------------------------
# Building blocks
# ---------------------------------------------------------------------------

def rms_norm(x: jax.Array, weight: jax.Array, eps: float,
             offset: float = 0.0) -> jax.Array:
    """RMSNorm with fp32 accumulation (bf16 variance underflows).
    ``offset``: Gemma parameterizes the scale as ``(1 + w)`` (HF
    GemmaRMSNorm); llama/qwen2 use plain ``w`` (offset 0)."""
    xf = x.astype(jnp.float32)
    var = jnp.mean(xf * xf, axis=-1, keepdims=True)
    normed = xf * jax.lax.rsqrt(var + eps)
    return (normed * (offset + weight.astype(jnp.float32))).astype(x.dtype)


def rope_tables(positions: jax.Array, head_dim: int, theta: float,
                scaling=None) -> tuple[jax.Array, jax.Array]:
    """cos/sin tables [..., head_dim/2] (fp32) for given absolute positions.
    ``scaling`` is an optional ``config.RopeScaling`` — without it a modern
    Llama-3.1-style checkpoint would silently load with wrong RoPE."""
    half = head_dim // 2
    freqs = 1.0 / (theta ** (jnp.arange(half, dtype=jnp.float32) / half))
    if scaling is not None:
        freqs = _scale_rope_freqs(freqs, scaling)
    angles = positions.astype(jnp.float32)[..., None] * freqs   # [..., half]
    return jnp.cos(angles), jnp.sin(angles)


def _scale_rope_freqs(freqs: jax.Array, scaling) -> jax.Array:
    """Apply HF-convention rope_scaling to the inverse-frequency vector
    (matches transformers' _compute_llama3_parameters numerics)."""
    if scaling.rope_type == "linear":
        return freqs / scaling.factor
    # llama3: long wavelengths (beyond the original context's low-freq band)
    # are slowed by `factor`; short ones kept; the middle band interpolates.
    old_ctx = float(scaling.original_max_seq)
    low_wavelen = old_ctx / scaling.low_freq_factor
    high_wavelen = old_ctx / scaling.high_freq_factor
    wavelen = 2.0 * jnp.pi / freqs
    scaled = jnp.where(wavelen > low_wavelen, freqs / scaling.factor, freqs)
    smooth = (old_ctx / wavelen - scaling.low_freq_factor) / (
        scaling.high_freq_factor - scaling.low_freq_factor)
    smoothed = (1.0 - smooth) * freqs / scaling.factor + smooth * freqs
    is_medium = (wavelen <= low_wavelen) & (wavelen >= high_wavelen)
    return jnp.where(is_medium, smoothed, scaled)


def apply_rope(x: jax.Array, cos: jax.Array, sin: jax.Array) -> jax.Array:
    """Rotate pairs (x[..., :half], x[..., half:]) — HF llama convention.
    x: [B, T, N, Dh]; cos/sin: [B, T, half]."""
    half = x.shape[-1] // 2
    x1, x2 = x[..., :half], x[..., half:]
    cos = cos[:, :, None, :]
    sin = sin[:, :, None, :]
    xf1, xf2 = x1.astype(jnp.float32), x2.astype(jnp.float32)
    out1 = xf1 * cos - xf2 * sin
    out2 = xf2 * cos + xf1 * sin
    return jnp.concatenate([out1, out2], axis=-1).astype(x.dtype)


class KVCache(NamedTuple):
    """Dense per-slot KV cache, stacked over layers, **head-major**.

    k, v: [L, B, KV, S_max, Dh] — per-head sequence contiguous, which is
    the layout the Pallas kernels want (Mosaic blocks tile the last two
    dims: (seq_block, head_dim) = (8k, 128)-aligned) and gives the jnp
    path unit-stride reads per head too. ``lengths`` ([B], int32) — tokens
    already cached per slot — lives in the engine's batch state, not here,
    so the cache stays a plain pytree of arrays.

    With KV quantization (``kv_quant: "int8"``) each of k/v is instead the
    sub-dict ``{"q": int8 [L,B,KV,S,Dh], "s": f32 [L,B,KV,1,S]}`` —
    symmetric per-token-per-head scales, the same plain-or-quantized dict
    convention as weight quant (models/quant.py). Ordinary pytree leaves:
    the layer scan, GSPMD shardings, and row slicing all treat them
    uniformly. The scales carry a unit dim before the token axis: that is
    the rank the Pallas kernels' BlockSpecs need (trailing block dims
    ``(1, block)`` are legal under Mosaic's (8, 128) tiling rule for any
    KV — a ``[.., KV, S]`` layout would need an illegal KV-dim block of
    1), and storing it natively means NO per-call relayout of the scale
    tensors (which scales with CACHE CAPACITY, not live context — on a
    large paged pool the reshape alternative costs whole milliseconds per
    step). The jnp reference paths broadcast it for free.
    """
    k: Any
    v: Any

    @classmethod
    def create(cls, config: ModelConfig, batch: int, max_seq: int,
               dtype=jnp.bfloat16, kv_quant: str = "") -> "KVCache":
        shape = (config.n_layers, batch, config.n_kv_heads, max_seq,
                 config.head_dim)
        if kv_quant == "int8":
            def qz():
                return {"q": jnp.zeros(shape, jnp.int8),
                        "s": jnp.zeros(shape[:-2] + (1, shape[-2]),
                                       jnp.float32)}
            return cls(k=qz(), v=qz())
        return cls(k=jnp.zeros(shape, dtype=dtype),
                   v=jnp.zeros(shape, dtype=dtype))


def quantize_kv(x: jax.Array) -> tuple[jax.Array, jax.Array]:
    """Symmetric per-token-per-head int8 over the LAST dim (Dh).
    x [..., Dh] → (int8 same shape, f32 scale [...])."""
    xf = x.astype(jnp.float32)
    amax = jnp.max(jnp.abs(xf), axis=-1)
    s = jnp.maximum(amax, 1e-30) / 127.0
    q = jnp.clip(jnp.round(xf / s[..., None]), -127, 127).astype(jnp.int8)
    return q, s


def insert_kv(layer_k, layer_v, k_new: jax.Array,
              v_new: jax.Array, lengths: jax.Array,
              active: jax.Array | None):
    """Insert new tokens at [lengths, lengths+T) per row of the head-major
    cache ([B, KV, S, Dh]; or its int8 ``{"q","s"}`` dict). T is static;
    offsets are data — per-row dynamic_update_slice through vmap (XLA
    lowers this efficiently on TPU). Rows with ``active=False`` are left
    untouched: their cache is owned by the prefill path. The ONE copy of
    this layout-sensitive invariant — both the jnp and the Pallas
    attention paths go through it.
    """
    # Inactive rows: instead of a full-cache `where` (which copies every
    # byte of the cache each step), route their write to the row TAIL via
    # offset clamping (dynamic_update_slice clamps start to S-T). Tail
    # positions are never visible before being rewritten: position p is only
    # attended once some step has length >= p, and that step (prefill chunk
    # or decode insert at offset p) writes p first.
    quant = isinstance(layer_k, dict)
    S = (layer_k["q"] if quant else layer_k).shape[2]
    if active is not None:
        lengths = jnp.where(active, lengths, S)

    def insert(cache_row, new_row, offset):
        # cache_row [KV, S, Dh]; new_row [T, KV, Dh] → [KV, T, Dh]
        return jax.lax.dynamic_update_slice(
            cache_row, new_row.transpose(1, 0, 2).astype(cache_row.dtype),
            (0, offset, 0))

    def insert_s(scale_row, new_row, offset):
        # scale_row [KV, 1, S]; new_row [T, KV] → [KV, 1, T]
        return jax.lax.dynamic_update_slice(
            scale_row, new_row.T[:, None, :].astype(scale_row.dtype),
            (0, 0, offset))

    if quant:
        kq, ks = quantize_kv(k_new)                  # [B,T,KV,Dh], [B,T,KV]
        vq, vs = quantize_kv(v_new)
        return (
            {"q": jax.vmap(insert)(layer_k["q"], kq, lengths),
             "s": jax.vmap(insert_s)(layer_k["s"], ks, lengths)},
            {"q": jax.vmap(insert)(layer_v["q"], vq, lengths),
             "s": jax.vmap(insert_s)(layer_v["s"], vs, lengths)},
        )
    inserted_k = jax.vmap(insert)(layer_k, k_new, lengths)
    inserted_v = jax.vmap(insert)(layer_v, v_new, lengths)
    return inserted_k, inserted_v


def insert_kv_stacked(cache_k, cache_v,
                      k_news: jax.Array, v_news: jax.Array,
                      lengths: jax.Array,
                      active: jax.Array | None):
    """Insert every layer's new tokens into the FULL stacked cache with one
    scatter — the deferred-decode half of :func:`insert_kv`.

    cache_k/v: [L, B, KV, S, Dh] (or the int8 ``{"q","s"}`` dict);
    k_news/v_news: [L, B, T, KV, Dh] (the layer scan's stacked ys, always
    bf16/fp32 — quantization happens here at write time); lengths: [B].
    One vmap(dynamic_update_slice) over B for ALL layers costs ~40× less
    than a per-layer insert inside the scan: the per-layer form lowers to
    2·L serialized TPU scatters per step (~2 ms/step at L=22), the stacked
    form to one (~0.1 ms) — measured in tools/profile_insert.py. Inactive
    rows reuse insert_kv's clamp-to-tail trick (see there for the
    visibility argument)."""
    quant = isinstance(cache_k, dict)
    S = (cache_k["q"] if quant else cache_k).shape[3]
    if active is not None:
        lengths = jnp.where(active, lengths, S)

    def ins(ck, new, off):
        # ck [L, KV, S, Dh]; new [L, T, KV, Dh] → [L, KV, T, Dh]
        return jax.lax.dynamic_update_slice(
            ck, new.transpose(0, 2, 1, 3).astype(ck.dtype), (0, 0, off, 0))

    def ins_s(cs, new, off):
        # cs [L, KV, 1, S]; new [L, T, KV] → [L, KV, 1, T]
        return jax.lax.dynamic_update_slice(
            cs, new.transpose(0, 2, 1)[:, :, None, :].astype(cs.dtype),
            (0, 0, 0, off))

    if quant:
        kq, ks = quantize_kv(k_news)          # [L,B,T,KV,Dh], [L,B,T,KV]
        vq, vs = quantize_kv(v_news)
        vb = partial(jax.vmap, in_axes=(1, 1, 0), out_axes=1)
        return (
            {"q": vb(ins)(cache_k["q"], kq, lengths),
             "s": vb(ins_s)(cache_k["s"], ks, lengths)},
            {"q": vb(ins)(cache_v["q"], vq, lengths),
             "s": vb(ins_s)(cache_v["s"], vs, lengths)},
        )
    new_k = jax.vmap(ins, in_axes=(1, 1, 0), out_axes=1)(
        cache_k, k_news, lengths)
    new_v = jax.vmap(ins, in_axes=(1, 1, 0), out_axes=1)(
        cache_v, v_news, lengths)
    return new_k, new_v


def dense_decode_attention(q: jax.Array, k_new: jax.Array, v_new: jax.Array,
                           layer_k: jax.Array, layer_v: jax.Array,
                           lengths: jax.Array,
                           active: jax.Array | None = None,
                           window: int = 0) -> jax.Array:
    """Deferred-insert decode attention: one query token against the STALE
    cache prefix ``[0, lengths)`` plus the new token itself (self-column).

    Mathematically identical to insert-then-attend over ``[0, lengths]``,
    but the cache write is deferred so the layer scan never copies cache
    blocks through its ys (see :func:`insert_kv_stacked`). The two-piece
    softmax is computed explicitly (no [S+1] concat) so every S-reduction
    stays a clean sharded reduction under GSPMD for seq-sharded caches.

    q [B,1,H,Dh]; k_new/v_new [B,1,KV,Dh]; layer_k/v [B,KV,S,Dh] (stale;
    or the int8 ``{"q","s"}`` dict — scales fold into scores/probs).
    Returns out [B, 1, H*Dh]; writes nothing.
    """
    B, T, H, Dh = q.shape
    KV = k_new.shape[2]
    lk, ks, lv, vs = _kv_dequant_views(layer_k, layer_v, q.dtype)
    S = lk.shape[2]
    G = H // KV
    scale = Dh ** -0.5

    qg = q[:, 0].reshape(B, KV, G, Dh)
    kn = k_new[:, 0]                                    # [B, KV, Dh]
    vn = v_new[:, 0].astype(jnp.float32)
    scores = jnp.einsum("bkgd,bksd->bkgs", qg, lk,
                        preferred_element_type=jnp.float32) * scale
    if ks is not None:
        scores = scores * ks          # [B,KV,1,S] broadcasts over G
    self_s = jnp.einsum("bkgd,bkd->bkg", qg, kn,
                        preferred_element_type=jnp.float32) * scale

    visible = jnp.arange(S)[None, :] < lengths[:, None]            # [B, S]
    if window:
        # Sliding window (HF Mistral semantics): the query at position
        # `lengths` sees keys j with lengths - j < window; the self
        # column is always in-window.
        visible = visible & (jnp.arange(S)[None, :]
                             > (lengths - window)[:, None])
    if active is not None:
        visible = visible & active[:, None]
    scores = jnp.where(visible[:, None, None, :], scores, -1e30)

    m = jnp.maximum(jnp.max(scores, axis=-1), self_s)              # [B,KV,G]
    p = jnp.exp(scores - m[..., None])                             # [B,KV,G,S]
    p_self = jnp.exp(self_s - m)                                   # [B,KV,G]
    l = jnp.sum(p, axis=-1) + p_self
    if vs is not None:
        p = p * vs                    # [B,KV,1,S] broadcasts over G
    out = jnp.einsum("bkgs,bksd->bkgd", p.astype(lv.dtype), lv,
                     preferred_element_type=jnp.float32)
    out = (out + p_self[..., None] * vn[:, :, None, :]) / l[..., None]
    return out.reshape(B, 1, H * Dh).astype(q.dtype)


def _kv_dequant_views(layer_k, layer_v, dtype):
    """(k, ks, v, vs) from a plain or int8-quantized cache layer. The
    per-token scale factors OUT of the Dh contraction — scores multiply by
    ``ks`` after the QK dot, probs by ``vs`` before the PV dot — so no
    dequantized [S, Dh] copy ever materializes. Scales come back in their
    stored rank-4 form ([B, KV, 1, S] — the unit dim broadcasts over G in
    the [B, KV, G, S] score layout for free)."""
    if isinstance(layer_k, dict):
        return (layer_k["q"].astype(dtype), layer_k["s"],
                layer_v["q"].astype(dtype), layer_v["s"])
    return layer_k, None, layer_v, None


def dense_verify_attention(q: jax.Array, k_new: jax.Array, v_new: jax.Array,
                           layer_k: jax.Array, layer_v: jax.Array,
                           lengths: jax.Array,
                           active: jax.Array | None = None,
                           window: int = 0) -> jax.Array:
    """Deferred-insert BLOCK attention: T new tokens attend the STALE cache
    prefix ``[0, lengths)`` plus a causal self-block of themselves — the
    T>1 generalization of :func:`dense_decode_attention` (T=1 self-column).

    Mathematically identical to insert-then-attend over ``[0, lengths+T)``,
    but with no cache write inside the layer scan: the speculative verify
    step (engine/speculative.py, T = k+1) otherwise pays the chunk path's
    per-layer serialized scatters every step. Two-piece online softmax,
    clean S-reductions under GSPMD (same rationale as the decode twin).

    q [B,T,H,Dh]; k_new/v_new [B,T,KV,Dh]; layer_k/v [B,KV,S,Dh] (stale).
    Returns out [B, T, H*Dh]; writes nothing. ``window``: sliding-window
    bound (0 = full causal).
    """
    B, T, H, Dh = q.shape
    KV = k_new.shape[2]
    lk, ks, lv, vs = _kv_dequant_views(layer_k, layer_v, q.dtype)
    S = lk.shape[2]
    G = H // KV
    scale = Dh ** -0.5

    qg = q.reshape(B, T, KV, G, Dh).transpose(0, 2, 3, 1, 4)  # [B,KV,G,T,Dh]
    kn = k_new.transpose(0, 2, 1, 3)                          # [B,KV,T,Dh]
    vn = v_new.transpose(0, 2, 1, 3).astype(jnp.float32)
    scores = jnp.einsum("bkgtd,bksd->bkgts", qg, lk,
                        preferred_element_type=jnp.float32) * scale
    if ks is not None:
        scores = scores * ks[:, :, :, None, :]    # [B,KV,1,1,S]
    self_s = jnp.einsum("bkgtd,bkud->bkgtu", qg, kn,
                        preferred_element_type=jnp.float32) * scale
    if ks is not None:
        # Quantized cache: MIXED-PRECISION self-block. Plain decode sees a
        # drafted token u two different ways — full precision in its own
        # step's self-column (u == t), quantize→dequantize from the cache
        # in every LATER step (u < t, inserted by insert_kv_stacked). For
        # greedy parity with spec off, the verify block must reproduce
        # that split exactly: off-diagonal entries use the SAME
        # quantize_kv the insert path will apply to these k_new/v_new
        # (bitwise-identical q and s), with the same op order as the
        # stale path ((dot · scale) · s; probs · s before the PV dot,
        # cast to the cache view dtype). The diagonal stays full
        # precision, matching the decode self-column.
        knq, kns = quantize_kv(k_new)             # [B,T,KV,Dh], [B,T,KV]
        knq = knq.transpose(0, 2, 1, 3).astype(q.dtype)     # [B,KV,U,Dh]
        kns = kns.transpose(0, 2, 1)                        # [B,KV,U]
        self_sq = jnp.einsum("bkgtd,bkud->bkgtu", qg, knq,
                             preferred_element_type=jnp.float32) * scale
        self_sq = self_sq * kns[:, :, None, None, :]
        diag = jnp.eye(T, dtype=bool)[None, None, None]   # [1,1,1,T,U]
        self_s = jnp.where(diag, self_s, self_sq)

    visible = jnp.arange(S)[None, :] < lengths[:, None]            # [B, S]
    if window:
        # Query t sits at position lengths + t: stale key j visible iff
        # (lengths + t) - j < window — a per-(B, T) bound.
        q_pos = lengths[:, None] + jnp.arange(T)[None, :]          # [B, T]
        in_win = (jnp.arange(S)[None, None, :]
                  > (q_pos - window)[:, :, None])                  # [B, T, S]
        vis_ts = visible[:, None, :] & in_win
        if active is not None:
            vis_ts = vis_ts & active[:, None, None]
        scores = jnp.where(vis_ts[:, None, None, :, :], scores, -1e30)
    else:
        if active is not None:
            visible = visible & active[:, None]
        scores = jnp.where(visible[:, None, None, None, :], scores, -1e30)
    # Self-block: new token u is visible to query t iff u <= t (the query
    # itself is always visible, so the softmax denominator is >= 1).
    causal = (jnp.arange(T)[None, :] <= jnp.arange(T)[:, None])    # [T, T]
    if window:
        # Within-block window: u visible to t iff t - u < window.
        causal = causal & (jnp.arange(T)[None, :]
                           > jnp.arange(T)[:, None] - window)
    self_s = jnp.where(causal[None, None, None], self_s, -1e30)

    m = jnp.maximum(jnp.max(scores, axis=-1), jnp.max(self_s, axis=-1))
    p = jnp.exp(scores - m[..., None])                      # [B,KV,G,T,S]
    p_self = jnp.exp(self_s - m[..., None])                 # [B,KV,G,T,T]
    l = jnp.sum(p, axis=-1) + jnp.sum(p_self, axis=-1)
    if vs is not None:
        p = p * vs[:, :, :, None, :]              # [B,KV,1,1,S]
    out = jnp.einsum("bkgts,bksd->bkgtd", p.astype(lv.dtype), lv,
                     preferred_element_type=jnp.float32)
    if vs is not None:
        # Mixed-precision PV to match: off-diagonal drafted values go
        # through the same qdq + dtype cast as the stale path; the
        # diagonal uses the full-precision fp32 value like the decode
        # self-column. Masking by multiply is exact (×1.0 / ×0.0).
        vnq, vns = quantize_kv(v_new)             # [B,T,KV,Dh], [B,T,KV]
        vnq = vnq.transpose(0, 2, 1, 3).astype(q.dtype)     # [B,KV,U,Dh]
        vns = vns.transpose(0, 2, 1)                        # [B,KV,U]
        diag_f = jnp.eye(T, dtype=jnp.float32)[None, None, None]
        p_off = p_self * (1.0 - diag_f) * vns[:, :, None, None, :]
        out = out + jnp.einsum("bkgtu,bkud->bkgtd",
                               p_off.astype(vnq.dtype), vnq,
                               preferred_element_type=jnp.float32)
        out = out + jnp.einsum("bkgtu,bkud->bkgtd", p_self * diag_f, vn)
    else:
        out = out + jnp.einsum("bkgtu,bkud->bkgtd", p_self, vn)
    out = out / l[..., None]
    # [B,KV,G,T,Dh] → [B,T,H*Dh]
    out = out.transpose(0, 3, 1, 2, 4).reshape(B, T, H * Dh)
    return out.astype(q.dtype)


def dense_cache_attention(q: jax.Array, k_new: jax.Array, v_new: jax.Array,
                          layer_k: jax.Array, layer_v: jax.Array,
                          lengths: jax.Array,
                          active: jax.Array | None = None,
                          window: int = 0
                          ) -> tuple[jax.Array, jax.Array, jax.Array]:
    """Reference cache attention (pure jnp; the Pallas paged kernel replaces
    this on TPU — ops/paged_attention.py).

    q:      [B, T, H, Dh] (RoPE already applied)
    k_new:  [B, T, KV, Dh], v_new same — new tokens to insert at `lengths`.
    layer_k/v: [B, KV, S, Dh] — this layer's cache (head-major).
    lengths: [B] int32 — tokens already cached (insert offset).
    window: sliding-window bound (0 = full causal; HF Mistral semantics —
    query at position i sees keys j with i - j < window).
    Returns (attn_out [B, T, H*Dh], updated layer_k, layer_v).
    """
    B, T, H, Dh = q.shape
    KV = k_new.shape[2]

    layer_k, layer_v = insert_kv(layer_k, layer_v, k_new, v_new,
                                 lengths, active)
    lk, ks, lv, vs = _kv_dequant_views(layer_k, layer_v, q.dtype)
    S = lk.shape[2]

    # GQA WITHOUT materializing repeated KV: group the query heads
    # [B,T,H,Dh] → [B,KV,G,T,Dh] and contract each group against its single
    # KV head. bf16 reads + fp32 MXU accumulation (preferred_element_type)
    # — no fp32 copy of the cache, no 8× `repeat` traffic.
    group = H // KV
    qg = q.reshape(B, T, KV, group, Dh).transpose(0, 2, 3, 1, 4)
    scores = jnp.einsum("bkgtd,bksd->bkgts", qg, lk,
                        preferred_element_type=jnp.float32)
    scores = scores / jnp.sqrt(jnp.asarray(Dh, jnp.float32))
    if ks is not None:
        scores = scores * ks[:, :, :, None, :]    # [B,KV,1,1,S]

    # Mask: key position s is visible to query t iff s <= lengths + t
    # (and, with a sliding window, within `window` of it).
    q_pos = lengths[:, None] + jnp.arange(T)[None, :]          # [B, T]
    s_idx = jnp.arange(S)[None, None, :]                        # [1, 1, S]
    visible = s_idx <= q_pos[:, :, None]                        # [B, T, S]
    if window:
        visible = visible & (s_idx > q_pos[:, :, None] - window)
    if active is not None:
        visible = visible & active[:, None, None]
    scores = jnp.where(visible[:, None, None, :, :], scores, -1e30)

    probs = jax.nn.softmax(scores, axis=-1)
    if vs is not None:
        probs = probs * vs[:, :, :, None, :]      # [B,KV,1,1,S]
    out = jnp.einsum("bkgts,bksd->bkgtd", probs.astype(lv.dtype),
                     lv, preferred_element_type=jnp.float32)
    # [B,KV,G,T,Dh] → [B,T,H*Dh]
    out = out.transpose(0, 3, 1, 2, 4).reshape(B, T, H * Dh)
    return out.astype(q.dtype), layer_k, layer_v


# The default attention provider supports the deferred-decode protocol
# (forward() docstring): decode steps attend the stale cache + self-column
# and the cache write happens once per step via insert_kv_stacked.
dense_cache_attention.decode = dense_decode_attention
dense_cache_attention.insert_all = insert_kv_stacked


@lru_cache(maxsize=8)
def windowed_dense_attention(window: int):
    """The default dense provider with a sliding-window bound threaded
    through every path (chunk, deferred decode, spec verify) —
    ``forward`` swaps it in for ``config.sliding_window`` models
    (mistral family). Memoized so the provider identity is stable."""
    def fn(q, k_new, v_new, layer_k, layer_v, lengths, active=None):
        return dense_cache_attention(q, k_new, v_new, layer_k, layer_v,
                                     lengths, active, window=window)
    fn.decode = partial(dense_decode_attention, window=window)
    # No ``.verify`` here: that attribute reroutes EVERY T>1 call (prefill
    # chunks included) through the deferred block path — the spec engine
    # adds its windowed verify via _spec_verify_attention_fn instead.
    fn.insert_all = insert_kv_stacked
    return fn


_GATE_ACTS = {
    "silu": jax.nn.silu,                                      # llama/qwen2
    "gelu_tanh": partial(jax.nn.gelu, approximate=True),      # gemma GeGLU
}


def swiglu_mlp(x: jax.Array, wg: jax.Array, wu: jax.Array,
               wd: jax.Array, act: str = "silu") -> jax.Array:
    """Gated MLP (SwiGLU for llama/qwen2, GeGLU for gemma via ``act``).
    Each weight is a plain array or an int8 ``{"q","s"}`` dict
    (models/quant.py) — ``mm`` dispatches."""
    gate = _GATE_ACTS[act](mm(x, wg))
    return mm(gate * mm(x, wu), wd)


def qkv_proj(h: jax.Array, lp: dict, config: ModelConfig
             ) -> tuple[jax.Array, jax.Array, jax.Array]:
    """Q/K/V projections with the optional qwen2-family bias, RoPE NOT yet
    applied. THE one copy of this block — the sequential layer scan and the
    pipeline-parallel staged block both call it (the bias was once added to
    only one of the two, silently forking the model). ``"bq" in lp`` is
    static at trace time. h [B, T, D] → q [B,T,H,Dh], k/v [B,T,KV,Dh]."""
    c = config
    B, T = h.shape[0], h.shape[1]
    dh = c.head_dim
    qp, kp, vp = mm(h, lp["wq"]), mm(h, lp["wk"]), mm(h, lp["wv"])
    if "bq" in lp:
        qp, kp, vp = qp + lp["bq"], kp + lp["bk"], vp + lp["bv"]
    return (qp.reshape(B, T, c.n_heads, dh),
            kp.reshape(B, T, c.n_kv_heads, dh),
            vp.reshape(B, T, c.n_kv_heads, dh))


# ---------------------------------------------------------------------------
# Forward
# ---------------------------------------------------------------------------

def forward(params: Params, config: ModelConfig, tokens: jax.Array,
            lengths: jax.Array, cache: KVCache,
            active: jax.Array | None = None,
            attention_fn: Callable = dense_cache_attention,
            mlp_fn: Callable | None = None,
            ) -> tuple[jax.Array, KVCache]:
    """One forward pass over new tokens (prefill chunk or single decode step).

    tokens:  [B, T] int32 — new token ids.
    lengths: [B] int32 — tokens already in the cache per slot.
    active:  [B] bool — mask for live batch slots (padding slots compute but
             can't corrupt anything; their cache rows are reset on admit).
    Returns (logits [B, T, V] fp32, updated cache).
    """
    c = config
    B, T = tokens.shape
    dh = c.head_dim
    if c.sliding_window and attention_fn is dense_cache_attention:
        # Mistral-family sliding window, threaded through the default
        # dense provider. Explicit providers must carry the window
        # themselves: the engine builds the flash kernels with it
        # (single-device), and excludes seq/paged/multi-chip-pallas for
        # SWA models at build.
        attention_fn = windowed_dense_attention(c.sliding_window)

    x = jnp.take(params["embed"], tokens, axis=0)   # [B, T, D]
    if c.scale_embed:
        # Gemma scales embeddings by sqrt(D) *in the model dtype* (HF casts
        # the normalizer to hidden-state dtype — match its rounding).
        x = x * jnp.asarray(c.d_model ** 0.5, x.dtype)

    positions = lengths[:, None] + jnp.arange(T)[None, :]       # [B, T]
    cos, sin = rope_tables(positions, dh, c.rope_theta, c.rope_scaling)

    layer_params = params["layers"]
    custom_mlp = mlp_fn

    # Deferred-insert protocol: an attention_fn may carry a ``.decode``
    # (T=1: stale-cache + self-column attention, NO cache write), a
    # ``.verify`` (T>1 twin with a causal self-block — the speculative
    # verify path), and an ``.insert_all`` (one stacked insert for every
    # layer's new tokens). This keeps the full-extent cache OUT of the
    # layer scan's ys — the per-layer functional cache update costs
    # ~2 ms/step in serialized scatters at L=22 (tools/profile_insert.py);
    # the deferred form stacks only the tiny [L,B,T,KV,Dh] new tokens and
    # inserts once. Providers WITHOUT ``.verify`` (the prefill chunk path,
    # Pallas causal kernels) keep insert-then-attend for T>1.
    decode_attend = getattr(attention_fn, "decode", None) if T == 1 else \
        getattr(attention_fn, "verify", None)

    # Phase markers (ISSUE 8): named_scope is trace-time op metadata —
    # zero runtime cost — so profiler captures segment each layer into
    # its attention and MLP halves in Perfetto. "decode" = the deferred-
    # insert path (T=1 decode and the speculative verify), "prefill" =
    # the insert-then-attend chunk path.
    scope = "decode" if decode_attend is not None else "prefill"

    def layer_step(x, scanned):
        lp, layer_k, layer_v = scanned
        # Attention block
        with jax.named_scope(f"{scope}.attention"):
            h = rms_norm(x, lp["attn_norm"], c.rms_eps, c.rms_offset)
            q, k, v = qkv_proj(h, lp, c)
            q = apply_rope(q, cos, sin)
            k = apply_rope(k, cos, sin)
            if decode_attend is not None:
                attn = decode_attend(q, k, v, layer_k, layer_v, lengths,
                                     active)
                ys = (k, v)                   # stacked for insert_all below
            else:
                attn, layer_k, layer_v = attention_fn(
                    q, k, v, layer_k, layer_v, lengths, active)
                ys = (layer_k, layer_v)
            x = x + mm(attn, lp["wo"])
        # MLP block
        with jax.named_scope(f"{scope}.mlp"):
            h = rms_norm(x, lp["mlp_norm"], c.rms_eps, c.rms_offset)
            if custom_mlp is not None:
                x = x + custom_mlp(h, lp)
            else:
                x = x + swiglu_mlp(h, lp["wg"], lp["wu"], lp["wd"], c.act)
        return x, ys

    x, (ys_k, ys_v) = jax.lax.scan(
        layer_step, x, (layer_params, cache.k, cache.v))
    if decode_attend is not None:
        new_k, new_v = attention_fn.insert_all(
            cache.k, cache.v, ys_k, ys_v, lengths, active)
    else:
        new_k, new_v = ys_k, ys_v

    x = rms_norm(x, params["final_norm"], c.rms_eps, c.rms_offset)
    head = _select_head(params, c)
    # bf16 (or int8) reads of the [V, D] head with MXU accumulation — an
    # explicit astype would materialize a fp32 copy of the vocab matrix.
    logits = head_matmul(x, head)
    return logits, KVCache(k=new_k, v=new_v)


def _select_head(params: Params, c: ModelConfig):
    """The LM head weight: ``lm_head`` (untied), or for tied-embedding
    models the int8 head copy ``lm_head_q8`` when quantized (models/
    quant.py quantize_tree) else the embed table itself."""
    if c.tie_embeddings:
        return params["lm_head_q8"] if "lm_head_q8" in params \
            else params["embed"]
    return params["lm_head"]
