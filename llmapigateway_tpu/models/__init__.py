from .config import ModelConfig, PRESETS, get_preset

__all__ = ["ModelConfig", "PRESETS", "get_preset"]
