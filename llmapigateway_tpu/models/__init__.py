"""Model families. Family dispatch: the engine asks for (init, forward) by
ModelConfig.family so new architectures plug in without engine changes."""
from .config import ModelConfig, PRESETS, get_preset


def forward_fn(config: ModelConfig):
    """The forward callable for a family, uniform signature:
    (params, config, tokens, lengths, cache, active=None) → (logits, cache)."""
    if config.is_moe:
        from . import mixtral
        return mixtral.forward
    from . import llama
    return llama.forward


def init_fn(config: ModelConfig):
    """Random-init callable for a family: (config, key, dtype) → params."""
    if config.is_moe:
        from . import mixtral
        return mixtral.init_params
    from . import llama
    return llama.init_params


__all__ = ["ModelConfig", "PRESETS", "get_preset", "forward_fn", "init_fn"]
