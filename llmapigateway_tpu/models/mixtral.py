"""Mixtral-family sparse-MoE decoder as pure JAX functions.

Covers the reference-parity gap called out in SURVEY.md §2b (Expert
Parallelism, BASELINE config 4: Mixtral-8×7B on v5e-8). The attention/norm
stack is shared with models/llama.py — only the MLP block differs: a top-k
router over ``n_experts`` SwiGLU experts.

TPU-first design:

* **Expert weights stacked on a leading expert dim** (``wg/wu/wd:
  [L, E, D, F]``, router ``[L, D, E]``) so one einsum batches all experts —
  the expert dim shards on the ``expert`` mesh axis (parallel/sharding.py)
  and GSPMD inserts the token all-to-all.
* **Two routing implementations**, both static-shape (no data-dependent
  shapes, jit-stable):
  - ``moe_mlp_dense`` — every expert computes every token, combined with
    the (top-k-masked) router weights. Exact, never drops a token; the
    right choice for decode steps and small prefill chunks where the MoE
    FFN is weight-bandwidth-bound anyway (all E experts' weights stream
    from HBM regardless of routing, so the extra FLOPs ride free on the
    MXU).
  - ``moe_mlp_dispatch`` — GShard/Mesh-TensorFlow capacity-based dispatch:
    one-hot dispatch tensor [N, E, C] built from a cumsum over the routing
    mask, expert FFN batched over [E, C, D], combine weighted by router
    probs. FLOPs scale with top-k, not E; tokens past an expert's capacity
    are dropped (contribute zero), standard for large prefill. Capacity
    C = ceil(k·N/E · capacity_factor).
* Router math in fp32 (softmax over the *top-k logits*, matching Mixtral's
  renormalized top-k semantics).

Checkpoint mapping: engine/checkpoint.py maps HF ``block_sparse_moe.gate``
→ ``layers.router`` and ``experts.{e}.w1/w3/w2`` → ``wg/wu/wd`` with the
[L, E, D, F] layout this module consumes.
"""
from __future__ import annotations

import math
from typing import Any

import jax
import jax.numpy as jnp

from . import llama
from .config import ModelConfig
from .quant import moe_mm_batched, moe_mm_dense

Params = dict[str, Any]


def init_params(config: ModelConfig, key: jax.Array,
                dtype: jnp.dtype = jnp.bfloat16) -> Params:
    """Random-init params: llama layout with MoE expert MLPs.

    Layout deltas vs llama.init_params:
      layers/router [L, D, E]; layers/{wg,wu,wd} gain an expert dim:
      wg/wu [L, E, D, F], wd [L, E, F, D].
    """
    c = config
    if not c.is_moe:
        raise ValueError("mixtral.init_params needs n_experts > 0")
    base_key, moe_key = jax.random.split(key)
    params = llama.init_params(c, base_key, dtype=dtype)
    keys = jax.random.split(moe_key, 4)
    L, E, D, F = c.n_layers, c.n_experts, c.d_model, c.d_ff

    def dense_init(k, *shape):
        fan_in = shape[-2]
        scale = 1.0 / math.sqrt(fan_in)
        return (jax.random.normal(k, shape, dtype=jnp.float32) * scale
                ).astype(dtype)

    layers = params["layers"]
    layers["router"] = dense_init(keys[0], L, D, E)
    layers["wg"] = dense_init(keys[1], L, E, D, F)
    layers["wu"] = dense_init(keys[2], L, E, D, F)
    layers["wd"] = dense_init(keys[3], L, E, F, D)
    return params


def route(x_flat: jax.Array, router: jax.Array,
          k: int) -> jax.Array:
    """Top-k routing weights. x_flat [N, D], router [D, E] → probs [N, E]
    (fp32; zero outside each token's top-k; softmax over top-k logits —
    Mixtral's renormalized semantics)."""
    logits = x_flat.astype(jnp.float32) @ router.astype(jnp.float32)  # [N, E]
    top_vals, top_idx = jax.lax.top_k(logits, k)                      # [N, k]
    top_w = jax.nn.softmax(top_vals, axis=-1)                         # [N, k]
    onehot = jax.nn.one_hot(top_idx, logits.shape[-1],
                            dtype=jnp.float32)                        # [N, k, E]
    return jnp.einsum("nk,nke->ne", top_w, onehot)


def moe_mlp_dense(x: jax.Array, lp: Params, config: ModelConfig) -> jax.Array:
    """All-experts MoE MLP (exact; no capacity drops).

    x [B, T, D]; lp carries this layer's router [D, E], wg/wu [E, D, F],
    wd [E, F, D]. Returns [B, T, D].
    """
    B, T, D = x.shape
    xf = x.reshape(B * T, D)
    probs = route(xf, lp["router"], config.experts_per_token)   # [N, E]
    # Batched expert FFN over the expert dim: [E, N, F]. Expert weights
    # may be int8 {"q","s"} dicts (models/quant.py) — the moe_mm helpers
    # dispatch, like `mm` does for the dense family.
    h = moe_mm_dense(xf, lp["wg"])
    u = moe_mm_dense(xf, lp["wu"])
    y = moe_mm_batched(jax.nn.silu(h) * u, lp["wd"])
    out = jnp.einsum("end,ne->nd", y.astype(jnp.float32), probs)
    return out.reshape(B, T, D).astype(x.dtype)


def moe_mlp_dispatch(x: jax.Array, lp: Params, config: ModelConfig,
                     capacity_factor: float = 2.0) -> jax.Array:
    """Capacity-based dispatch MoE MLP (GShard einsum formulation).

    FLOPs ∝ top-k instead of E; tokens beyond an expert's capacity are
    dropped (contribute zero to the residual). Static shapes throughout:
    C depends only on N/E/k/capacity_factor, all compile-time constants.
    """
    B, T, D = x.shape
    N = B * T
    E, k = config.n_experts, config.experts_per_token
    C = max(1, math.ceil(k * N / E * capacity_factor))
    C = min(C, N)

    xf = x.reshape(N, D)
    probs = route(xf, lp["router"], k)                           # [N, E] fp32
    mask = probs > 0.0                                           # [N, E]
    # Position of each token within its expert's queue (1-based), N-major so
    # earlier tokens win capacity.
    position = jnp.cumsum(mask.astype(jnp.int32), axis=0) * mask  # [N, E]
    keep = mask & (position <= C)
    # One-hot over capacity slots: dispatch [N, E, C].
    dispatch = (jax.nn.one_hot(position - 1, C, dtype=xf.dtype)
                * keep[..., None].astype(xf.dtype))
    combine = dispatch.astype(jnp.float32) * probs[..., None]    # [N, E, C]

    xs = jnp.einsum("nd,nec->ecd", xf, dispatch)                 # [E, C, D]
    h = moe_mm_batched(xs, lp["wg"])
    u = moe_mm_batched(xs, lp["wu"])
    ys = moe_mm_batched(jax.nn.silu(h) * u, lp["wd"])
    out = jnp.einsum("ecd,nec->nd", ys.astype(jnp.float32), combine)
    return out.reshape(B, T, D).astype(x.dtype)


def make_mlp_fn(config: ModelConfig, dispatch_threshold: int = 64,
                capacity_factor: float = 2.0):
    """The ``mlp_fn`` hook for llama.forward: picks dense vs dispatch by
    (static) shape — decode steps (T==1) and small chunks always run exact
    dense (capacity drops would silently degrade generation quality under
    routing imbalance); only long prefill chunks run capacity dispatch."""
    def mlp_fn(h: jax.Array, lp: Params) -> jax.Array:
        B, T, _ = h.shape
        if T == 1 or B * T <= dispatch_threshold:
            return moe_mlp_dense(h, lp, config)
        return moe_mlp_dispatch(h, lp, config,
                                capacity_factor=capacity_factor)
    return mlp_fn


def forward(params: Params, config: ModelConfig, tokens: jax.Array,
            lengths: jax.Array, cache: llama.KVCache,
            active: jax.Array | None = None,
            attention_fn=llama.dense_cache_attention,
            ) -> tuple[jax.Array, llama.KVCache]:
    """Mixtral forward = llama forward with the MoE MLP plugged in."""
    return llama.forward(params, config, tokens, lengths, cache,
                         active=active, attention_fn=attention_fn,
                         mlp_fn=make_mlp_fn(config))
