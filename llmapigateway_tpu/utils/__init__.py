from .logging_setup import configure_logging
from .sse import SSEParser, format_sse, SSE_DONE

__all__ = ["configure_logging", "SSEParser", "format_sse", "SSE_DONE"]
