"""Structured JSON logging to console + rotating file.

Parity with the reference's ``configure_logging``
(``llm_gateway_core/utils/logging_setup.py:14-54``): JSON lines, console +
256 KB x 5 rotating file, noisy HTTP libraries demoted to WARNING. Implemented
on stdlib only (no python-json-logger dependency).
"""
from __future__ import annotations

import json
import logging
import logging.handlers
import time
from pathlib import Path

_LOG_MAX_BYTES = 256 * 1024
_LOG_BACKUPS = 5


class JsonFormatter(logging.Formatter):
    """One JSON object per line; includes any `extra` fields."""

    _SKIP = frozenset(
        "name msg args levelname levelno pathname filename module exc_info "
        "exc_text stack_info lineno funcName created msecs relativeCreated "
        "thread threadName processName process taskName message".split())

    def format(self, record: logging.LogRecord) -> str:
        out = {
            "ts": time.strftime("%Y-%m-%dT%H:%M:%S", time.localtime(record.created))
                  + f".{int(record.msecs):03d}",
            "level": record.levelname,
            "logger": record.name,
            "message": record.getMessage(),
        }
        for key, val in record.__dict__.items():
            if key not in self._SKIP and not key.startswith("_"):
                try:
                    json.dumps(val)
                    out[key] = val
                except (TypeError, ValueError):
                    out[key] = repr(val)
        if record.exc_info:
            out["exc"] = self.formatException(record.exc_info)
        return json.dumps(out, ensure_ascii=False)


def configure_logging(logs_dir: Path | str = "logs", level: str = "INFO") -> None:
    logs_path = Path(logs_dir)
    logs_path.mkdir(parents=True, exist_ok=True)

    root = logging.getLogger()
    root.setLevel(getattr(logging, level.upper(), logging.INFO))
    # Idempotent: replace our handlers on re-configure instead of stacking.
    for h in list(root.handlers):
        if getattr(h, "_llmgw", False):
            root.removeHandler(h)

    fmt = JsonFormatter()
    console = logging.StreamHandler()
    console.setFormatter(fmt)
    console._llmgw = True  # type: ignore[attr-defined]
    root.addHandler(console)

    filehandler = logging.handlers.RotatingFileHandler(
        logs_path / "gateway.log", maxBytes=_LOG_MAX_BYTES, backupCount=_LOG_BACKUPS)
    filehandler.setFormatter(fmt)
    filehandler._llmgw = True  # type: ignore[attr-defined]
    root.addHandler(filehandler)

    for noisy in ("httpcore", "httpx", "aiohttp.access", "jax", "urllib3"):
        logging.getLogger(noisy).setLevel(logging.WARNING)


SENSITIVE_HEADERS = frozenset(
    ("authorization", "api-key", "x-api-key", "proxy-authorization", "cookie"))


def mask_headers(headers: dict[str, str]) -> dict[str, str]:
    """Mask secret-bearing headers for logs (cf. request_logging.py:37-45)."""
    out = {}
    for k, v in headers.items():
        if k.lower() in SENSITIVE_HEADERS and v:
            out[k] = v[:12] + "****" if len(v) > 16 else "****"
        else:
            out[k] = v
    return out
