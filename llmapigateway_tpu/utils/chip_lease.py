"""Advisory flock-based chip lease shared by bench runs and watcher probes.

A TPU chip admits ONE process at a time: any ``jax.devices()`` call —
including a builder-side tunnel-watcher "is the chip alive?" probe — holds
the runtime until the process exits, and a probe that overlaps the
driver's bench turns the whole scoreboard into an rc=2 empty JSON (the
round-5 failure: ``BENCH_r05.json``'s "candidate holders" list was the
builder's own watch-script probes). The fix is a single advisory lock
file every chip user takes FIRST:

* ``bench.py`` takes the lease with a bounded wait before its backend
  probe and holds it for the whole run — a probe can delay the bench a
  few seconds, never kill it.
* Watcher probes take it NON-BLOCKING (``timeout_s=0``) and simply skip
  the probe cycle when the bench holds it:

      python -m llmapigateway_tpu.utils.chip_lease --timeout 0 -- \
          python -c "import jax; jax.devices()"

The lock is ``flock(2)`` on ``/tmp/tpu_chip.lock``: per open-file-
description (two opens conflict even in one process), released by the
kernel on ANY process exit — a SIGKILLed bench can never wedge the chip
behind a stale lockfile the way a pid-file scheme would.
"""
from __future__ import annotations

import contextlib
import os
import sys
import time

LOCK_PATH = "/tmp/tpu_chip.lock"


def _read_holder(path: str) -> str:
    try:
        with open(path) as f:
            return f.read(200).strip()
    except OSError:
        return ""


@contextlib.contextmanager
def chip_lease(path: str = LOCK_PATH, timeout_s: float = 0.0,
               poll_s: float = 0.5, label: str = ""):
    """Hold the exclusive chip lease for the duration of the ``with``.

    ``timeout_s=0`` is a non-blocking try. Raises ``TimeoutError`` (with
    the current holder's label, if it wrote one) when the lease can't be
    taken in time. The holder label (pid + argv by default) is written
    into the lock file purely for diagnostics."""
    import fcntl
    fd = os.open(path, os.O_CREAT | os.O_RDWR, 0o666)
    t0 = time.monotonic()
    try:
        while True:
            try:
                fcntl.flock(fd, fcntl.LOCK_EX | fcntl.LOCK_NB)
                break
            except OSError:
                if time.monotonic() - t0 >= timeout_s:
                    holder = _read_holder(path)
                    raise TimeoutError(
                        f"chip lease {path} held"
                        + (f" by [{holder}]" if holder else "")
                        + f" (waited {time.monotonic() - t0:.1f}s)"
                    ) from None
                time.sleep(poll_s)
        me = label or f"pid {os.getpid()}: {' '.join(sys.argv)[:120]}"
        with contextlib.suppress(OSError):
            os.ftruncate(fd, 0)
            os.pwrite(fd, me.encode(), 0)
        yield
    finally:
        with contextlib.suppress(OSError):
            os.ftruncate(fd, 0)
            fcntl.flock(fd, fcntl.LOCK_UN)
        os.close(fd)


def main(argv: list[str] | None = None) -> int:
    """CLI wrapper: run a command under the lease, or report lease state.

    ``... chip_lease [--timeout S] [--path P] -- CMD ARGS...`` runs CMD
    holding the lease and propagates its exit code; 75 (EX_TEMPFAIL) when
    the lease can't be taken — the watcher's cue to skip this cycle.
    With no command, prints ``free`` / ``held [holder]`` and exits 0/1.
    """
    import argparse
    import subprocess
    argv = sys.argv[1:] if argv is None else argv
    cmd: list[str] = []
    if "--" in argv:
        i = argv.index("--")
        argv, cmd = argv[:i], argv[i + 1:]
    ap = argparse.ArgumentParser(prog="chip_lease")
    ap.add_argument("--timeout", type=float, default=0.0)
    ap.add_argument("--path", default=LOCK_PATH)
    args = ap.parse_args(argv)
    try:
        with chip_lease(args.path, timeout_s=args.timeout):
            if not cmd:
                print("free")
                return 0
            return subprocess.run(cmd).returncode
    except TimeoutError as e:
        if not cmd:
            print(f"held [{_read_holder(args.path)}]")
            return 1
        print(f"chip_lease: {e}", file=sys.stderr)
        return 75


if __name__ == "__main__":
    sys.exit(main())
