"""JAX API compatibility shims.

One home for version-portability so kernel/parallel code reads as if on
current JAX: every ``shard_map`` site in ops/ and parallel/ calls the
wrapper below with the new public keyword surface, and the shim maps it
onto whatever the installed JAX provides.
"""
from __future__ import annotations

import jax

try:                        # public API from jax 0.5+
    from jax import shard_map as _shard_map_impl
    _SHARD_MAP_NEW_API = True
except (ImportError, AttributeError):
    from jax.experimental.shard_map import shard_map as _shard_map_impl
    _SHARD_MAP_NEW_API = False

# Whether partially-manual shard_map (axis_names a strict subset of the
# mesh axes, the rest left to GSPMD) is trustworthy. The legacy
# `auto=` form miscompiles programs that combine ppermute/psum with a
# real (>1) auto axis — observed as an XLA abort (not a Python error)
# compiling the pipeline schedule with pipe x model — so callers that
# need real partial-auto must check this and fail cleanly first.
# Size-1 auto axes are fine either way.
SHARD_MAP_PARTIAL_AUTO_OK = _SHARD_MAP_NEW_API


def axis_size(axis_name) -> int:
    """``jax.lax.axis_size`` where it exists; the classic ``psum(1, axis)``
    idiom (statically folded to the axis size) on older jax."""
    if hasattr(jax.lax, "axis_size"):
        return jax.lax.axis_size(axis_name)
    return jax.lax.psum(1, axis_name)


def shard_map(f, *, mesh, in_specs, out_specs, axis_names=None,
              check_vma=False):
    """jax.shard_map with the new keyword surface, runnable on older jax:
    ``axis_names`` (the axes to go Manual over) maps to the legacy
    ``auto`` complement, ``check_vma`` to ``check_rep``."""
    if _SHARD_MAP_NEW_API:
        kwargs = {"check_vma": check_vma}
        if axis_names is not None:
            kwargs["axis_names"] = frozenset(axis_names)
        return _shard_map_impl(f, mesh=mesh, in_specs=in_specs,
                               out_specs=out_specs, **kwargs)
    kwargs = {"check_rep": check_vma}
    if axis_names is not None:
        auto = frozenset(mesh.axis_names) - frozenset(axis_names)
        if auto:
            kwargs["auto"] = auto
    return _shard_map_impl(f, mesh=mesh, in_specs=in_specs,
                           out_specs=out_specs, **kwargs)
