"""Server-Sent Events frame parsing and formatting.

The reference parses every upstream SSE chunk **twice** — once in the
dispatcher for error/usage sniffing (``services/request_handler.py:102-146``)
and again in the logging thread (``middleware/chat_logging.py:104-146``), see
SURVEY.md §3.2. Here parsing happens exactly once, in an incremental parser
shared by the dispatch path and the usage-capture observer.
"""
from __future__ import annotations

import json
from dataclasses import dataclass, field
from typing import Any, Iterator

SSE_DONE = "[DONE]"


def format_sse(data: Any) -> bytes:
    """Format one SSE data frame. `data` may be a dict (JSON-encoded) or str.
    Embedded newlines become multiple ``data:`` lines per the SSE spec (a bare
    continuation line would be silently dropped by conforming clients)."""
    if isinstance(data, (dict, list)):
        payload = json.dumps(data, ensure_ascii=False, separators=(",", ":"))
    else:
        payload = str(data)
    body = "".join(f"data: {line}\n" for line in payload.split("\n"))
    return (body + "\n").encode()


@dataclass
class SSEFrame:
    """One parsed SSE event: raw data string plus lazily-parsed JSON."""
    data: str
    _json: Any = field(default=None, repr=False)
    _json_tried: bool = field(default=False, repr=False)

    @property
    def is_done(self) -> bool:
        return self.data.strip() == SSE_DONE

    @property
    def json(self) -> Any | None:
        """The frame's JSON payload, or None if not JSON / is [DONE]."""
        if not self._json_tried:
            self._json_tried = True
            s = self.data.strip()
            if s and s != SSE_DONE and s[0] in "{[":
                try:
                    self._json = json.loads(s)
                except ValueError:
                    self._json = None
        return self._json


class SSEParser:
    """Incremental byte-stream → SSEFrame parser with partial-frame buffering.

    Frames are delimited by a blank line; multiple ``data:`` lines in one
    event are joined per the SSE spec. Tolerates ``\\r\\n`` line endings and
    incomplete trailing frames (kept in the buffer until the next feed),
    the behavior the reference reimplements ad hoc at
    ``request_handler.py:34-42``.
    """

    def __init__(self) -> None:
        self._buf = b""

    def feed(self, chunk: bytes) -> Iterator[SSEFrame]:
        self._buf += chunk
        while True:
            # Find the earliest blank-line delimiter (\n\n or \r\n\r\n).
            idx_nn = self._buf.find(b"\n\n")
            idx_rr = self._buf.find(b"\r\n\r\n")
            if idx_nn == -1 and idx_rr == -1:
                return
            if idx_rr != -1 and (idx_nn == -1 or idx_rr < idx_nn):
                raw, self._buf = self._buf[:idx_rr], self._buf[idx_rr + 4:]
            else:
                raw, self._buf = self._buf[:idx_nn], self._buf[idx_nn + 2:]
            frame = self._parse_event(raw)
            if frame is not None:
                yield frame

    def flush(self) -> Iterator[SSEFrame]:
        """Parse whatever remains in the buffer as a final (unterminated) event."""
        if self._buf.strip():
            frame = self._parse_event(self._buf)
            self._buf = b""
            if frame is not None:
                yield frame
        else:
            self._buf = b""

    @staticmethod
    def _parse_event(raw: bytes) -> SSEFrame | None:
        data_lines: list[str] = []
        for line in raw.decode("utf-8", errors="replace").splitlines():
            if line.startswith("data:"):
                data_lines.append(line[5:].lstrip(" "))
            # comment lines (":") and other fields (event:, id:) are ignored
        if not data_lines:
            return None
        return SSEFrame(data="\n".join(data_lines))


def frame_error_detail(obj: Any) -> str | None:
    """Detect an in-band error object inside an SSE JSON frame / response body.

    Providers signal errors three ways the reference handles
    (``request_handler.py:83-93,125-133,160-172``): a top-level ``error``
    object, a ``detail`` field, or a bare ``code`` field mid-stream.
    Returns a human-readable detail string, or None if the frame is healthy.
    """
    if not isinstance(obj, dict):
        return None
    if "error" in obj and obj["error"]:
        err = obj["error"]
        if isinstance(err, dict):
            return str(err.get("message") or err)
        return str(err)
    if "detail" in obj and obj["detail"] and "choices" not in obj:
        return str(obj["detail"])
    if "code" in obj and "choices" not in obj and "id" not in obj:
        return f"upstream error code {obj['code']}"
    return None
