"""Per-(api-key, gateway-model) round-robin rotation state in SQLite.

Parity with the reference's ``ModelRotationDB``
(``llm_gateway_core/db/model_rotation_db.py:36-110``): rotation indices
survive restarts; first use yields index 0; subsequent calls advance
``(last+1) % total`` atomically; any DB error degrades to index 0 rather than
failing the request.

Unlike the reference (which opens a fresh connection per call and blocks the
event loop — ``chat.py:66-72``), one connection is kept per DB instance and
async callers go through :meth:`next_index_async` (thread offload).
"""
from __future__ import annotations

import asyncio
import logging
import sqlite3
import threading
from pathlib import Path

logger = logging.getLogger(__name__)


class RotationDB:
    def __init__(self, db_dir: Path | str = "db"):
        path = Path(db_dir)
        path.mkdir(parents=True, exist_ok=True)
        self._path = path / "rotation.db"
        self._lock = threading.Lock()
        # One shared connection; every statement runs under the lock
        # (check_same_thread=False makes cross-thread use legal, not safe).
        self._conn = sqlite3.connect(self._path,
                                     check_same_thread=False)  # guarded-by: _lock
        with self._lock:
            self._conn.execute(
                """CREATE TABLE IF NOT EXISTS model_rotation (
                       api_key TEXT NOT NULL,
                       gateway_model TEXT NOT NULL,
                       last_model_index INTEGER NOT NULL,
                       PRIMARY KEY (api_key, gateway_model)
                   )""")
            self._conn.commit()

    def next_index(self, api_key: str, gateway_model: str, total: int) -> int:
        """Advance and persist the rotation pointer; 0 on first use or error."""
        if total <= 0:
            return 0
        try:
            with self._lock:
                cur = self._conn.execute(
                    "SELECT last_model_index FROM model_rotation "
                    "WHERE api_key=? AND gateway_model=?",
                    (api_key, gateway_model))
                row = cur.fetchone()
                if row is None:
                    idx = 0
                    self._conn.execute(
                        "INSERT INTO model_rotation VALUES (?,?,?)",
                        (api_key, gateway_model, idx))
                else:
                    idx = (row[0] + 1) % total
                    self._conn.execute(
                        "UPDATE model_rotation SET last_model_index=? "
                        "WHERE api_key=? AND gateway_model=?",
                        (idx, api_key, gateway_model))
                self._conn.commit()
                return idx
        except sqlite3.Error:
            logger.exception("rotation db error; degrading to index 0")
            return 0

    async def next_index_async(self, api_key: str, gateway_model: str,
                               total: int) -> int:
        return await asyncio.to_thread(self.next_index, api_key, gateway_model, total)

    def close(self) -> None:
        with self._lock:
            self._conn.close()
