"""Token-usage ledger in SQLite with period aggregation.

Parity with the reference's ``TokensUsageDB``
(``llm_gateway_core/db/tokens_usage_db.py``): same logical schema
(timestamped rows of prompt/completion/total/reasoning/cached tokens, cost,
model, provider — ``tokens_usage_db.py:37-56``), strftime-bucketed
aggregation (``:222-304``), paginated latest-records (``:69-117``), count
(``:200-220``), retention cleanup (``:164-198``; dead code there, actually
wired here). Inserts never raise into the serving path (``:155-159``).

Extended with per-request serving metrics the reference cannot observe:
``ttft_ms`` (time to first token) and ``tokens_per_sec`` — the BASELINE
north-star metrics, visible in the stats UI.
"""
from __future__ import annotations

import asyncio
import logging
import sqlite3
import threading
import time
from dataclasses import dataclass, field
from pathlib import Path
from typing import Any

logger = logging.getLogger(__name__)

_PERIOD_FMT = {"hour": "%Y-%m-%d %H:00", "day": "%Y-%m-%d",
               "week": "%Y-%W", "month": "%Y-%m"}


@dataclass
class UsageRecord:
    model: str = ""
    provider: str = ""
    prompt_tokens: int = 0
    completion_tokens: int = 0
    total_tokens: int = 0
    reasoning_tokens: int = 0
    cached_tokens: int = 0
    cost: float = 0.0
    ttft_ms: float | None = None
    tokens_per_sec: float | None = None
    # SLO attribution (ISSUE 7): 1/0 when the request carried targets
    # (None = no SLO), and the violated phase (queued / prefill /
    # decode_contention / decode) when it missed them.
    slo_met: int | None = None
    slo_phase: str | None = None
    timestamp: str = field(default_factory=lambda: time.strftime("%Y-%m-%d %H:%M:%S"))


class UsageDB:
    def __init__(self, db_dir: Path | str = "db"):
        path = Path(db_dir)
        path.mkdir(parents=True, exist_ok=True)
        self._path = path / "tokens_usage.db"
        self._lock = threading.Lock()
        # One shared connection; every statement runs under the lock
        # (check_same_thread=False makes cross-thread use legal, not safe).
        self._conn = sqlite3.connect(self._path,
                                     check_same_thread=False)  # guarded-by: _lock
        self._conn.row_factory = sqlite3.Row
        with self._lock:
            self._conn.execute(
                """CREATE TABLE IF NOT EXISTS tokens_usage (
                       id INTEGER PRIMARY KEY AUTOINCREMENT,
                       timestamp TEXT NOT NULL,
                       prompt_tokens INTEGER DEFAULT 0,
                       completion_tokens INTEGER DEFAULT 0,
                       total_tokens INTEGER DEFAULT 0,
                       reasoning_tokens INTEGER DEFAULT 0,
                       cached_tokens INTEGER DEFAULT 0,
                       cost REAL DEFAULT 0,
                       model TEXT,
                       provider TEXT,
                       ttft_ms REAL,
                       tokens_per_sec REAL,
                       slo_met INTEGER,
                       slo_phase TEXT
                   )""")
            # Migrate pre-0.20 ledgers in place (ALTER ADD is cheap and
            # idempotent-by-check; rows predating the SLO plane stay NULL).
            cols = {r[1] for r in self._conn.execute(
                "PRAGMA table_info(tokens_usage)")}
            for col, decl in (("slo_met", "INTEGER"), ("slo_phase", "TEXT")):
                if col not in cols:
                    self._conn.execute(
                        f"ALTER TABLE tokens_usage ADD COLUMN {col} {decl}")
            self._conn.execute(
                "CREATE INDEX IF NOT EXISTS idx_tokens_usage_ts "
                "ON tokens_usage(timestamp)")
            self._conn.commit()

    # -- writes --------------------------------------------------------------
    def insert(self, rec: UsageRecord) -> None:
        """Insert one usage row; errors are logged, never raised (the ledger
        must not break serving — cf. tokens_usage_db.py:155-159)."""
        try:
            with self._lock:
                self._conn.execute(
                    """INSERT INTO tokens_usage
                       (timestamp, prompt_tokens, completion_tokens, total_tokens,
                        reasoning_tokens, cached_tokens, cost, model, provider,
                        ttft_ms, tokens_per_sec, slo_met, slo_phase)
                       VALUES (?,?,?,?,?,?,?,?,?,?,?,?,?)""",
                    (rec.timestamp, rec.prompt_tokens, rec.completion_tokens,
                     rec.total_tokens, rec.reasoning_tokens, rec.cached_tokens,
                     rec.cost, rec.model, rec.provider, rec.ttft_ms,
                     rec.tokens_per_sec, rec.slo_met, rec.slo_phase))
                self._conn.commit()
        except sqlite3.Error:
            logger.exception("usage insert failed (ignored)")

    async def insert_async(self, rec: UsageRecord) -> None:
        await asyncio.to_thread(self.insert, rec)

    def cleanup_old_records(self, days: int = 180) -> int:
        """Delete rows older than `days`; returns count removed."""
        try:
            with self._lock:
                # Rows are stamped in local time; compare in local time too.
                cur = self._conn.execute(
                    "DELETE FROM tokens_usage WHERE timestamp < "
                    "datetime('now', 'localtime', ?)", (f"-{int(days)} days",))
                self._conn.commit()
                return cur.rowcount
        except sqlite3.Error:
            logger.exception("usage cleanup failed (ignored)")
            return 0

    # -- reads ---------------------------------------------------------------
    def aggregated(self, period: str, start: str, end: str) -> list[dict[str, Any]]:
        """SUM per (period-bucket, model) between start/end timestamps.
        period ∈ {hour, day, week, month} (cf. tokens_usage_db.py:222-304)."""
        fmt = _PERIOD_FMT.get(period)
        if fmt is None:
            raise ValueError(f"unknown period {period!r}")
        with self._lock:
            cur = self._conn.execute(
                f"""SELECT strftime('{fmt}', timestamp) AS period, model,
                           SUM(prompt_tokens) AS prompt_tokens,
                           SUM(completion_tokens) AS completion_tokens,
                           SUM(total_tokens) AS total_tokens,
                           SUM(reasoning_tokens) AS reasoning_tokens,
                           SUM(cached_tokens) AS cached_tokens,
                           SUM(cost) AS cost,
                           COUNT(*) AS requests,
                           AVG(ttft_ms) AS avg_ttft_ms,
                           AVG(tokens_per_sec) AS avg_tokens_per_sec,
                           SUM(slo_met) AS slo_met_requests,
                           COUNT(slo_met) AS slo_requests
                    FROM tokens_usage
                    WHERE timestamp >= ? AND timestamp <= ?
                    GROUP BY period, model
                    ORDER BY period DESC, model""",
                (start, end))
            rows = [dict(r) for r in cur.fetchall()]
            # p50/p95 TTFT per bucket (BASELINE's latency target is a
            # PERCENTILE — a mean hides tail stalls). SQLite has no
            # percentile aggregate, so pull the raw column and fold in
            # Python; volumes are bounded by the 180-day retention sweep.
            cur = self._conn.execute(
                f"""SELECT strftime('{fmt}', timestamp) AS period, model,
                           ttft_ms
                    FROM tokens_usage
                    WHERE timestamp >= ? AND timestamp <= ?
                      AND ttft_ms IS NOT NULL""",
                (start, end))
            samples: dict[tuple[str, str], list[float]] = {}
            for period_b, model, ttft in cur.fetchall():
                samples.setdefault((period_b, model), []).append(float(ttft))
        def pct(vals: list[float], q: float) -> float:
            vals = sorted(vals)
            i = q * (len(vals) - 1)
            lo, hi = int(i), min(int(i) + 1, len(vals) - 1)
            return vals[lo] + (vals[hi] - vals[lo]) * (i - lo)
        for row in rows:
            vals = samples.get((row["period"], row["model"]))
            row["ttft_p50_ms"] = round(pct(vals, 0.50), 1) if vals else None
            row["ttft_p95_ms"] = round(pct(vals, 0.95), 1) if vals else None
        return rows

    def latest(self, limit: int = 25, offset: int = 0) -> list[dict[str, Any]]:
        with self._lock:
            cur = self._conn.execute(
                "SELECT * FROM tokens_usage ORDER BY id DESC LIMIT ? OFFSET ?",
                (limit, offset))
            return [dict(r) for r in cur.fetchall()]

    def total_count(self) -> int:
        with self._lock:
            cur = self._conn.execute("SELECT COUNT(*) FROM tokens_usage")
            return int(cur.fetchone()[0])

    async def aggregated_async(self, period: str, start: str, end: str):
        return await asyncio.to_thread(self.aggregated, period, start, end)

    async def latest_async(self, limit: int = 25, offset: int = 0):
        return await asyncio.to_thread(self.latest, limit, offset)

    async def total_count_async(self) -> int:
        return await asyncio.to_thread(self.total_count)

    def close(self) -> None:
        with self._lock:
            self._conn.close()
