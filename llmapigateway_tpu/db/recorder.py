"""Write-behind usage recording (ISSUE 14 / ROADMAP item 4).

The usage ledger used to be written synchronously from stream-end
executors — correct, but coupling every request's tail latency to an
SQLite fsync and leaving nothing between "the row was written" and "the
row was lost" when the process dies mid-write under incident load.

:class:`UsageRecorder` decouples the two: producers enqueue
:class:`~..db.usage.UsageRecord` rows into a bounded in-memory queue
(never blocking the serving path; overflow increments a drop counter
surfaced at ``gateway_usage_recorder_dropped_total``), and ONE
background flusher thread drains them into the ledger. The flusher
touches sqlite only — no JAX, no device handles — a hard rule learned
from the PR 8 cost-resolver revert (daemon threads holding JAX state
segfault at interpreter teardown).

Crash-safety contract: rows are flushed eagerly (the flusher sleeps
only when the queue is empty), ``flush()`` blocks until everything
enqueued so far is durable, and ``close()`` drains before returning —
so graceful drain / SIGTERM / engine crash recovery all persist the
partial usage of interrupted streams. It duck-types ``UsageDB.insert``
so :class:`~..server.usage_capture.UsageCollector` needs no changes.
"""
from __future__ import annotations

import logging
import queue
import threading
import time
from typing import Any

logger = logging.getLogger(__name__)


class UsageRecorder:
    """Bounded write-behind queue in front of a :class:`UsageDB`."""

    def __init__(self, usage_db: Any, maxsize: int = 1024):
        self._db = usage_db
        self._queue: queue.Queue = queue.Queue(maxsize=max(1, maxsize))
        self._closed = False
        # Counter invariant: enqueued == flushed + in-queue (drops never
        # enter the queue), so flush() can wait on plain ints (GIL-atomic
        # increments; readers tolerate momentary staleness).
        self._enqueued = 0
        self._flushed = 0
        self._dropped = 0
        self._thread = threading.Thread(target=self._flush_loop,
                                        daemon=True, name="usage-recorder")
        self._thread.start()

    # -- producer side (duck-types UsageDB.insert) --------------------------
    def insert(self, rec: Any) -> None:
        """Enqueue one usage row; NEVER blocks the serving path. A full
        queue drops the row and counts it — under incident load, losing
        a ledger row beats stalling a stream's finally-block."""
        if self._closed:
            # Late stragglers after shutdown go straight through: the
            # underlying DB insert is already never-raise.
            self._db.insert(rec)
            return
        try:
            self._queue.put_nowait(rec)
            self._enqueued += 1
        except queue.Full:
            self._dropped += 1

    # -- flusher ------------------------------------------------------------
    def _flush_loop(self) -> None:
        # sqlite only in here (see module docstring).
        while not self._closed or not self._queue.empty():
            try:
                rec = self._queue.get(timeout=0.05)
            except queue.Empty:
                continue
            try:
                self._db.insert(rec)    # UsageDB.insert never raises
            finally:
                self._flushed += 1

    def flush(self, timeout_s: float = 5.0) -> bool:
        """Block until every row enqueued before this call is durable
        (or the timeout passes). Returns True when fully drained."""
        target = self._enqueued
        deadline = time.monotonic() + timeout_s
        while self._flushed < target:
            if time.monotonic() > deadline:
                logger.warning("usage recorder flush timed out with "
                               "%d rows pending", target - self._flushed)
                return False
            time.sleep(0.002)
        return True

    def close(self, timeout_s: float = 5.0) -> None:
        """Drain the queue and stop the flusher. Idempotent."""
        if self._closed:
            return
        self.flush(timeout_s)
        self._closed = True
        self._thread.join(timeout=timeout_s)
        if self._thread.is_alive():
            logger.warning("usage recorder flusher did not exit cleanly")

    # -- reporting ----------------------------------------------------------
    def stats(self) -> dict[str, Any]:
        return {
            "usage_recorder_queued": self._queue.qsize(),
            "usage_recorder_capacity": self._queue.maxsize,
            "usage_recorder_enqueued_total": self._enqueued,
            "usage_recorder_flushed_total": self._flushed,
            "usage_recorder_dropped_total": self._dropped,
        }
