from .rotation import RotationDB
from .usage import UsageDB

__all__ = ["RotationDB", "UsageDB"]
