/* Config editor SPA logic. Counterpart of the reference's static/editor.js
   (CodeMirror json5 editor, tabs, load/save via /v1/config/*, pydantic-error
   rendering, agent-config downloads) — rebuilt dependency-free: a plain
   textarea with a line-number gutter and a small built-in JSON5 checker,
   since the zero-egress deployment cannot load CodeMirror from a CDN. */
"use strict";

/* ---------------- tiny JSON5 syntax checker (lint only) ----------------
   Tolerates: // and block comments, trailing commas, single-quoted strings,
   unquoted identifier keys, +/-/leading-dot numbers, Infinity/NaN.
   Returns null on success or {line, col, message} on the first error. */
function json5Check(text) {
  let i = 0;
  const n = text.length;
  function err(message) {
    const upto = text.slice(0, i);
    const line = upto.split("\n").length;
    const col = i - upto.lastIndexOf("\n");
    return { line, col, message };
  }
  function ws() {
    for (;;) {
      while (i < n && /[\s]/.test(text[i])) i++;
      if (text[i] === "/" && text[i + 1] === "/") {
        while (i < n && text[i] !== "\n") i++;
      } else if (text[i] === "/" && text[i + 1] === "*") {
        i += 2;
        while (i < n && !(text[i] === "*" && text[i + 1] === "/")) i++;
        if (i >= n) return "unterminated block comment";
        i += 2;
      } else {
        return null;
      }
    }
  }
  function string(quote) {
    i++; // opening quote
    while (i < n) {
      const c = text[i];
      if (c === "\\") { i += 2; continue; }
      if (c === quote) { i++; return null; }
      if (c === "\n") return "unterminated string (newline in string)";
      i++;
    }
    return "unterminated string";
  }
  function value() {
    const e = ws();
    if (e) return e;
    if (i >= n) return "unexpected end of input";
    const c = text[i];
    if (c === "{") return object();
    if (c === "[") return array();
    if (c === '"' || c === "'") {
      const s = string(c);
      return s ? err(s) : null;
    }
    const m = /^(?:[+-]?(?:Infinity|NaN|0x[0-9a-fA-F]+|(?:\d+\.?\d*|\.\d+)(?:[eE][+-]?\d+)?)|true|false|null)/
      .exec(text.slice(i));
    if (m) { i += m[0].length; return null; }
    return err(`unexpected character ${JSON.stringify(c)}`);
  }
  function object() {
    i++; // {
    for (;;) {
      let e = ws();
      if (e) return e;
      if (i >= n) return err("unterminated object");
      if (text[i] === "}") { i++; return null; }
      // key: quoted string or identifier
      if (text[i] === '"' || text[i] === "'") {
        const s = string(text[i]);
        if (s) return err(s);
      } else {
        const m = /^[$A-Za-z_][$\w]*/.exec(text.slice(i));
        if (!m) return err("expected object key");
        i += m[0].length;
      }
      e = ws();
      if (e) return e;
      if (text[i] !== ":") return err("expected ':' after object key");
      i++;
      e = value();
      if (e) return e;
      e = ws();
      if (e) return e;
      if (text[i] === ",") { i++; continue; }
      if (text[i] === "}") { i++; return null; }
      return err("expected ',' or '}' in object");
    }
  }
  function array() {
    i++; // [
    for (;;) {
      let e = ws();
      if (e) return e;
      if (i >= n) return err("unterminated array");
      if (text[i] === "]") { i++; return null; }
      e = value();
      if (e) return e;
      e = ws();
      if (e) return e;
      if (text[i] === ",") { i++; continue; }
      if (text[i] === "]") { i++; return null; }
      return err("expected ',' or ']' in array");
    }
  }
  let e = value();
  if (e) return typeof e === "string" ? err(e) : e;
  e = ws();
  if (e) return e;
  if (i < n) return err("trailing content after top-level value");
  return null;
}

/* ---------------- JSON5 syntax highlighter (overlay) ----------------
   Tokenizes the buffer into HTML spans rendered in a <pre> positioned
   behind the transparent-text textarea — caret/selection/undo stay native
   to the textarea while colors come from the overlay. One master regex,
   alternatives ordered: comments first (so strings inside comments don't
   tokenize), then strings (key vs value decided by a ':' lookahead),
   numbers, keywords, punctuation. */
const TOKEN_RE = new RegExp(
  [
    "(\\/\\/[^\\n]*|\\/\\*[\\s\\S]*?\\*\\/)",                 // 1 comment
    "(\"(?:\\\\.|[^\"\\\\\\n])*\"?|'(?:\\\\.|[^'\\\\\\n])*'?)", // 2 string
    "([+-]?(?:Infinity|NaN|0x[0-9a-fA-F]+|(?:\\d+\\.?\\d*|\\.\\d+)(?:[eE][+-]?\\d+)?))", // 3 number
    "\\b(true|false|null)\\b",                                  // 4 keyword
    "([{}\\[\\],:])",                                           // 5 punct
  ].join("|"), "g");

function escapeHtml(s) {
  return s.replace(/&/g, "&amp;").replace(/</g, "&lt;").replace(/>/g, "&gt;");
}

// Sticky (O(1), no buffer copy) "is the next non-space char a ':'" probe —
// decides string-token key-vs-value without slicing the document tail per
// token (which would make every keystroke's re-highlight O(n^2)).
const COLON_AHEAD = /\s*:/y;

function highlightJson5(text) {
  let out = "";
  let last = 0;
  TOKEN_RE.lastIndex = 0;
  for (let m; (m = TOKEN_RE.exec(text)); ) {
    out += escapeHtml(text.slice(last, m.index));
    last = m.index + m[0].length;
    let cls = "tok-punct";
    if (m[1] !== undefined) cls = "tok-comment";
    else if (m[2] !== undefined) {
      COLON_AHEAD.lastIndex = last;
      cls = COLON_AHEAD.test(text) ? "tok-key" : "tok-string";
    } else if (m[3] !== undefined) cls = "tok-number";
    else if (m[4] !== undefined) cls = "tok-keyword";
    out += `<span class="${cls}">${escapeHtml(m[0])}</span>`;
  }
  out += escapeHtml(text.slice(last));
  return out;
}

/* ---------------- helpers ---------------- */
const $ = (id) => document.getElementById(id);

function apiKey() { return $("api-key").value.trim(); }
function authHeaders() {
  const k = apiKey();
  return k ? { Authorization: "Bearer " + k } : {};
}

function setStatus(el, text, cls) {
  el.textContent = text;
  el.className = "status" + (cls ? " " + cls : "");
}

/* ---------------- theme + key persistence ---------------- */
const THEMES = ["light", "dark", "solarized", "midnight", "contrast"];
function applyTheme(name) {
  if (!THEMES.includes(name)) name = "light";
  document.body.dataset.theme = name;
  document.body.classList.toggle("dark",
    name === "dark" || name === "midnight");   // back-compat for page chrome
  $("theme-select").value = name;
  localStorage.setItem("gw-theme", name);
}
applyTheme(localStorage.getItem("gw-theme") || "light");
$("theme-select").addEventListener("change",
  (ev) => applyTheme(ev.target.value));
$("api-key").value = localStorage.getItem("gw-api-key") || "";
$("api-key").addEventListener("change", () => {
  localStorage.setItem("gw-api-key", apiKey());
});

/* ---------------- tabs ---------------- */
$("tabs").addEventListener("click", (ev) => {
  const btn = ev.target.closest("button[data-tab]");
  if (!btn) return;
  document.querySelectorAll("#tabs button").forEach(
    (b) => b.classList.toggle("active", b === btn));
  document.querySelectorAll(".panel").forEach(
    (p) => p.classList.toggle("active", p.id === "panel-" + btn.dataset.tab));
});

/* ---------------- editor panes ---------------- */
const ENDPOINTS = {
  rules: "/v1/config/models-rules",
  providers: "/v1/config/providers",
};
const original = { rules: "", providers: "" };
const errPos = { rules: null, providers: null };   // {line, col} | null
const lintTimers = {};

function syncGutter(which) {
  const ta = $("editor-" + which);
  const lines = ta.value.split("\n").length || 1;
  const gutter = $("gutter-" + which);
  const bad = errPos[which] ? errPos[which].line : 0;
  gutter.innerHTML = Array.from({ length: lines }, (_, k) =>
    k + 1 === bad ? `<span class="ln-err">${k + 1}</span>` : String(k + 1)
  ).join("\n");
  gutter.scrollTop = ta.scrollTop;
}

function render(which) {
  const ta = $("editor-" + which);
  // Trailing newline keeps the overlay's scrollHeight matching the
  // textarea's when the caret sits on a fresh last line.
  $("hl-" + which).innerHTML = highlightJson5(ta.value) + "\n";
}

function syncScroll(which) {
  const ta = $("editor-" + which);
  $("gutter-" + which).scrollTop = ta.scrollTop;
  const hl = $("hl-" + which);
  hl.scrollTop = ta.scrollTop;
  hl.scrollLeft = ta.scrollLeft;
}

/* Shared check-and-mark core: run the syntax checker, update errPos, the
   error box, and the gutter marker. Every buffer-mutating path goes
   through checkAndMark (directly or via the debounced liveLint). */
function checkAndMark(which) {
  const e = json5Check($("editor-" + which).value);
  errPos[which] = e ? { line: e.line, col: e.col } : null;
  showErrors(which,
    e ? [`line ${e.line}, col ${e.col}: ${e.message}`] : null);
  syncGutter(which);
  return e;
}

/* Lint-as-you-type: debounced — the explicit "Check syntax" button stays
   for a loud pass/fail status. */
function liveLint(which) {
  clearTimeout(lintTimers[which]);
  lintTimers[which] = setTimeout(() => checkAndMark(which), 250);
}

/* The one entry point after ANY buffer mutation: gutter, overlay, lint. */
function refresh(which, { immediate = false } = {}) {
  syncGutter(which);
  render(which);
  if (immediate) checkAndMark(which);
  else liveLint(which);
}

function showErrors(which, errors) {
  const box = $("errors-" + which);
  if (errors && errors.length) {
    box.textContent = errors.join("\n");
    box.classList.add("visible");
  } else {
    box.textContent = "";
    box.classList.remove("visible");
  }
}

async function loadFile(which) {
  const status = $("status-" + which);
  setStatus(status, "loading…");
  try {
    const resp = await fetch(ENDPOINTS[which], { headers: authHeaders() });
    if (!resp.ok) {
      const body = await resp.text();
      setStatus(status, `load failed (${resp.status}): ${body.slice(0, 200)}`, "err");
      return;
    }
    const text = await resp.text();
    original[which] = text;
    $("editor-" + which).value = text;
    // immediate: a stale error marker must not linger on fresh content
    // for the lint debounce interval.
    refresh(which, { immediate: true });
    setStatus(status, "loaded", "ok");
  } catch (e) {
    setStatus(status, "load failed: " + e, "err");
  }
}

function lint(which) {
  const e = checkAndMark(which);
  setStatus($("status-" + which),
            e ? "syntax error" : "syntax OK", e ? "err" : "ok");
  return !e;
}

async function saveFile(which) {
  if (!lint(which)) return;
  const status = $("status-" + which);
  setStatus(status, "saving…");
  try {
    const resp = await fetch(ENDPOINTS[which], {
      method: "POST",
      headers: { "Content-Type": "text/plain", ...authHeaders() },
      body: $("editor-" + which).value,
    });
    const body = await resp.json().catch(() => ({}));
    if (resp.ok) {
      original[which] = $("editor-" + which).value;
      showErrors(which, null);
      setStatus(status,
        `saved & reloaded (config v${body.config_version ?? "?"})`, "ok");
    } else if (resp.status === 400 && body.errors) {
      showErrors(which, body.errors);
      setStatus(status, body.detail || "validation failed", "err");
    } else if (resp.status === 401 || resp.status === 403) {
      setStatus(status, "auth failed — set the gateway API key (top right)", "err");
    } else {
      setStatus(status, `save failed (${resp.status}): ${body.detail || ""}`, "err");
    }
  } catch (e) {
    setStatus(status, "save failed: " + e, "err");
  }
}

for (const which of ["rules", "providers"]) {
  const ta = $("editor-" + which);
  ta.addEventListener("input", () => refresh(which));
  ta.addEventListener("scroll", () => syncScroll(which));
  ta.addEventListener("keydown", (ev) => {   // Tab inserts two spaces
    if (ev.key === "Tab") {
      ev.preventDefault();
      const s = ta.selectionStart;
      ta.setRangeText("  ", s, ta.selectionEnd, "end");
      refresh(which);
    }
  });
  // Click the error message → jump the caret to the reported position.
  $("errors-" + which).addEventListener("click", () => {
    const p = errPos[which];
    if (!p) return;
    const lines = ta.value.split("\n");
    let idx = 0;
    for (let l = 0; l < p.line - 1 && l < lines.length; l++) {
      idx += lines[l].length + 1;
    }
    idx += Math.max(0, p.col - 1);
    ta.focus();
    ta.setSelectionRange(idx, idx);
  });
  $("save-" + which).addEventListener("click", () => saveFile(which));
  $("lint-" + which).addEventListener("click", () => lint(which));
  $("revert-" + which).addEventListener("click", () => {
    ta.value = original[which];
    refresh(which, { immediate: true });
    setStatus($("status-" + which), "reverted", "ok");
  });
  loadFile(which);
}

window.addEventListener("beforeunload", (ev) => {
  if ($("editor-rules").value !== original.rules ||
      $("editor-providers").value !== original.providers) {
    ev.preventDefault();
  }
});

/* ---------------- agents integration ---------------- */
const AGENT_ENDPOINTS = {
  oc: { url: "/v1/models/AsOpenCodeFormat", file: "opencode.json" },
  gh: { url: "/v1/models/AsGitHubCopilotFormat", file: "chatLanguageModels.json" },
};

async function fetchAgentConfig(kind) {
  const include = $(kind + "-fallback").checked ? "true" : "false";
  const { url } = AGENT_ENDPOINTS[kind];
  const resp = await fetch(`${url}?includefallbackmodels=${include}`,
                           { headers: authHeaders() });
  if (!resp.ok) throw new Error(`HTTP ${resp.status}`);
  return await resp.json();
}

function download(filename, data) {
  const blob = new Blob([JSON.stringify(data, null, 2)],
                        { type: "application/json" });
  const a = document.createElement("a");
  a.href = URL.createObjectURL(blob);
  a.download = filename;
  a.click();
  URL.revokeObjectURL(a.href);
}

for (const kind of ["oc", "gh"]) {
  $(kind + "-preview").addEventListener("click", async () => {
    const status = $("status-agents");
    try {
      const data = await fetchAgentConfig(kind);
      const pre = $("agents-preview");
      pre.textContent = JSON.stringify(data, null, 2);
      pre.style.display = "block";
      setStatus(status, "", "");
    } catch (e) {
      setStatus(status, "fetch failed: " + e, "err");
    }
  });
  $(kind + "-download").addEventListener("click", async () => {
    const status = $("status-agents");
    try {
      download(AGENT_ENDPOINTS[kind].file, await fetchAgentConfig(kind));
      setStatus(status, "downloaded " + AGENT_ENDPOINTS[kind].file, "ok");
    } catch (e) {
      setStatus(status, "download failed: " + e, "err");
    }
  });
}
