/* Usage-stats SPA logic. Counterpart of the reference's static/usage-stats.js:
   period aggregate tables with per-bucket grouping and the derived Cost/Million
   column (cost / total_tokens * 1e6 — usage-stats.js:80-85 in the reference),
   paginated raw-records tab (25/page), dark mode — plus the TPU serving
   columns (p50/p95 TTFT, avg tok/s) this framework's usage schema records. */
"use strict";

const $ = (id) => document.getElementById(id);
const PAGE_SIZE = 25;

function apiKey() { return $("api-key").value.trim(); }
function authHeaders() {
  const k = apiKey();
  return k ? { Authorization: "Bearer " + k } : {};
}

/* theme + key persistence (shared localStorage keys with the editor).
   gw-theme carries the editor's 5 theme names; this page only has
   light/dark chrome, so map dark-family themes to dark and NEVER write
   the key back except from an explicit toggle here — a plain page load
   must not clobber a richer saved editor theme. */
const DARK_THEMES = ["dark", "midnight", "contrast"];
if (DARK_THEMES.includes(localStorage.getItem("gw-theme"))) {
  document.body.classList.add("dark");
}
$("theme-toggle").addEventListener("click", () => {
  document.body.classList.toggle("dark");
  localStorage.setItem(
    "gw-theme", document.body.classList.contains("dark") ? "dark" : "light");
});
$("api-key").value = localStorage.getItem("gw-api-key") || "";
$("api-key").addEventListener("change", () => {
  localStorage.setItem("gw-api-key", apiKey());
  loadAgg();
  loadRaw();
});

/* tabs */
$("tabs").addEventListener("click", (ev) => {
  const btn = ev.target.closest("button[data-tab]");
  if (!btn) return;
  document.querySelectorAll("#tabs button").forEach(
    (b) => b.classList.toggle("active", b === btn));
  document.querySelectorAll(".panel").forEach(
    (p) => p.classList.toggle("active", p.id === "panel-" + btn.dataset.tab));
});

/* formatting */
const fmtInt = (v) => (v == null ? "—" : Number(v).toLocaleString("en-US"));
const fmtCost = (v) => (v == null ? "—" : Number(v).toFixed(4));
const fmt1 = (v) => (v == null ? "—" : Number(v).toFixed(1));
function costPerMillion(cost, total) {
  if (!cost || !total) return "—";
  return (cost / total * 1e6).toFixed(3);
}
function td(text, cls) {
  const el = document.createElement("td");
  el.textContent = text;
  if (cls) el.className = cls;
  return el;
}

/* ---------------- aggregated tab ---------------- */
let currentPeriod = "day";

const BUCKET_LABEL = {
  hour: (b) => `${b}:00`,
  day: (b) => b,
  week: (b) => `week ${b}`,
  month: (b) => b,
};

async function loadAgg() {
  const status = $("status-agg");
  status.textContent = "loading…";
  status.className = "status";
  try {
    const resp = await fetch("/v1/api/usage-stats/" + currentPeriod,
                             { headers: authHeaders() });
    if (!resp.ok) {
      status.textContent = resp.status === 401 || resp.status === 403
        ? "auth failed — set the gateway API key (top right)"
        : `load failed (${resp.status})`;
      status.className = "status err";
      return;
    }
    const { data } = await resp.json();
    renderAgg(data || []);
    status.textContent = `${data.length} row(s), period = ${currentPeriod}`;
  } catch (e) {
    status.textContent = "load failed: " + e;
    status.className = "status err";
  }
}

function renderAgg(rows) {
  const body = $("agg-body");
  body.textContent = "";
  if (!rows.length) {
    const tr = document.createElement("tr");
    const cell = td("no usage recorded in this window", "empty");
    cell.colSpan = 12;
    tr.appendChild(cell);
    body.appendChild(tr);
    return;
  }
  /* rows arrive newest-bucket first, grouped (bucket, model); render a
     bucket header row, then per-model rows, then a bucket total row. */
  const buckets = new Map();
  for (const r of rows) {
    if (!buckets.has(r.period)) buckets.set(r.period, []);
    buckets.get(r.period).push(r);
  }
  for (const [bucket, group] of buckets) {
    const hdr = document.createElement("tr");
    hdr.className = "bucket";
    const cell = td(BUCKET_LABEL[currentPeriod](bucket));
    cell.colSpan = 12;
    hdr.appendChild(cell);
    body.appendChild(hdr);

    const tot = { requests: 0, prompt: 0, completion: 0, reasoning: 0,
                  cached: 0, total: 0, cost: 0 };
    for (const r of group) {
      const tr = document.createElement("tr");
      tr.appendChild(td(r.model || "—", "model"));
      tr.appendChild(td(fmtInt(r.requests)));
      tr.appendChild(td(fmtInt(r.prompt_tokens)));
      tr.appendChild(td(fmtInt(r.completion_tokens)));
      tr.appendChild(td(fmtInt(r.reasoning_tokens)));
      tr.appendChild(td(fmtInt(r.cached_tokens)));
      tr.appendChild(td(fmtInt(r.total_tokens)));
      tr.appendChild(td(fmtCost(r.cost)));
      tr.appendChild(td(costPerMillion(r.cost, r.total_tokens)));
      tr.appendChild(td(fmt1(r.ttft_p50_ms)));
      tr.appendChild(td(fmt1(r.ttft_p95_ms)));
      tr.appendChild(td(fmt1(r.avg_tokens_per_sec)));
      body.appendChild(tr);
      tot.requests += r.requests || 0;
      tot.prompt += r.prompt_tokens || 0;
      tot.completion += r.completion_tokens || 0;
      tot.reasoning += r.reasoning_tokens || 0;
      tot.cached += r.cached_tokens || 0;
      tot.total += r.total_tokens || 0;
      tot.cost += r.cost || 0;
    }
    if (group.length > 1) {
      const tr = document.createElement("tr");
      tr.className = "total";
      tr.appendChild(td("total"));
      tr.appendChild(td(fmtInt(tot.requests)));
      tr.appendChild(td(fmtInt(tot.prompt)));
      tr.appendChild(td(fmtInt(tot.completion)));
      tr.appendChild(td(fmtInt(tot.reasoning)));
      tr.appendChild(td(fmtInt(tot.cached)));
      tr.appendChild(td(fmtInt(tot.total)));
      tr.appendChild(td(fmtCost(tot.cost)));
      tr.appendChild(td(costPerMillion(tot.cost, tot.total)));
      tr.appendChild(td("—"));
      tr.appendChild(td("—"));
      tr.appendChild(td("—"));
      body.appendChild(tr);
    }
  }
}

$("periods").addEventListener("click", (ev) => {
  const btn = ev.target.closest("button[data-period]");
  if (!btn) return;
  currentPeriod = btn.dataset.period;
  document.querySelectorAll("#periods button").forEach(
    (b) => b.classList.toggle("active", b === btn));
  loadAgg();
});

/* ---------------- raw records tab ---------------- */
let rawOffset = 0;
let rawTotal = 0;

async function loadRaw() {
  const status = $("status-raw");
  status.textContent = "loading…";
  status.className = "status";
  try {
    const resp = await fetch(
      `/v1/api/usage-records?limit=${PAGE_SIZE}&offset=${rawOffset}`,
      { headers: authHeaders() });
    if (!resp.ok) {
      status.textContent = resp.status === 401 || resp.status === 403
        ? "auth failed — set the gateway API key (top right)"
        : `load failed (${resp.status})`;
      status.className = "status err";
      return;
    }
    const { records, total } = await resp.json();
    rawTotal = total;
    renderRaw(records || []);
    const page = Math.floor(rawOffset / PAGE_SIZE) + 1;
    const pages = Math.max(1, Math.ceil(total / PAGE_SIZE));
    $("raw-page").textContent = `page ${page} / ${pages} (${total} records)`;
    $("raw-prev").disabled = rawOffset === 0;
    $("raw-next").disabled = rawOffset + PAGE_SIZE >= total;
    status.textContent = "";
  } catch (e) {
    status.textContent = "load failed: " + e;
    status.className = "status err";
  }
}

function renderRaw(records) {
  const body = $("raw-body");
  body.textContent = "";
  if (!records.length) {
    const tr = document.createElement("tr");
    const cell = td("no records", "empty");
    cell.colSpan = 11;
    tr.appendChild(cell);
    body.appendChild(tr);
    return;
  }
  for (const r of records) {
    const tr = document.createElement("tr");
    tr.appendChild(td(r.timestamp || "—"));
    tr.appendChild(td(r.provider || "—", "model"));
    tr.appendChild(td(r.model || "—", "model"));
    tr.appendChild(td(fmtInt(r.prompt_tokens)));
    tr.appendChild(td(fmtInt(r.completion_tokens)));
    tr.appendChild(td(fmtInt(r.reasoning_tokens)));
    tr.appendChild(td(fmtInt(r.cached_tokens)));
    tr.appendChild(td(fmtInt(r.total_tokens)));
    tr.appendChild(td(fmtCost(r.cost)));
    tr.appendChild(td(fmt1(r.ttft_ms)));
    tr.appendChild(td(fmt1(r.tokens_per_sec)));
    body.appendChild(tr);
  }
}

$("raw-prev").addEventListener("click", () => {
  rawOffset = Math.max(0, rawOffset - PAGE_SIZE);
  loadRaw();
});
$("raw-next").addEventListener("click", () => {
  if (rawOffset + PAGE_SIZE < rawTotal) {
    rawOffset += PAGE_SIZE;
    loadRaw();
  }
});

loadAgg();
loadRaw();
