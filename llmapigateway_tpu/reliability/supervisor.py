"""Engine lifecycle supervision: state machine, watchdog, restart backoff.

The inference engine used to be a process-lifetime assumption — a dead
scheduler loop or an XLA runtime error stranded every queued request
with no recovery path (ISSUE 14). This module makes the engine a
*supervised* component:

* an explicit lifecycle state machine (``starting → serving → draining
  → restarting → failed``) whose transitions happen ONLY through
  :meth:`EngineSupervisor.transition` — generalizing the ad-hoc
  ``_work_event`` rebinding fix from ISSUE 7 into a single place where
  "what state is the engine in" is answerable and enforceable (the
  ``lifecycle-discipline`` graftlint rule pins direct ``_lc_state``
  writes to this file);
* a heartbeat the scheduler loop stamps each step (piggybacked on the
  flight-ring sequence number, so the heartbeat is free when the ring
  is already recording) plus a watchdog deadline that distinguishes
  "idle" from "silently stalled";
* typed failure classification (:class:`EngineFailure`) separating
  transient device/runtime errors — worth a supervised restart — from
  fatal config/programming errors that restarting would just loop on;
* bounded exponential restart backoff, and drain bookkeeping for
  administrative restarts.

The supervisor holds NO engine resources itself; the engine calls in.
All mutable fields are scheduler-loop state, same contract as the
flight recorder (enforced by the sanitizer's GuardTracker — this class
is on the instrumented list).
"""
from __future__ import annotations

import time
from typing import Any, Callable

__all__ = ["EngineFailure", "EngineSupervisor", "LIFECYCLE_STATES",
           "STATE_CODES"]

# Lifecycle states, in rough severity order. STATE_CODES maps them onto
# a [0, 1] gauge (``gateway_engine_supervisor_state_ratio``) the same
# way breaker states map onto {0, 0.5, 1}: 0 = healthy/serving,
# 1 = failed, intermediates = degraded.
LIFECYCLE_STATES = ("starting", "serving", "draining", "restarting",
                    "failed", "stopped")
STATE_CODES = {"serving": 0.0, "starting": 0.25, "draining": 0.5,
               "restarting": 0.75, "stopped": 0.9, "failed": 1.0}

# Legal transitions. "stopped" is reachable from anywhere (stop() is
# always allowed); "failed" likewise (a fatal fault can strike in any
# state). Everything else must follow the lifecycle.
_TRANSITIONS: dict[str, tuple[str, ...]] = {
    "starting": ("serving", "failed", "stopped"),
    "serving": ("draining", "restarting", "failed", "stopped"),
    "draining": ("serving", "restarting", "failed", "stopped"),
    "restarting": ("serving", "failed", "stopped"),
    "failed": ("stopped",),
    "stopped": ("starting", "serving"),
}

# Exception-text markers that mean "the device/runtime hiccupped" — the
# restartable class. RESOURCE_EXHAUSTED is XLA's HBM-OOM status;
# the rest are XLA/PJRT runtime failure shapes seen in practice.
_TRANSIENT_MARKERS = ("RESOURCE_EXHAUSTED", "INTERNAL", "UNAVAILABLE",
                     "DEADLINE_EXCEEDED", "ABORTED", "device", "xla",
                     "pjrt")


class EngineFailure(Exception):
    """A classified step-loop failure.

    ``kind`` is one of:

    * ``transient`` — device/runtime error (XLA internal, HBM OOM,
      injected chaos fault): supervised restart is worth attempting;
    * ``stall`` — the watchdog declared the loop dead (heartbeat went
      stale while work was pending): restart, same as transient;
    * ``fatal`` — config/programming error (ValueError, TypeError,
      assertion): restarting would loop on the same bug, so the engine
      parks in ``failed`` and traffic stays on the fallback chain.
    """

    def __init__(self, message: str, *, kind: str = "transient",
                 cause: BaseException | None = None):
        super().__init__(message)
        self.kind = kind
        self.cause = cause

    @classmethod
    def classify(cls, exc: BaseException) -> "EngineFailure":
        """Wrap an arbitrary step-loop exception with a failure kind."""
        if isinstance(exc, EngineFailure):
            return exc
        msg = f"{type(exc).__name__}: {exc}"
        # Programming/config errors restart into the same error; park.
        if isinstance(exc, (ValueError, TypeError, KeyError,
                            AttributeError, AssertionError)):
            return cls(msg, kind="fatal", cause=exc)
        low = msg.lower()
        if any(m.lower() in low for m in _TRANSIENT_MARKERS):
            return cls(msg, kind="transient", cause=exc)
        # Unknown RuntimeError-ish failures default to transient: a
        # restart that fails again escalates through the backoff cap,
        # so optimism here is bounded, not unbounded.
        return cls(msg, kind="transient", cause=exc)


class EngineSupervisor:
    """Lifecycle + health bookkeeping for one engine.

    The engine owns the scheduler loop; the supervisor owns the *story*
    of that loop — current state, heartbeat age, restart budget, drain
    deadline. ``clock`` is injectable for fake-clock tests.
    """

    def __init__(self, *, watchdog_ms: float = 0.0, max_restarts: int = 3,
                 backoff_ms: float = 50.0, backoff_max_ms: float = 5000.0,
                 drain_deadline_ms: float = 10000.0,
                 clock: Callable[[], float] = time.monotonic,
                 on_transition: Callable[[str, str, str], None] | None = None):
        self._clock = clock
        self.watchdog_ms = watchdog_ms
        self.max_restarts = max_restarts
        self.backoff_ms = backoff_ms
        self.backoff_max_ms = backoff_max_ms
        self.drain_deadline_ms = drain_deadline_ms
        self._on_transition = on_transition
        self._lc_state = "starting"          # guarded-by: loop
        self._restarts = 0                   # guarded-by: loop
        self._last_failure_kind = ""         # guarded-by: loop
        self._last_failure_msg = ""          # guarded-by: loop
        self._last_heartbeat = self._clock() # guarded-by: loop
        self._heartbeat_seq = 0              # guarded-by: loop
        self._drain_started: float | None = None  # guarded-by: loop
        self._history: list[tuple[float, str, str, str]] = []  # guarded-by: loop

    # -- state machine ------------------------------------------------------
    @property
    def state(self) -> str:
        return self._lc_state

    def transition(self, to: str, reason: str = "") -> None:
        """The ONLY legal way to change lifecycle state (graftlint:
        lifecycle-discipline). Raises on an illegal edge so a buggy
        caller fails loudly instead of corrupting the story."""
        if to not in LIFECYCLE_STATES:
            raise ValueError(f"unknown lifecycle state {to!r}")
        frm = self._lc_state
        if to == frm:
            return                      # idempotent (double stop() etc.)
        if to not in _TRANSITIONS[frm]:
            raise ValueError(
                f"illegal lifecycle transition {frm!r} -> {to!r} ({reason})")
        self._lc_state = to
        if to == "draining":
            self._drain_started = self._clock()
        elif frm == "draining":
            self._drain_started = None
        # Bounded transition history: enough to reconstruct an incident
        # from stats() without growing unboundedly across restarts.
        self._history.append((self._clock(), frm, to, reason))
        del self._history[:-32]
        if self._on_transition is not None:
            self._on_transition(frm, to, reason)

    def is_accepting(self) -> bool:
        """May submit() admit new work? (starting is accepting: submit
        races engine start-up and the queue absorbs the gap.)"""
        return self._lc_state in ("starting", "serving", "stopped")

    # -- heartbeat / watchdog ----------------------------------------------
    def heartbeat(self, seq: int = 0) -> None:
        """Stamped by the scheduler loop each step; ``seq`` is the
        flight-ring sequence so stats can expose 'last step = ring
        record N' for free."""
        self._last_heartbeat = self._clock()
        self._heartbeat_seq = seq

    def heartbeat_age_s(self) -> float:
        return max(0.0, self._clock() - self._last_heartbeat)

    def is_stalled(self, busy: bool) -> bool:
        """Watchdog predicate: stale heartbeat counts only while the
        engine *should* be stepping (``busy``) — an idle engine parks
        on its work event legitimately."""
        if self.watchdog_ms <= 0 or not busy:
            return False
        return self.heartbeat_age_s() * 1000.0 > self.watchdog_ms

    # -- restart budget -----------------------------------------------------
    def note_failure(self, failure: EngineFailure) -> None:
        self._last_failure_kind = failure.kind
        self._last_failure_msg = str(failure)[:500]

    def can_restart(self) -> bool:
        return self._restarts < self.max_restarts

    def backoff_s(self) -> float:
        """Bounded exponential backoff for the NEXT restart attempt."""
        ms = min(self.backoff_max_ms,
                 self.backoff_ms * (2.0 ** self._restarts))
        return ms / 1000.0

    def note_restart(self) -> None:
        self._restarts += 1

    def reset_restarts(self) -> None:
        """A healthy serving stretch re-earns the full restart budget
        (callers invoke this after sustained successful stepping)."""
        self._restarts = 0

    # -- drain --------------------------------------------------------------
    def drain_elapsed_s(self) -> float:
        if self._drain_started is None:
            return 0.0
        return max(0.0, self._clock() - self._drain_started)

    def drain_expired(self, deadline_s: float | None = None) -> bool:
        if self._drain_started is None:
            return False
        limit = self.drain_deadline_ms / 1000.0 \
            if deadline_s is None else deadline_s
        return self.drain_elapsed_s() > limit

    # -- reporting ----------------------------------------------------------
    def state_code(self) -> float:
        return STATE_CODES.get(self._lc_state, 1.0)

    def stats(self) -> dict[str, Any]:
        return {
            "supervisor_state": self._lc_state,
            "supervisor_state_code": self.state_code(),
            "supervisor_restarts_total": self._restarts,
            "supervisor_max_restarts": self.max_restarts,
            "supervisor_last_failure_kind": self._last_failure_kind,
            "supervisor_last_failure": self._last_failure_msg,
            "supervisor_heartbeat_age_seconds": round(self.heartbeat_age_s(), 3),
            "supervisor_heartbeat_seq": self._heartbeat_seq,
            "supervisor_backoff_seconds": self.backoff_s(),
            "supervisor_watchdog_ms": self.watchdog_ms,
            "supervisor_drain_elapsed_seconds": round(self.drain_elapsed_s(), 3),
            "supervisor_transitions": [
                {"t": round(t, 3), "from": f, "to": to, "reason": r}
                for (t, f, to, r) in self._history[-8:]],
        }
