"""Reliability layer: deadline budgets, per-provider circuit breakers,
overload shedding (ISSUE 3).

The paper's fault-tolerance story — `local_tpu` as "just another entry in
providers.json" — only works if a dead or drowning target costs the chain
nothing: a request must carry an end-to-end time budget instead of waiting
out `retry_count x retry_delay x 300 s` per target, a provider that keeps
failing must be skipped *before* its timeout is paid (DistServe's framing:
goodput is requests that finish inside their SLO, PAPERS.md), and overload
must surface as backpressure the client can act on (429 + Retry-After)
rather than a generic 503.

Three small, clock-injectable pieces:

* :class:`~.deadline.Deadline` — a monotonic per-request budget carried
  from the HTTP layer through routing into provider attempts, where it
  caps httpx timeouts, retry sleeps, and engine first-token waits.
* :class:`~.breaker.CircuitBreaker` / :class:`~.breaker.BreakerRegistry` —
  sliding-window failure-rate tracking per provider with
  closed/open/half-open states; the router skips open breakers so a dead
  upstream adds ~0 latency once detected.
* failure classification (:func:`~.breaker.counts_as_breaker_failure`) —
  which provider errors indicate an unhealthy upstream (network errors,
  timeouts, 5xx, 429, engine overload) vs. a healthy upstream rejecting a
  bad request (other 4xx).
"""
from .breaker import (
    BreakerRegistry,
    CircuitBreaker,
    counts_as_breaker_failure,
)
from .deadline import Deadline, budget_ms_from_request

__all__ = [
    "BreakerRegistry",
    "CircuitBreaker",
    "Deadline",
    "budget_ms_from_request",
    "counts_as_breaker_failure",
]
