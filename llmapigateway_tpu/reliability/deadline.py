"""Per-request deadline budgets.

One ``Deadline`` is created per chat request (server/chat.py → routing) and
flows through every layer that can wait: the router clamps retry sleeps and
remaining attempts against it, the remote provider caps its httpx timeouts
with it, and the local provider bounds its first-token wait / decode drain
with it (cancelling the engine slot on expiry). Exhaustion maps to HTTP 504
with the partial-attempt log.

The clock is injectable so breaker/deadline unit tests run with zero real
sleeps (tier-1-fast requirement, ISSUE 3 satellite).
"""
from __future__ import annotations

import time
from typing import Any, Callable, Mapping

# Per-request budgets above this are treated as "no budget": the transport
# default (300 s total per attempt) is already the effective ceiling.
MAX_BUDGET_MS = 3_600_000.0

TIMEOUT_HEADER = "x-request-timeout-ms"
TIMEOUT_BODY_FIELD = "timeout_ms"


class Deadline:
    """A monotonic time budget for one request.

    ``remaining()`` never goes below zero from the caller's point of view —
    use :meth:`expired` for the terminal check and :meth:`clamp` to bound a
    wait (sleep, httpx timeout, first-token wait) by what's left.
    """

    __slots__ = ("budget_s", "_t0", "_clock")

    def __init__(self, budget_s: float,
                 clock: Callable[[], float] = time.monotonic):
        self.budget_s = float(budget_s)
        self._clock = clock
        self._t0 = clock()

    def elapsed(self) -> float:
        return self._clock() - self._t0

    def remaining(self) -> float:
        return max(0.0, self.budget_s - self.elapsed())

    def expired(self) -> bool:
        return self.elapsed() >= self.budget_s

    def clamp(self, seconds: float) -> float:
        """Bound a wait by the remaining budget (never negative)."""
        return max(0.0, min(float(seconds), self.remaining()))

    def __repr__(self) -> str:  # diagnostic only
        return (f"Deadline(budget={self.budget_s * 1000:.0f}ms, "
                f"remaining={self.remaining() * 1000:.0f}ms)")


def budget_ms_from_request(headers: Mapping[str, str],
                           payload: dict[str, Any]) -> float | None:
    """Extract the client-requested budget in milliseconds, if any.

    Sources, highest precedence first: the ``x-request-timeout-ms`` header,
    then a ``timeout_ms`` body field. The body field is **popped** from the
    payload so it is never forwarded to an upstream that would reject an
    unknown parameter. Invalid or non-positive values are ignored (None);
    oversized values are treated as "no budget".
    """
    raw: Any = headers.get(TIMEOUT_HEADER)
    if raw is None:
        raw = payload.pop(TIMEOUT_BODY_FIELD, None)
    else:
        payload.pop(TIMEOUT_BODY_FIELD, None)
    if raw is None:
        return None
    try:
        ms = float(raw)
    except (TypeError, ValueError):
        return None
    if ms <= 0 or ms > MAX_BUDGET_MS:
        return None
    return ms
