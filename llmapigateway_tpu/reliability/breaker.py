"""Per-provider circuit breakers.

A dead upstream must stop costing the chain its full
``retry_count x retry_delay x timeout`` on every request. Each provider
gets a breaker with the classic three states:

* **closed** — requests flow; outcomes are recorded into a sliding window.
  When the window holds at least ``min_requests`` samples and the failure
  rate reaches ``failure_threshold``, the breaker opens.
* **open** — the router skips this provider instantly (the chain falls
  through with ~0 added latency). After ``cooldown_s`` the next
  ``allow()`` transitions to half-open.
* **half-open** — exactly ONE probe request is let through. Success closes
  the breaker (window reset); failure re-opens it for another cooldown.

State transitions are logged and exported via ``snapshot()`` for
``GET /v1/api/health/providers``. Everything is event-loop-confined (the
router is the only caller), so no locking; the clock is injectable so the
chaos tests drive open→half-open→closed without real sleeps.
"""
from __future__ import annotations

import logging
import time
from collections import deque
from typing import TYPE_CHECKING, Any, Callable

if TYPE_CHECKING:                      # import cycle guard: schemas only for types
    from ..config.loader import ConfigLoader
    from ..config.schemas import BreakerSettings

logger = logging.getLogger(__name__)

CLOSED, OPEN, HALF_OPEN = "closed", "open", "half_open"


def counts_as_breaker_failure(error: Any) -> bool:
    """Does a ``CompletionError`` indicate an *unhealthy provider*?

    Network errors and timeouts (no status), 5xx, upstream 429, and engine
    overload all do. Other 4xx mean the provider is alive and rejecting
    this particular request — recording those as failures would let one
    misbehaving client open the breaker for everyone.
    """
    if error is None:
        return False
    if getattr(error, "kind", "") in ("overload", "timeout"):
        return True
    status = getattr(error, "status", None)
    if status is None:
        return True                    # network-level failure
    return status >= 500 or status == 429


class CircuitBreaker:
    """Sliding-window failure-rate breaker for one provider."""

    def __init__(self, name: str, cfg: "BreakerSettings",
                 clock: Callable[[], float] = time.monotonic):
        self.name = name
        self.cfg = cfg
        self._clock = clock
        self._events: deque[tuple[float, bool]] = deque()   # (t, ok)
        self._state = CLOSED
        self._opened_at = 0.0
        self._probe_inflight = False
        self.opens = 0                 # lifetime open transitions
        self.last_transition: str | None = None

    # -- gate ---------------------------------------------------------------
    def allow(self) -> bool:
        """May a request be sent to this provider right now?

        In half-open state a True return RESERVES the single probe slot;
        the caller must follow up with record_success/record_failure.
        """
        if not self.cfg.enabled:
            return True
        if self._state == CLOSED:
            return True
        now = self._clock()
        if self._state == OPEN:
            if now - self._opened_at < self.cfg.cooldown_s:
                return False
            self._transition(HALF_OPEN, "cooldown elapsed; probing")
            self._probe_inflight = True
            return True
        # half-open: one probe at a time
        if self._probe_inflight:
            return False
        self._probe_inflight = True
        return True

    def release_probe(self) -> None:
        """Un-reserve a half-open probe that was never actually sent (the
        router reserved it via allow() but bailed — e.g. the request's
        deadline expired first). Without this the reservation would leak
        and the breaker would refuse traffic forever."""
        if self._state == HALF_OPEN:
            self._probe_inflight = False

    # -- outcome recording ---------------------------------------------------
    def record_success(self) -> None:
        if self._state == HALF_OPEN:
            self._probe_inflight = False
            self._events.clear()
            self._transition(CLOSED, "half-open probe succeeded")
            return
        self._push(ok=True)

    def record_failure(self) -> None:
        if self._state == HALF_OPEN:
            self._probe_inflight = False
            self._open("half-open probe failed")
            return
        self._push(ok=False)
        if (self._state == CLOSED and self.cfg.enabled
                and self._window_trips()):
            self._open(
                f"failure rate over last {self.cfg.window_s:g}s reached "
                f"{self.failure_rate():.0%}")

    # -- introspection -------------------------------------------------------
    @property
    def state(self) -> str:
        # An open breaker whose cooldown has lapsed is *reported* as open
        # until the next allow() actually starts the probe.
        return self._state

    def cooldown_remaining(self) -> float:
        if self._state != OPEN:
            return 0.0
        return max(0.0, self.cfg.cooldown_s
                   - (self._clock() - self._opened_at))

    def failure_rate(self) -> float:
        self._prune()
        if not self._events:
            return 0.0
        bad = sum(1 for _, ok in self._events if not ok)
        return bad / len(self._events)

    def snapshot(self) -> dict[str, Any]:
        self._prune()
        return {
            "state": self._state,
            # Numeric twin of `state` for the metrics plane's
            # gateway_provider_breaker_open_ratio gauge (ISSUE 4).
            "state_code": {CLOSED: 0.0, HALF_OPEN: 0.5, OPEN: 1.0}[self._state],
            "failure_rate": round(self.failure_rate(), 3),
            "window_requests": len(self._events),
            "cooldown_remaining_s": round(self.cooldown_remaining(), 2),
            "opens": self.opens,
            "last_transition": self.last_transition,
            "enabled": self.cfg.enabled,
        }

    # -- internals -----------------------------------------------------------
    def _push(self, ok: bool) -> None:
        self._events.append((self._clock(), ok))
        self._prune()

    def _prune(self) -> None:
        horizon = self._clock() - self.cfg.window_s
        while self._events and self._events[0][0] < horizon:
            self._events.popleft()

    def _window_trips(self) -> bool:
        self._prune()
        if len(self._events) < self.cfg.min_requests:
            return False
        return self.failure_rate() >= self.cfg.failure_threshold

    def _open(self, why: str) -> None:
        self._opened_at = self._clock()
        self.opens += 1
        self._transition(OPEN, why)

    def _transition(self, new_state: str, why: str) -> None:
        old, self._state = self._state, new_state
        self.last_transition = f"{old}->{new_state}: {why}"
        logger.warning("breaker[%s] %s -> %s (%s)",
                       self.name, old, new_state, why)


class BreakerRegistry:
    """One breaker per provider name, config sourced from the live
    providers.json (hot-reload aware: a changed breaker config rebuilds
    that provider's breaker; unchanged providers keep their window)."""

    def __init__(self, loader: "ConfigLoader | None" = None,
                 clock: Callable[[], float] = time.monotonic):
        self._loader = loader
        self._clock = clock
        # name -> (config fingerprint, breaker)   — event-loop confined
        self._breakers: dict[str, tuple[str, CircuitBreaker]] = {}

    def _settings_for(self, name: str) -> "BreakerSettings":
        from ..config.schemas import BreakerSettings
        if self._loader is not None:
            details = self._loader.providers.get(name)
            if details is not None and details.breaker is not None:
                return details.breaker
        return BreakerSettings()

    def get(self, name: str) -> CircuitBreaker:
        cfg = self._settings_for(name)
        fingerprint = cfg.model_dump_json()
        cached = self._breakers.get(name)
        if cached is not None and cached[0] == fingerprint:
            return cached[1]
        breaker = CircuitBreaker(name, cfg, clock=self._clock)
        self._breakers[name] = (fingerprint, breaker)
        return breaker

    def snapshot(self) -> dict[str, dict[str, Any]]:
        """State of every breaker that has seen traffic (health endpoint
        merges in untouched configured providers as implicit closed)."""
        return {name: br.snapshot()
                for name, (_, br) in sorted(self._breakers.items())}
