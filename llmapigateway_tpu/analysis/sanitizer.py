"""Runtime asyncio sanitizer: the dynamic half of graftlint v2.

Static analysis (rules/, program.py) proves what it can from source; this
module catches the classes of event-loop bug that are *structurally*
invisible to an AST — a callback that blocks because of data-dependent
control flow, a guarded field mutated through an alias the call graph
couldn't resolve, a task or span leaked by an exception path nobody
wrote a test for. Three detectors, all cheap enough to run under the
entire tier-1 suite (tests/conftest.py installs them session-wide, so
every chaos/obs/engine test doubles as a race hunt):

* :class:`StallDetector` — wraps ``asyncio.events.Handle._run`` to time
  every callback/coroutine step on the loop. A step exceeding the
  threshold is a violation; a watchdog thread samples the loop thread's
  stack *mid-stall* (``sys._current_frames``), so the report shows where
  the loop was stuck, not just which callback was slow.

* :class:`GuardTracker` — runtime enforcement of the ``# guarded-by:``
  convention the static lock-discipline rule checks lexically. Tracked
  objects get their annotated container fields wrapped in checking
  proxies (dict/list subclasses; a delegating proxy for ``asyncio.Queue``
  / ``sqlite3.Connection``) and their class ``__setattr__`` patched:
  ``guarded-by: loop`` fields must only be touched from the owning
  (instrumentation-time) thread, ``guarded-by: <lock>`` fields only while
  the named lock is held. Guard maps are parsed from the class's own
  source annotations, so the static and dynamic layers read one truth.

* leak checks — :func:`leaked_tasks` (pending tasks on a loop at
  teardown) and :func:`leaked_spans` (finished traces holding open
  non-root spans in obs/trace ring buffers).

Violations are recorded, never raised: a sanitizer that throws from
``__setattr__`` turns a diagnosed race into an undiagnosable crash. The
test harness asserts the violation list is empty at session end.
"""
from __future__ import annotations

import ast
import asyncio
import functools
import inspect
import logging
import sys
import threading
import time
import traceback
import weakref
from contextlib import contextmanager
from dataclasses import dataclass
from typing import Any, Callable, Iterable

from .rules.lock_discipline import _GUARDED_RE

logger = logging.getLogger(__name__)

MAX_VIOLATIONS = 200            # cap: a hot broken path must not OOM the run
DEFAULT_STALL_THRESHOLD_S = 0.25


@dataclass
class Violation:
    kind: str                   # "stall" | "guard" | "task-leak" | "span-leak"
    message: str
    stack: str = ""
    thread: str = ""

    def render(self) -> str:
        head = f"[{self.kind}] {self.message}"
        if self.thread:
            head += f" (thread={self.thread})"
        if self.stack:
            head += "\n" + "\n".join(
                "    " + l for l in self.stack.rstrip().splitlines())
        return head


# -- guard-map extraction (one truth with the static rule) -------------------

@functools.lru_cache(maxsize=None)
def guard_map_for(cls: type) -> dict[str, str]:
    """{attr: guard} for a class, parsed from the ``# guarded-by:``
    annotations in its defining module's source. Empty when the source is
    unavailable (frozen/REPL classes) — instrumentation degrades to a
    no-op rather than failing."""
    mod = sys.modules.get(cls.__module__)
    if mod is None:
        return {}
    try:
        src = inspect.getsource(mod)
        tree = ast.parse(src)
    except (OSError, TypeError, SyntaxError):
        return {}
    lines = src.splitlines()
    for node in ast.walk(tree):
        if isinstance(node, ast.ClassDef) and node.name == cls.__name__:
            guards: dict[str, str] = {}
            for sub in ast.walk(node):
                if not isinstance(sub, (ast.Assign, ast.AnnAssign)):
                    continue
                targets = (sub.targets if isinstance(sub, ast.Assign)
                           else [sub.target])
                for t in targets:
                    if (isinstance(t, ast.Attribute)
                            and isinstance(t.value, ast.Name)
                            and t.value.id == "self"):
                        for ln in range(sub.lineno,
                                        getattr(sub, "end_lineno",
                                                sub.lineno) + 1):
                            if ln <= len(lines):
                                m = _GUARDED_RE.search(lines[ln - 1])
                                if m:
                                    guards[t.attr] = m.group(1)
                                    break
            return guards
    return {}


# -- stall detection ----------------------------------------------------------

class StallDetector:
    """Times every ``Handle._run`` on every loop in the process; records a
    violation for steps exceeding ``threshold_s``. ``clock`` is injectable
    for fake-clock unit tests (:meth:`timed_call` exercises the exact
    production code path without a real loop)."""

    def __init__(self, threshold_s: float = DEFAULT_STALL_THRESHOLD_S,
                 clock: Callable[[], float] = time.monotonic,
                 watchdog: bool = True):
        self.threshold_s = threshold_s
        self._clock = clock
        self._watchdog_enabled = watchdog
        self.violations: list[Violation] = []
        self.installed = False
        self._orig_run: Callable | None = None
        self._paused = 0
        # thread id -> (start time, description) for steps in flight; the
        # watchdog samples these. GIL-atomic dict ops only.
        self._active: dict[int, tuple[float, str]] = {}
        self._stacks: dict[int, str] = {}
        self._watchdog: threading.Thread | None = None
        self._stop_watchdog = threading.Event()

    # -- the timed path (shared by the patch and the unit tests) ----------
    def timed_call(self, fn: Callable[[], Any], describe: str = "",
                   handle: Any = None) -> Any:
        """``describe`` may be empty when ``handle`` is given: the
        description is then built lazily, only for over-threshold steps —
        per-callback string building is measurable overhead on the hot
        loop and perturbs the timing the detector is meant to observe."""
        tid = threading.get_ident()
        t0 = self._clock()
        self._active[tid] = (t0, describe)
        try:
            return fn()
        finally:
            self._active.pop(tid, None)
            dt = self._clock() - t0
            if dt >= self.threshold_s and not self._paused:
                desc = describe or (_describe_handle(handle)
                                    if handle is not None else repr(fn))
                self._record(desc, dt, self._stacks.pop(tid, ""))

    def _record(self, desc: str, dt: float, stack: str) -> None:
        if len(self.violations) >= MAX_VIOLATIONS:
            return
        self.violations.append(Violation(
            kind="stall",
            message=(f"event-loop callback ran {dt * 1000.0:.1f} ms "
                     f"(threshold {self.threshold_s * 1000.0:.0f} ms): "
                     f"{desc[:300]}"),
            stack=stack, thread=threading.current_thread().name))

    # -- install/uninstall -------------------------------------------------
    def install(self) -> None:
        if self.installed:
            return
        self._orig_run = asyncio.events.Handle._run
        detector = self
        orig = self._orig_run

        def _run(handle):        # noqa: ANN001 — asyncio internal signature
            return detector.timed_call(lambda: orig(handle), handle=handle)

        asyncio.events.Handle._run = _run
        self.installed = True
        if self._watchdog_enabled:
            self._stop_watchdog.clear()
            self._watchdog = threading.Thread(
                target=self._watch, name="graft-sanitizer-watchdog",
                daemon=True)
            self._watchdog.start()

    def uninstall(self) -> None:
        if not self.installed:
            return
        asyncio.events.Handle._run = self._orig_run
        self.installed = False
        self._stop_watchdog.set()
        if self._watchdog is not None:
            self._watchdog.join(timeout=2.0)
            self._watchdog = None

    def _watch(self) -> None:
        """Sample the stack of any thread whose current step has already
        exceeded the threshold — captured mid-stall, this is the actual
        blocking site, which the post-hoc duration report can't show."""
        poll = max(0.01, min(0.25, self.threshold_s / 4.0))
        while not self._stop_watchdog.wait(poll):
            if self._paused or not self._active:
                continue
            now = self._clock()
            for tid, (t0, _desc) in list(self._active.items()):
                if now - t0 < self.threshold_s or tid in self._stacks:
                    continue
                frame = sys._current_frames().get(tid)
                if frame is not None:
                    self._stacks[tid] = "".join(
                        traceback.format_stack(frame, limit=12))

    @contextmanager
    def pause(self):
        self._paused += 1
        try:
            yield
        finally:
            self._paused -= 1


def _describe_handle(handle) -> str:
    return _describe_callback(getattr(handle, "_callback", None))


def _describe_callback(cb) -> str:
    """Describe a callback from metadata only — NEVER ``repr(cb)``. A
    bound method's repr calls ``repr(__self__)``, and instance reprs are
    not side-effect-free: aiohttp's ``ClientResponse.__repr__`` reads a
    ``@reify`` (cache-on-first-access) property, so an eager repr here
    caches it unpopulated and corrupts the object under test."""
    if cb is None:
        return "<handle>"
    if isinstance(cb, functools.partial):
        return f"partial({_describe_callback(cb.func)})"
    func = getattr(cb, "__func__", cb)      # unwrap bound methods
    name = (getattr(func, "__qualname__", None)
            or getattr(func, "__name__", None) or type(cb).__name__)
    owner = getattr(cb, "__self__", None)
    if owner is not None and not isinstance(owner, type):
        name = f"{name} of {type(owner).__name__}"
    mod = getattr(func, "__module__", None)
    return f"{mod}:{name}" if mod else name


# -- guarded-field tracking ---------------------------------------------------

class _GuardInfo:
    __slots__ = ("tracker", "obj", "guards", "owner_ident")

    def __init__(self, tracker: "GuardTracker", obj: Any,
                 guards: dict[str, str], owner_ident: int | None):
        self.tracker = tracker
        self.obj = obj
        self.guards = guards
        # For `guarded-by: loop` fields: the event-loop thread that owns
        # the object. None = not yet known — objects are often BUILT off
        # the loop (ProviderRegistry constructs engines in a worker
        # thread), so ownership binds lazily to the first thread that
        # touches a loop-guarded field while actually running an event
        # loop (see GuardTracker._check).
        self.owner_ident = owner_ident


def _running_loop_here() -> bool:
    try:
        asyncio.get_running_loop()
        return True
    except RuntimeError:
        return False


def _lock_held(lock: Any) -> bool | None:
    """Best-effort: is this lock held (by anyone)? None = can't tell."""
    is_owned = getattr(lock, "_is_owned", None)
    if callable(is_owned):               # RLock: ownership, not just held
        try:
            return bool(is_owned())
        except TypeError:
            pass
    locked = getattr(lock, "locked", None)
    if callable(locked):                 # threading.Lock / asyncio.Lock
        return bool(locked())
    return None


class GuardedDict(dict):
    """dict that runs the guard check before every mutation."""
    __slots__ = ("_graft_check",)

    def __init__(self, data: dict, check: Callable[[str], None]):
        super().__init__(data)
        self._graft_check = check

    def __reduce__(self):                # pickling drops the proxy
        return (dict, (dict(self),))


class GuardedList(list):
    """list that runs the guard check before every mutation."""
    __slots__ = ("_graft_check",)

    def __init__(self, data: list, check: Callable[[str], None]):
        super().__init__(data)
        self._graft_check = check

    def __reduce__(self):
        return (list, (list(self),))


def _checked(method_name: str):
    def op(self, *a, **kw):
        self._graft_check(f".{method_name}()")
        return getattr(super(type(self), self), method_name)(*a, **kw)
    op.__name__ = method_name
    return op


for _m in ("__setitem__", "__delitem__", "pop", "popitem", "clear",
           "update", "setdefault"):
    setattr(GuardedDict, _m, _checked(_m))
for _m in ("__setitem__", "__delitem__", "__iadd__", "append", "extend",
           "insert", "pop", "remove", "clear", "sort", "reverse"):
    setattr(GuardedList, _m, _checked(_m))


class _CheckedDelegate:
    """Attribute-delegating proxy for stateful non-container guarded
    values (asyncio.Queue, sqlite3.Connection): mutator method calls run
    the guard check, everything else passes straight through."""

    _MUTATORS = frozenset({
        "put_nowait", "get_nowait", "put", "get", "task_done",
        "execute", "executemany", "executescript", "commit", "rollback",
        "close",
    })

    def __init__(self, target: Any, check: Callable[[str], None]):
        object.__setattr__(self, "_graft_target", target)
        object.__setattr__(self, "_graft_check", check)

    def __getattr__(self, name: str) -> Any:
        target = object.__getattribute__(self, "_graft_target")
        val = getattr(target, name)
        if name in _CheckedDelegate._MUTATORS and callable(val):
            check = object.__getattribute__(self, "_graft_check")

            def checked(*a, **kw):
                check(f".{name}()")
                return val(*a, **kw)
            return checked
        return val

    def __setattr__(self, name: str, value: Any) -> None:
        setattr(object.__getattribute__(self, "_graft_target"), name, value)

    def __repr__(self) -> str:
        return f"<guarded {object.__getattribute__(self, '_graft_target')!r}>"


class GuardTracker:
    """Tracks objects whose classes carry ``# guarded-by:`` annotations
    and records violations of the declared guard at mutation time."""

    def __init__(self):
        self.violations: list[Violation] = []
        self._patched: list[tuple[type, Any]] = []
        self._patched_types: set[type] = set()
        self._paused = 0

    # -- tracking ----------------------------------------------------------
    def track(self, obj: Any, guards: dict[str, str] | None = None,
              owner_ident: int | None = None) -> Any:
        """Instrument one object. ``guards`` defaults to the class's
        source annotations. ``owner_ident`` pins the loop-owner thread for
        ``guarded-by: loop`` fields; by default it binds lazily to the
        first toucher that is running an event loop."""
        if guards is None:
            guards = guard_map_for(type(obj))
        if not guards:
            return obj
        info = _GuardInfo(self, obj, dict(guards), owner_ident)
        self._ensure_patched(type(obj))
        object.__setattr__(obj, "_graft_guard_info", info)
        for attr in guards:
            if attr in obj.__dict__:
                val = obj.__dict__[attr]
                wrapped = self._wrap(info, attr, val)
                if wrapped is not val:
                    object.__setattr__(obj, attr, wrapped)
        return obj

    def _wrap(self, info: "_GuardInfo", attr: str, val: Any) -> Any:
        def check(op: str, _info=info, _attr=attr) -> None:
            self._check(_info, _attr, op)
        if type(val) is dict:
            return GuardedDict(val, check)
        if type(val) is list:
            return GuardedList(val, check)
        if isinstance(val, asyncio.Queue) or \
                type(val).__module__ == "sqlite3":
            return _CheckedDelegate(val, check)
        return val

    def _ensure_patched(self, cls: type) -> None:
        if cls in self._patched_types:
            return
        had_own = "__setattr__" in cls.__dict__
        orig = cls.__setattr__
        tracker = self

        def __setattr__(obj, name, value):
            info = obj.__dict__.get("_graft_guard_info")
            if info is not None and name in info.guards:
                tracker._check(info, name, "rebind")
                value = tracker._wrap(info, name, value)
            orig(obj, name, value)

        cls.__setattr__ = __setattr__
        self._patched.append((cls, orig if had_own else None))
        self._patched_types.add(cls)

    def untrack_all(self) -> None:
        for cls, orig in self._patched:
            if orig is None:
                del cls.__setattr__      # fall back to the inherited slot
            else:
                cls.__setattr__ = orig
        self._patched.clear()
        self._patched_types.clear()

    # -- the check ---------------------------------------------------------
    def _check(self, info: "_GuardInfo", attr: str, op: str) -> None:
        if self._paused or len(self.violations) >= MAX_VIOLATIONS:
            return
        guard = info.guards.get(attr)
        cls_name = type(info.obj).__name__
        if guard == "loop":
            ident = threading.get_ident()
            if info.owner_ident is None:
                # First touch wins ownership — but only from a thread that
                # is actually running an event loop (construction and
                # direct sync-test pokes don't bind).
                if _running_loop_here():
                    info.owner_ident = ident
                return
            if ident != info.owner_ident:
                self._violate(
                    f"{cls_name}.{attr} is `guarded-by: loop` (owner "
                    f"thread only) but was mutated ({op}) from "
                    f"{threading.current_thread().name}")
            return
        lock = getattr(info.obj, guard, None)
        if lock is None:
            return
        held = _lock_held(lock)
        if held is False:
            self._violate(
                f"{cls_name}.{attr} is `guarded-by: {guard}` but was "
                f"mutated ({op}) without the lock held")

    def _violate(self, message: str) -> None:
        self.violations.append(Violation(
            kind="guard", message=message,
            stack="".join(traceback.format_stack(limit=10)[:-2]),
            thread=threading.current_thread().name))

    @contextmanager
    def pause(self):
        self._paused += 1
        try:
            yield
        finally:
            self._paused -= 1


# -- leak detection -----------------------------------------------------------

def leaked_tasks(loop: asyncio.AbstractEventLoop) -> list[Violation]:
    """Tasks still pending on ``loop`` — at teardown, anything here was
    started and never awaited/cancelled (the 'Task was destroyed but it
    is pending' class of bug, caught deterministically)."""
    out: list[Violation] = []
    try:
        tasks = asyncio.all_tasks(loop)
    except RuntimeError:
        return out
    for t in tasks:
        if t.done():
            continue
        coro = getattr(t, "get_coro", lambda: None)()
        out.append(Violation(
            kind="task-leak",
            message=f"task still pending at teardown: {coro!r}"))
    return out


def leaked_spans(tracers: Iterable[Any]) -> list[Violation]:
    """Finished traces holding open non-root spans, across tracer ring
    buffers — a leaked span makes every later trace read a lie."""
    out: list[Violation] = []
    for tracer in tracers:
        traces = getattr(tracer, "_traces", None)
        if traces is None:
            continue
        for trace in list(traces.values()):
            root = trace.root
            if root.end is None:
                continue                     # still in flight: not a leak
            for sp in root.walk():
                if sp is not root and sp.end is None:
                    out.append(Violation(
                        kind="span-leak",
                        message=(f"trace {trace.request_id!r} finished "
                                 f"with open span {sp.name!r} "
                                 f"(layer={sp.layer})")))
    return out


# -- the facade ---------------------------------------------------------------

class AsyncioSanitizer:
    """Bundles the three detectors behind one install/report surface —
    what tests/conftest.py activates for the tier-1 suite."""

    def __init__(self, stall_threshold_s: float = DEFAULT_STALL_THRESHOLD_S,
                 clock: Callable[[], float] = time.monotonic,
                 watchdog: bool = True):
        self.stall = StallDetector(stall_threshold_s, clock=clock,
                                   watchdog=watchdog)
        self.guards = GuardTracker()
        self.leaks: list[Violation] = []
        self._init_patches: list[tuple[type, Any]] = []
        self.tracers: "weakref.WeakSet[Any]" = weakref.WeakSet()

    # -- lifecycle ---------------------------------------------------------
    def install(self) -> None:
        self.stall.install()

    def uninstall(self) -> None:
        self.stall.uninstall()
        self.guards.untrack_all()
        for cls, orig in self._init_patches:
            cls.__init__ = orig
        self._init_patches.clear()

    @property
    def active(self) -> bool:
        return self.stall.installed

    # -- instrumentation ---------------------------------------------------
    def track(self, obj: Any, guards: dict[str, str] | None = None,
              owner_ident: int | None = None) -> Any:
        return self.guards.track(obj, guards, owner_ident)

    def register_tracer(self, tracer: Any) -> None:
        self.tracers.add(tracer)

    def instrument_classes(self, classes: Iterable[type]) -> None:
        """Wrap each class's ``__init__`` so every future instance is
        tracked (guard annotations) or registered (trace ring buffers)
        automatically. Undone by :meth:`uninstall`."""
        for cls in classes:
            orig_init = cls.__init__
            sanitizer = self

            def make_init(orig_init=orig_init, cls=cls):
                @functools.wraps(orig_init)
                def __init__(obj, *args, **kwargs):
                    orig_init(obj, *args, **kwargs)
                    try:
                        if hasattr(obj, "_traces"):
                            sanitizer.register_tracer(obj)
                        else:
                            sanitizer.track(obj)
                    except Exception:       # sanitizer must never break SUT
                        logger.exception("sanitizer track() failed for %s",
                                         cls.__name__)
                return __init__

            cls.__init__ = make_init()
            self._init_patches.append((cls, orig_init))

    # -- reporting ---------------------------------------------------------
    def check_leaks(self, loop: asyncio.AbstractEventLoop | None = None) -> list[Violation]:
        found: list[Violation] = []
        if loop is not None:
            found.extend(leaked_tasks(loop))
        found.extend(leaked_spans(self.tracers))
        self.leaks.extend(found)
        return found

    def violations(self) -> list[Violation]:
        return list(self.stall.violations) + list(self.guards.violations) \
            + list(self.leaks)

    def report(self) -> str:
        v = self.violations()
        if not v:
            return "asyncio sanitizer: clean"
        lines = [f"asyncio sanitizer: {len(v)} violation(s)"]
        lines += [x.render() for x in v]
        return "\n".join(lines)

    @contextmanager
    def pause(self):
        with self.stall.pause(), self.guards.pause():
            yield


def default_instrumented_classes() -> list[type]:
    """The gateway classes the tier-1 suite instruments: every layer that
    carries ``# guarded-by:`` annotations, plus the tracer (span leaks).
    Imported lazily so proxy-only deployments can use the sanitizer
    without JAX."""
    from ..config.loader import ConfigLoader
    from ..db.rotation import RotationDB
    from ..db.usage import UsageDB
    from ..obs.trace import Tracer
    from ..routing.router import ProviderRegistry
    classes: list[type] = [ConfigLoader, RotationDB, UsageDB, Tracer,
                           ProviderRegistry]
    try:
        from ..engine.engine import InferenceEngine
        classes.append(InferenceEngine)
    except Exception:                       # JAX-less deployment
        logger.info("engine unavailable; sanitizer skips it", exc_info=True)
    # The radix prefix cache is jax-free but lives in the engine package;
    # its `guarded-by: loop` counters must only mutate on the scheduler
    # thread (ISSUE 6).
    from ..engine.prefix_cache import RadixPrefixCache
    classes.append(RadixPrefixCache)
    # The flight recorder's ring is single-writer-from-the-loop BY
    # CONTRACT (ISSUE 7: allocation- AND lock-free appends); the
    # sanitizer enforcing its `guarded-by: loop` fields is what makes
    # that contract testable instead of aspirational.
    from ..obs.flight import FlightRecorder
    classes.append(FlightRecorder)
    # The disaggregation controller + pools (ISSUE 13) are scheduler
    # state carved out of the engine — same loop-thread-only contract,
    # same enforcement. jax-free module, so no import guard.
    from ..engine.disagg import DisaggController, SlotPool
    classes.append(DisaggController)
    classes.append(SlotPool)
    # The engine supervisor (ISSUE 14) is lifecycle state with the same
    # loop-thread-only contract: transitions, heartbeats and restart
    # bookkeeping all happen scheduler-side. jax-free module.
    from ..reliability.supervisor import EngineSupervisor
    classes.append(EngineSupervisor)
    return classes
