"""graftlint — AST-based invariant checker for the gateway.

Project-specific static analysis the stock toolchain can't express:
async-hygiene on the serving path, JAX tracer safety in compiled-program
bodies, lock discipline across the engine/router/db layers, secret
hygiene at log sites, and SSE framing at yield sites. Run it as::

    python -m llmapigateway_tpu.analysis llmapigateway_tpu/

Exit code 0 = clean; 1 = findings; 2 = usage error. tests/test_graftlint.py
keeps the live tree at exit 0 forever (tier-1 gate). Suppression syntax
and the rule catalog are documented in tools/README.md.
"""
from __future__ import annotations

from .core import (ChainHop, Finding, Rule, analyze_file, analyze_paths,
                   analyze_source, iter_python_files, package_relpath)
from .program import Program, analyze_program, summarize_module, summarize_source
from .rules import ALL_RULES, RULES_BY_NAME

__all__ = [
    "ALL_RULES", "RULES_BY_NAME", "ChainHop", "Finding", "Program", "Rule",
    "analyze_file", "analyze_paths", "analyze_program", "analyze_source",
    "iter_python_files", "package_relpath", "summarize_module",
    "summarize_source",
]
