"""graftlint core: findings, the rule protocol, suppressions, the driver.

The gateway's hot paths depend on invariants no off-the-shelf tool checks
(PAPER.md §7: the reference ships zero correctness tooling): the asyncio
request path must never block the event loop, jitted prefill/decode
programs must never smuggle host syncs into traced bodies, and the
engine/router/db layers each carry their own lock discipline. graftlint is
an AST-level checker for exactly those project invariants — a tier-1 gate
(tests/test_graftlint.py asserts the live tree is clean), not a style
linter.

Suppression syntax (documented in tools/README.md):

* trailing comment — suppresses the named rule(s) on that line only::

      time.sleep(0.1)  # graftlint: disable=async-blocking — startup only

* standalone comment line — suppresses the rule(s) for the whole file::

      # graftlint: disable=tracer-hazard

``disable=all`` suppresses every rule. Unknown rule names in a
suppression are findings themselves (rule ``graftlint-meta``), so stale
suppressions can't silently rot.
"""
from __future__ import annotations

import ast
import re
from dataclasses import dataclass, field
from pathlib import Path
from typing import Iterable, Iterator

PACKAGE_NAME = "llmapigateway_tpu"

_SUPPRESS_RE = re.compile(r"#\s*graftlint:\s*disable=([\w\-,\s]+)")


@dataclass(frozen=True)
class ChainHop:
    """One hop of an interprocedural finding's call chain: where the call
    (or dispatch, or blocking primitive) sits and what it does."""
    path: str
    line: int
    note: str

    def to_dict(self) -> dict:
        return {"path": self.path, "line": self.line, "note": self.note}


@dataclass(frozen=True)
class Finding:
    """One rule violation at a source location. Whole-program findings
    (analysis/program.py) carry the full call chain — every file:line hop
    from the flagged site to the primitive that makes it a violation — in
    ``chain``; per-file lexical findings leave it empty."""
    rule: str
    path: str
    line: int
    col: int
    message: str
    chain: tuple[ChainHop, ...] = ()

    def to_dict(self) -> dict:
        d = {"rule": self.rule, "path": self.path, "line": self.line,
             "col": self.col, "message": self.message}
        if self.chain:
            d["chain"] = [h.to_dict() for h in self.chain]
        return d

    def render(self) -> str:
        head = f"{self.path}:{self.line}:{self.col}: [{self.rule}] {self.message}"
        if not self.chain:
            return head
        hops = "\n".join(f"    {i + 1}. {h.path}:{h.line}: {h.note}"
                         for i, h in enumerate(self.chain))
        return head + "\n" + hops


class Rule:
    """Base class for graftlint rules.

    Subclasses set ``name``/``description`` and either ``dirs`` (package-
    relative directory prefixes) or ``files`` (exact package-relative
    paths) to scope where the rule applies; both empty means everywhere.
    ``check`` receives the parsed module, the raw source, and the
    package-relative path, and returns findings (unsuppressed — the
    driver filters)."""

    name: str = ""
    description: str = ""
    dirs: tuple[str, ...] = ()
    files: tuple[str, ...] = ()

    def applies_to(self, relpath: str) -> bool:
        if not self.dirs and not self.files:
            return True
        if relpath in self.files:
            return True
        return any(relpath.startswith(d.rstrip("/") + "/") for d in self.dirs)

    def check(self, tree: ast.Module, source: str,
              relpath: str) -> list[Finding]:
        raise NotImplementedError

    def finding(self, relpath: str, node: ast.AST, message: str) -> Finding:
        return Finding(rule=self.name, path=relpath,
                       line=getattr(node, "lineno", 0),
                       col=getattr(node, "col_offset", 0), message=message)


@dataclass
class Suppressions:
    """Parsed ``# graftlint: disable=...`` comments for one file."""
    file_rules: set[str] = field(default_factory=set)
    line_rules: dict[int, set[str]] = field(default_factory=dict)
    bad_names: list[tuple[int, str]] = field(default_factory=list)

    @classmethod
    def parse(cls, source: str, known_rules: set[str]) -> "Suppressions":
        supp = cls()
        for lineno, line in enumerate(source.splitlines(), start=1):
            m = _SUPPRESS_RE.search(line)
            if not m:
                continue
            names = {n.strip() for n in m.group(1).split(",") if n.strip()}
            for n in names:
                if n != "all" and n not in known_rules:
                    supp.bad_names.append((lineno, n))
            if line.lstrip().startswith("#"):       # standalone → whole file
                supp.file_rules |= names
            else:                                   # trailing → this line
                supp.line_rules.setdefault(lineno, set()).update(names)
        return supp

    def is_suppressed(self, f: Finding) -> bool:
        if "all" in self.file_rules or f.rule in self.file_rules:
            return True
        on_line = self.line_rules.get(f.line, ())
        return "all" in on_line or f.rule in on_line


def package_relpath(path: str | Path, base: Path | None = None) -> str:
    """Path relative to the package root: everything after the last
    ``llmapigateway_tpu`` component (so rule scoping works from any CWD).
    Paths without the component fall back to ``base``-relative (the CLI
    passes the scanned root, so out-of-tree layouts still scope), else
    pass through — fixture paths in tests."""
    parts = Path(path).parts
    for i in range(len(parts) - 1, -1, -1):
        if parts[i] == PACKAGE_NAME:
            return "/".join(parts[i + 1:])
    if base is not None:
        try:
            return Path(path).resolve().relative_to(
                base.resolve()).as_posix()
        except ValueError:
            pass
    return "/".join(parts)


def analyze_source(source: str, path: str | Path,
                   rules: Iterable[Rule],
                   base: Path | None = None) -> list[Finding]:
    """Run the given rules over one file's source; returns unsuppressed
    findings sorted by location. A syntax error is itself a finding
    (rule ``parse-error``) so broken files can't slip past the gate."""
    relpath = package_relpath(path, base)
    rules = list(rules)
    try:
        tree = ast.parse(source, filename=str(path))
    except SyntaxError as e:
        return [Finding(rule="parse-error", path=relpath,
                        line=e.lineno or 0, col=e.offset or 0,
                        message=f"syntax error: {e.msg}")]
    known = {r.name for r in rules}
    supp = Suppressions.parse(source, known)
    findings: list[Finding] = []
    for rule in rules:
        if rule.applies_to(relpath):
            findings.extend(rule.check(tree, source, relpath))
    findings = [f for f in findings if not supp.is_suppressed(f)]
    for lineno, bad in supp.bad_names:
        findings.append(Finding(
            rule="graftlint-meta", path=relpath, line=lineno, col=0,
            message=f"suppression names unknown rule {bad!r}"))
    findings.sort(key=lambda f: (f.path, f.line, f.col, f.rule))
    return findings


def analyze_file(path: str | Path, rules: Iterable[Rule],
                 base: Path | None = None) -> list[Finding]:
    return analyze_source(Path(path).read_text(), path, rules, base)


def iter_python_files(root: str | Path) -> Iterator[Path]:
    root = Path(root)
    if root.is_file():
        yield root
        return
    for p in sorted(root.rglob("*.py")):
        if "__pycache__" not in p.parts:
            yield p


def analyze_paths(paths: Iterable[str | Path],
                  rules: Iterable[Rule]) -> list[Finding]:
    rules = list(rules)
    findings: list[Finding] = []
    for root in paths:
        base = Path(root) if Path(root).is_dir() else Path(root).parent
        for f in iter_python_files(root):
            findings.extend(analyze_file(f, rules, base))
    return findings
