"""CLI entry point: ``python -m llmapigateway_tpu.analysis [paths...]``.

v2 drives both layers: the per-file lexical rules AND the whole-program
pass (symbol table + call graph + dataflow, analysis/program.py), with an
mtime/content-hash incremental cache (analysis/cache.py) so warm runs and
the tier-1 gate stay fast.

Modes::

    python -m llmapigateway_tpu.analysis llmapigateway_tpu/
    python -m llmapigateway_tpu.analysis --format sarif > graftlint.sarif
    python -m llmapigateway_tpu.analysis --changed origin/main   # pre-commit
    python -m llmapigateway_tpu.analysis --cache /tmp/gl.json pkg/

Exit code 0 = clean; 1 = findings; 2 = usage error.
"""
from __future__ import annotations

import argparse
import subprocess
import sys
from pathlib import Path

from .cache import LintCache
from .core import analyze_source, iter_python_files, package_relpath
from .program import analyze_program, summarize_source
from .reporter import render_json, render_rules, render_sarif, render_text
from .rules import ALL_RULES, RULES_BY_NAME

DEFAULT_CACHE = ".graftlint_cache.json"


def _repo_root(start: Path) -> Path | None:
    for p in (start, *start.parents):
        if (p / ".git").exists():
            return p
    return None


def _changed_files(ref: str, repo: Path) -> list[Path] | None:
    """Tracked files differing from ``ref`` plus untracked files; None on
    git failure (caller reports the usage error)."""
    files: set[str] = set()
    for args in (["diff", "--name-only", ref, "--"],
                 ["ls-files", "--others", "--exclude-standard"]):
        proc = subprocess.run(["git", "-C", str(repo), *args],
                              capture_output=True, text=True)
        if proc.returncode != 0:
            sys.stderr.write(proc.stderr)
            return None
        files.update(l.strip() for l in proc.stdout.splitlines() if l.strip())
    out = []
    for rel in sorted(files):
        p = repo / rel
        if p.suffix == ".py" and p.exists():
            out.append(p)
    return out


def main(argv: list[str] | None = None) -> int:
    parser = argparse.ArgumentParser(
        prog="python -m llmapigateway_tpu.analysis",
        description="graftlint v2: per-file invariants + whole-program "
                    "dataflow analysis for the gateway")
    parser.add_argument("paths", nargs="*",
                        help="files/directories to check (default: the "
                             "installed llmapigateway_tpu package)")
    parser.add_argument("--format", choices=("text", "json", "sarif"),
                        default="text")
    parser.add_argument("--rules", default="",
                        help="comma-separated subset of rules to run")
    parser.add_argument("--list-rules", action="store_true",
                        help="print the rule catalog and exit")
    parser.add_argument("--changed", metavar="GIT_REF", default="",
                        help="lint only files differing from GIT_REF "
                             "(plus untracked); the whole-program pass "
                             "still sees the full tree, reported findings "
                             "are filtered to the changed set")
    parser.add_argument("--cache", metavar="PATH", default="",
                        help=f"incremental cache file (mtime+sha256 keyed); "
                             f"--changed defaults it to ./{DEFAULT_CACHE}")
    parser.add_argument("--no-cache", action="store_true",
                        help="disable the incremental cache")
    parser.add_argument("--no-program", action="store_true",
                        help="skip the whole-program (interprocedural) pass")
    args = parser.parse_args(argv)

    if args.list_rules:
        print(render_rules(ALL_RULES))
        return 0

    rules = list(ALL_RULES)
    if args.rules:
        try:
            rules = [RULES_BY_NAME[n.strip()]
                     for n in args.rules.split(",") if n.strip()]
        except KeyError as e:
            print(f"unknown rule {e.args[0]!r}; available: "
                  f"{', '.join(sorted(RULES_BY_NAME))}", file=sys.stderr)
            return 2

    package_dir = Path(__file__).resolve().parents[1]

    cache = None
    cache_path = args.cache
    if args.changed and not cache_path and not args.no_cache:
        cache_path = DEFAULT_CACHE
    if cache_path and not args.no_cache:
        cache = LintCache(cache_path,
                          rule_names=tuple(r.name for r in rules))

    # -- the file set --------------------------------------------------------
    report_only: set[str] | None = None
    program_roots: list[Path]
    if args.changed:
        repo = _repo_root(package_dir)
        if repo is None:
            print("--changed needs a git repository above the package",
                  file=sys.stderr)
            return 2
        changed = _changed_files(args.changed, repo)
        if changed is None:
            print(f"git diff against {args.changed!r} failed", file=sys.stderr)
            return 2
        file_sets = [(p, p.parent) for p in changed]
        report_only = {package_relpath(p, base) for p, base in file_sets}
        program_roots = [package_dir]
    else:
        roots = [Path(p) for p in (args.paths or [str(package_dir)])]
        for root in roots:
            if not root.exists():
                print(f"no such path: {root}", file=sys.stderr)
                return 2
        file_sets = []
        for root in roots:
            base = root if root.is_dir() else root.parent
            file_sets.extend((f, base) for f in iter_python_files(root))
        program_roots = roots

    # -- per-file lexical pass (cache-aware) ---------------------------------
    findings = []
    summaries: dict[str, dict] = {}
    n_files = 0
    for f, base in file_sets:
        n_files += 1
        rel = package_relpath(f, base)
        if cache is not None:
            hit = cache.lookup(f, rel)
            if hit is not None:
                file_findings, summary, _ = hit
                findings.extend(file_findings)
                if summary is not None:
                    summaries[rel] = summary
                continue
        try:
            src = f.read_text()
        except OSError as e:
            print(f"cannot read {f}: {e}", file=sys.stderr)
            return 2
        file_findings = analyze_source(src, f, rules, base)
        summary = summarize_source(src, f, base)
        findings.extend(file_findings)
        if summary is not None:
            summaries[rel] = summary
        if cache is not None:
            cache.store(f, rel, src, file_findings, summary)

    # -- whole-program pass --------------------------------------------------
    if not args.no_program:
        # With --changed, unchanged files' summaries come from the cache
        # (analyze_program parses whatever is still missing).
        if cache is not None and args.changed:
            for root in program_roots:
                base = root if root.is_dir() else root.parent
                for f in iter_python_files(root):
                    rel = package_relpath(f, base)
                    if rel in summaries:
                        continue
                    hit = cache.lookup(f, rel)
                    if hit is not None and hit[1] is not None:
                        summaries[rel] = hit[1]
        findings.extend(analyze_program(program_roots, summaries=summaries,
                                        report_only=report_only))

    if cache is not None:
        cache.save()

    findings.sort(key=lambda x: (x.path, x.line, x.col, x.rule))

    if args.format == "sarif":
        print(render_sarif(findings, checked_files=n_files, rules=rules))
    elif args.format == "json":
        print(render_json(findings, checked_files=n_files))
    else:
        print(render_text(findings, checked_files=n_files))
    return 1 if findings else 0


if __name__ == "__main__":
    sys.exit(main())
