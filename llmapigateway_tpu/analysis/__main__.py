"""CLI entry point: ``python -m llmapigateway_tpu.analysis [paths...]``."""
from __future__ import annotations

import argparse
import sys
from pathlib import Path

from .core import analyze_file, iter_python_files
from .reporter import render_json, render_rules, render_text
from .rules import ALL_RULES, RULES_BY_NAME


def main(argv: list[str] | None = None) -> int:
    parser = argparse.ArgumentParser(
        prog="python -m llmapigateway_tpu.analysis",
        description="graftlint: AST-based invariant checker for the gateway")
    parser.add_argument("paths", nargs="*",
                        help="files/directories to check (default: the "
                             "installed llmapigateway_tpu package)")
    parser.add_argument("--format", choices=("text", "json"), default="text")
    parser.add_argument("--rules", default="",
                        help="comma-separated subset of rules to run")
    parser.add_argument("--list-rules", action="store_true",
                        help="print the rule catalog and exit")
    args = parser.parse_args(argv)

    if args.list_rules:
        print(render_rules(ALL_RULES))
        return 0

    rules = list(ALL_RULES)
    if args.rules:
        try:
            rules = [RULES_BY_NAME[n.strip()]
                     for n in args.rules.split(",") if n.strip()]
        except KeyError as e:
            print(f"unknown rule {e.args[0]!r}; available: "
                  f"{', '.join(sorted(RULES_BY_NAME))}", file=sys.stderr)
            return 2

    paths = args.paths or [str(Path(__file__).resolve().parents[1])]
    findings = []
    n_files = 0
    for p in paths:
        root = Path(p)
        if not root.exists():
            print(f"no such path: {p}", file=sys.stderr)
            return 2
        base = root if root.is_dir() else root.parent
        for f in iter_python_files(root):
            n_files += 1
            findings.extend(analyze_file(f, rules, base))
    findings.sort(key=lambda x: (x.path, x.line, x.col, x.rule))

    render = render_json if args.format == "json" else render_text
    print(render(findings, checked_files=n_files))
    return 1 if findings else 0


if __name__ == "__main__":
    sys.exit(main())
