"""async-blocking: event-loop-blocking calls lexically inside ``async def``.

The gateway serves every request on one asyncio event loop; a single
blocking call in the request path stalls *all* in-flight SSE streams (the
whole reason the engine offloads compiled-program calls to a worker
thread). This rule flags the blocking primitives this codebase has
actually reached for — ``time.sleep``, synchronous sqlite3/file I/O,
``requests.*``, ``jax.block_until_ready``/``jax.device_get``, and
device-sync fetches (``.item()``, ``float(jnp...)``) — anywhere lexically
inside an ``async def`` in the serving layers (``server/``, ``routing/``,
``providers/``).

Bodies of *nested synchronous* functions are skipped: a sync def inside a
coroutine is how this codebase packages work for ``asyncio.to_thread`` /
daemon threads, where blocking is the point.
"""
from __future__ import annotations

import ast

from ..core import Finding, Rule
from ._util import call_name, references_module

_JAX_ROOTS = frozenset({"jax", "jnp"})

# Exact dotted calls that block the loop.
_BLOCKING_CALLS = {
    "time.sleep": "time.sleep() blocks the event loop; use await asyncio.sleep()",
    "jax.block_until_ready":
        "jax.block_until_ready() is a host sync; offload via asyncio.to_thread",
    "jax.device_get":
        "jax.device_get() is a device->host sync; offload via asyncio.to_thread",
}

# Any call into these modules is synchronous I/O.
_BLOCKING_MODULE_ROOTS = {
    "requests": "requests.* is synchronous HTTP; use the pooled httpx.AsyncClient",
    "sqlite3": "synchronous sqlite3 call on the event loop; go through the "
               "DB layer's *_async methods (asyncio.to_thread)",
}

# Method names that mean synchronous file I/O whatever the receiver
# (pathlib.Path and file objects both).
_BLOCKING_METHODS = {
    "read_text": "synchronous file read on the event loop; use asyncio.to_thread",
    "write_text": "synchronous file write on the event loop; use asyncio.to_thread",
    "read_bytes": "synchronous file read on the event loop; use asyncio.to_thread",
    "write_bytes": "synchronous file write on the event loop; use asyncio.to_thread",
}


def classify_blocking_call(node: ast.Call) -> str | None:
    """The message describing why this Call blocks the event loop, or None
    if it doesn't. Shared between the lexical rule (direct calls inside
    ``async def``) and the whole-program pass (analysis/program.py), so the
    two can never disagree about what counts as blocking."""
    name = call_name(node)
    if name is not None:
        if name in _BLOCKING_CALLS:
            return _BLOCKING_CALLS[name]
        root = name.split(".")[0]
        if root in _BLOCKING_MODULE_ROOTS and "." in name:
            return _BLOCKING_MODULE_ROOTS[root]
    func = node.func
    if isinstance(func, ast.Attribute):
        if func.attr in _BLOCKING_METHODS:
            return _BLOCKING_METHODS[func.attr]
        if func.attr == "item" and not node.args and not node.keywords:
            return (".item() forces a device->host sync on the event loop; "
                    "fetch via asyncio.to_thread")
    if (isinstance(func, ast.Name) and func.id == "open"
            and not _is_async_open(node)):
        return ("open() is synchronous file I/O on the event loop; use "
                "asyncio.to_thread")
    if (isinstance(func, ast.Name) and func.id in ("float", "int")
            and node.args
            and references_module(node.args[0], _JAX_ROOTS)):
        return (f"{func.id}() of a JAX array is a device->host sync on the "
                "event loop; fetch via asyncio.to_thread")
    return None


class AsyncBlockingRule(Rule):
    name = "async-blocking"
    description = ("blocking calls (time.sleep, sync sqlite3/file I/O, "
                   "requests.*, JAX host syncs, .item()/float(arr)) inside "
                   "async def bodies in the serving layers; the "
                   "whole-program pass extends this transitively through "
                   "sync helpers in any module")
    dirs = ("server", "routing", "providers")

    def check(self, tree: ast.Module, source: str,
              relpath: str) -> list[Finding]:
        findings: list[Finding] = []
        for node in ast.walk(tree):
            if isinstance(node, ast.AsyncFunctionDef):
                self._check_async_body(node, relpath, findings)
        return findings

    def _check_async_body(self, fn: ast.AsyncFunctionDef, relpath: str,
                          findings: list[Finding]) -> None:
        # Walk the coroutine body without descending into nested SYNC defs
        # (worker-thread payloads); nested async defs are still on the loop.
        stack: list[ast.AST] = list(ast.iter_child_nodes(fn))
        while stack:
            node = stack.pop()
            if isinstance(node, ast.FunctionDef):
                continue
            stack.extend(ast.iter_child_nodes(node))
            if isinstance(node, ast.Call):
                msg = classify_blocking_call(node)
                if msg is not None:
                    findings.append(self.finding(relpath, node, msg))


def _is_async_open(node: ast.Call) -> bool:
    # `async with open(...)` never parses this way, but `aiofiles.open`
    # resolves as a dotted call, not bare `open` — nothing to special-case
    # today; kept as a seam for an async-file library if one arrives.
    return False


RULE = AsyncBlockingRule()
