"""secret-hygiene: provider credentials flowing into log calls.

The gateway holds real provider API keys (providers.json / env vars) and
forwards client bearer tokens; one careless ``logger.info`` puts them in
the rotating JSON log file and every log aggregator downstream. This rule
flags log-call arguments — positional, f-string interpolations, and
``extra=`` dict values — that reference a name matching the secret
pattern (``api_key``/``apikey``/``secret``/``password``/
``authorization``/``bearer``/``credential``), unless the value is wrapped
in a masking/redaction call (``mask_headers(...)``, ``redact(...)``).

Name-based, deliberately: taint tracking through locals is out of scope
for an AST pass, but this codebase's convention is that secrets keep
their secret-shaped names (``self.api_key``, ``pd.apikey``), so the
lexical check catches the realistic leak shapes.
"""
from __future__ import annotations

import ast
import re

from ..core import Finding, Rule

_SECRET_RE = re.compile(
    r"(?i)(?:^|_)(api_?key|secret|passw(?:or)?d|authorization|bearer|"
    r"credential|access_token)(?:$|_)")
_SANITIZER_RE = re.compile(r"(?i)(mask|redact|fingerprint|hash)")

_LOG_METHODS = frozenset({"debug", "info", "warning", "warn", "error",
                          "exception", "critical", "log"})
_LOG_OBJECTS = frozenset({"logger", "logging", "log", "_logger"})


def _terminal_name(node: ast.AST) -> str | None:
    if isinstance(node, ast.Attribute):
        return node.attr
    if isinstance(node, ast.Name):
        return node.id
    return None


def _is_log_call(node: ast.Call) -> bool:
    func = node.func
    if not isinstance(func, ast.Attribute) or func.attr not in _LOG_METHODS:
        return isinstance(func, ast.Name) and func.id == "print"
    base = func.value
    base_name = _terminal_name(base)
    return base_name is not None and (base_name in _LOG_OBJECTS
                                      or base_name.endswith("logger"))


class SecretHygieneRule(Rule):
    name = "secret-hygiene"
    description = ("secret-named values (api keys, bearer tokens, "
                   "passwords) passed to logging calls or interpolated "
                   "into logged f-strings")

    def check(self, tree: ast.Module, source: str,
              relpath: str) -> list[Finding]:
        findings: list[Finding] = []
        for node in ast.walk(tree):
            if isinstance(node, ast.Call) and _is_log_call(node):
                exprs = list(node.args)
                exprs += [kw.value for kw in node.keywords]
                for expr in exprs:
                    self._check_expr(expr, relpath, findings)
        return findings

    def _check_expr(self, expr: ast.AST, relpath: str,
                    findings: list[Finding]) -> None:
        for node, sanitized in _walk_sanitized(expr):
            if sanitized:
                continue
            name = _terminal_name(node)
            if name and _SECRET_RE.search(name):
                findings.append(self.finding(
                    relpath, node,
                    f"secret-named value {name!r} reaches a log call; log a "
                    f"masked form (cf. utils.logging_setup.mask_headers) "
                    f"or drop it"))


def _walk_sanitized(expr: ast.AST, sanitized: bool = False):
    """Yield (node, under_sanitizer) for every node, marking subtrees
    wrapped in a masking/redaction call as sanitized."""
    if isinstance(expr, ast.Call):
        func_name = _terminal_name(expr.func) or ""
        if _SANITIZER_RE.search(func_name):
            sanitized = True
    yield expr, sanitized
    for child in ast.iter_child_nodes(expr):
        yield from _walk_sanitized(child, sanitized)


RULE = SecretHygieneRule()
