"""device-sync-discipline: implicit device→host syncs on the event loop.

``async-blocking`` catches the classic blocking primitives, but the
device-sync family has quieter spellings this codebase actually uses:
``.block_until_ready()`` on an array, ``np.asarray(...)`` / ``np.array(...)``
of a JAX value (a synchronous device fetch), and ``float()``/``int()`` of a
device array. Any of these reachable from a serving-layer ``async def``
stalls every in-flight SSE stream for a device round trip — through a
remote-TPU tunnel that is tens of milliseconds per call, and through a
DEAD tunnel it is forever.

Some helpers sync *by design* (e.g. the engine's worker-thread fetch
paths reached via documented loop-side accessors that only touch host
mirrors). Those opt out with a ``# device-sync: ok`` marker on their
``def`` line (or within the signature) — the marker is the
documentation: it says a human has checked the receiver is host data or
the sync is intentional. The whole-program pass (analysis/program.py)
extends this rule transitively through sync helpers in ANY module using
the PR 5 call graph; functions dispatched to worker threads
(``asyncio.to_thread`` / ``run_in_executor`` / ``Thread(target=)``)
create no call edge, so worker-side fetch code is never flagged.
"""
from __future__ import annotations

import ast

from ..core import Finding, Rule
from ._util import call_name, references_module

_JAX_ROOTS = frozenset({"jax", "jnp"})
_NP_ROOTS = ("np", "numpy")

DEVICE_SYNC_OK_MARK = "device-sync: ok"


def classify_device_sync(node: ast.Call) -> str | None:
    """The message describing why this Call is (or may be) a device→host
    sync, or None. Shared with the whole-program pass so the lexical and
    transitive layers can never disagree."""
    name = call_name(node)
    if name == "jax.block_until_ready":
        return ("jax.block_until_ready() waits for the device on the "
                "event loop")
    if name == "jax.device_get":
        return "jax.device_get() is a synchronous device->host fetch"
    func = node.func
    if (isinstance(func, ast.Attribute)
            and func.attr == "block_until_ready"
            and not node.args and not node.keywords):
        return (".block_until_ready() waits for the device on the event "
                "loop")
    if (isinstance(func, ast.Attribute) and func.attr == "item"
            and not node.args and not node.keywords):
        return ".item() forces a device->host sync on the event loop"
    if (name is not None and "." in name
            and name.split(".")[0] in _NP_ROOTS
            and name.split(".")[-1] in ("asarray", "array")
            and node.args and references_module(node.args[0], _JAX_ROOTS)):
        return (f"{name}() of a JAX value is a synchronous device->host "
                f"fetch")
    if (isinstance(func, ast.Name) and func.id in ("float", "int")
            and node.args
            and references_module(node.args[0], _JAX_ROOTS)):
        return (f"{func.id}() of a JAX value is a synchronous "
                f"device->host fetch")
    return None


def sync_ok_marked(fn_node: ast.AST, lines: list[str]) -> bool:
    """True when the function carries the ``# device-sync: ok`` marker as
    a TRAILING comment on its ``def`` line or a later signature line
    (multi-line signatures work). Standalone comment lines are ignored —
    a comment *about* the marker between signature and body must not
    arm it."""
    body = getattr(fn_node, "body", None)
    last = max(fn_node.lineno, (body[0].lineno - 1) if body
               else fn_node.lineno)
    for ln in range(fn_node.lineno, last + 1):
        if ln > len(lines):
            break
        line = lines[ln - 1]
        if line.lstrip().startswith("#"):
            continue
        if DEVICE_SYNC_OK_MARK in line:
            return True
    return False


class DeviceSyncRule(Rule):
    name = "device-sync-discipline"
    description = ("implicit device->host syncs (.block_until_ready(), "
                   "np.asarray/float of JAX values) inside serving-layer "
                   "async defs; the whole-program pass extends this "
                   "transitively through sync helpers in any module — "
                   "documented helpers opt out with `# device-sync: ok` "
                   "on the def line")
    dirs = ("server", "routing", "providers")

    def check(self, tree: ast.Module, source: str,
              relpath: str) -> list[Finding]:
        lines = source.splitlines()
        findings: list[Finding] = []
        for node in ast.walk(tree):
            if isinstance(node, ast.AsyncFunctionDef):
                if sync_ok_marked(node, lines):
                    continue
                self._check_async_body(node, relpath, findings)
        return findings

    def _check_async_body(self, fn: ast.AsyncFunctionDef, relpath: str,
                          findings: list[Finding]) -> None:
        # Like async-blocking: skip nested SYNC defs (worker payloads);
        # nested async defs are still on the loop.
        stack: list[ast.AST] = list(ast.iter_child_nodes(fn))
        while stack:
            node = stack.pop()
            if isinstance(node, ast.FunctionDef):
                continue
            stack.extend(ast.iter_child_nodes(node))
            if isinstance(node, ast.Call):
                msg = classify_device_sync(node)
                if msg is not None:
                    findings.append(self.finding(
                        relpath, node,
                        f"{msg} — offload via asyncio.to_thread, or mark "
                        f"the helper `# device-sync: ok` if the receiver "
                        f"is host data"))


RULE = DeviceSyncRule()
