"""Shared AST helpers for graftlint rules."""
from __future__ import annotations

import ast
from typing import Iterator


def dotted_name(node: ast.AST) -> str | None:
    """``a.b.c`` for a Name/Attribute chain, else None (calls, subscripts
    and other dynamic roots don't resolve — rules treat that as unknown)."""
    parts: list[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return None


def call_name(node: ast.Call) -> str | None:
    return dotted_name(node.func)


def references_module(node: ast.AST, roots: frozenset[str]) -> bool:
    """True if any Name in the expression is one of ``roots`` (e.g. a
    ``jnp.``/``jax.`` usage inside a condition)."""
    return any(isinstance(n, ast.Name) and n.id in roots
               for n in ast.walk(node))


def contains_call_rooted_at(node: ast.AST, roots: frozenset[str]) -> bool:
    """True if the expression contains a Call whose function resolves to a
    dotted name rooted at one of ``roots`` (``jnp.any(x)``,
    ``jax.lax.cond(...)``)."""
    for n in ast.walk(node):
        if isinstance(n, ast.Call):
            name = call_name(n)
            if name and name.split(".")[0] in roots:
                return True
    return False


def walk_excluding(node: ast.AST, exclude: tuple[type, ...]) -> Iterator[ast.AST]:
    """Walk ``node``'s subtree, not descending into children whose type is
    in ``exclude`` (the node itself is always yielded first)."""
    yield node
    for child in ast.iter_child_nodes(node):
        if isinstance(child, exclude):
            continue
        yield from walk_excluding(child, exclude)


def self_attr(node: ast.AST) -> str | None:
    """``X`` for an ``self.X`` attribute node, else None."""
    if (isinstance(node, ast.Attribute)
            and isinstance(node.value, ast.Name)
            and node.value.id == "self"):
        return node.attr
    return None
