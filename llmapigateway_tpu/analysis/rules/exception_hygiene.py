"""exception-hygiene: swallowed errors in the serving-critical layers.

A silent ``except Exception: pass`` in the router's fallback loop, a
provider, or the engine turns a real failure (dead upstream, deleted
device buffer, poisoned cache) into a mystery the operator debugs from
symptom instead of cause — the gateway's reliability layer (breakers,
deadline 504s, typed overload shedding) only works when failures surface
as *classified* errors. The contract this rule pins for ``routing/``,
``providers/`` and ``engine/``:

* **no bare ``except:``** — it traps ``KeyboardInterrupt`` /
  ``SystemExit`` / ``asyncio.CancelledError`` and breaks cooperative
  cancellation (the local provider's cancel-on-disconnect path relies on
  CancelledError propagating).
* **no swallowed broad handlers** — ``except Exception`` (or
  ``BaseException``, alone or in a tuple) must do at least one of: log
  (any ``logger.*``/``logging.*`` call), re-raise, or convert to a typed
  error (construct something named ``*Error``/``*Overloaded``). A body of
  ``pass``/``...``/bare ``return``/``continue`` hides the failure.

Narrow handlers (``except httpx.TimeoutException``, ``except
sqlite3.Error``) are exempt: catching a *specific* exception is itself
the classification. Documented intentional swallows take a
``# graftlint: disable=exception-hygiene`` with a justification.
"""
from __future__ import annotations

import ast

from ..core import Finding, Rule
from ._util import dotted_name

_BROAD = frozenset({"Exception", "BaseException"})

_LOG_METHODS = frozenset({"debug", "info", "warning", "warn", "error",
                          "exception", "critical", "log"})


def _is_broad(handler_type: ast.AST | None) -> bool:
    if handler_type is None:
        return False
    if isinstance(handler_type, ast.Tuple):
        return any(_is_broad(e) for e in handler_type.elts)
    name = dotted_name(handler_type)
    return name is not None and name.split(".")[-1] in _BROAD


def _handles_the_error(handler: ast.ExceptHandler) -> bool:
    """True when the handler body logs, re-raises, or converts to a typed
    error somewhere in its subtree."""
    for node in ast.walk(handler):
        if isinstance(node, ast.Raise):
            return True
        if isinstance(node, ast.Call):
            func = node.func
            if isinstance(func, ast.Attribute) and func.attr in _LOG_METHODS:
                base = func.value
                base_name = (base.attr if isinstance(base, ast.Attribute)
                             else base.id if isinstance(base, ast.Name) else "")
                if base_name and ("log" in base_name.lower()
                                  or base_name == "logging"):
                    return True
            name = dotted_name(func)
            if name and (name.split(".")[-1].endswith("Error")
                         or name.split(".")[-1].endswith("Overloaded")):
                return True
    return False


class ExceptionHygieneRule(Rule):
    name = "exception-hygiene"
    description = ("no bare `except:`; `except Exception` in routing/, "
                   "providers/, engine/ must log, re-raise, or convert to "
                   "a typed *Error — silent swallows hide real failures "
                   "from the reliability layer")
    dirs = ("routing", "providers", "engine")

    def check(self, tree: ast.Module, source: str,
              relpath: str) -> list[Finding]:
        findings: list[Finding] = []
        for node in ast.walk(tree):
            if not isinstance(node, ast.ExceptHandler):
                continue
            if node.type is None:
                findings.append(self.finding(
                    relpath, node,
                    "bare `except:` traps KeyboardInterrupt/SystemExit/"
                    "CancelledError and breaks cooperative cancellation; "
                    "catch a specific exception (or `except Exception` "
                    "with logging)"))
                continue
            if _is_broad(node.type) and not _handles_the_error(node):
                findings.append(self.finding(
                    relpath, node,
                    "`except Exception` swallows the failure silently: "
                    "log it, re-raise, or convert it to a typed *Error so "
                    "the router/breaker layer can classify it"))
        return findings


RULE = ExceptionHygieneRule()
