"""graftlint rule registry.

Each rule module exposes a ``RULE`` singleton; the registry is the
ordered list the driver and CLI iterate. Adding a rule = adding a module
here — the fixture tests in tests/test_graftlint.py enforce that every
registered rule both fires on its known-bad snippet and stays silent on
its known-good one.
"""
from __future__ import annotations

from ..core import Rule
from .async_blocking import RULE as ASYNC_BLOCKING
from .device_sync import RULE as DEVICE_SYNC
from .exception_hygiene import RULE as EXCEPTION_HYGIENE
from .lifecycle_discipline import RULE as LIFECYCLE_DISCIPLINE
from .lock_discipline import RULE as LOCK_DISCIPLINE
from .metric_discipline import RULE as METRIC_DISCIPLINE
from .secret_hygiene import RULE as SECRET_HYGIENE
from .sse_protocol import RULE as SSE_PROTOCOL
from .timeout_discipline import RULE as TIMEOUT_DISCIPLINE
from .tracer_hazard import RULE as TRACER_HAZARD

ALL_RULES: tuple[Rule, ...] = (
    ASYNC_BLOCKING,
    TRACER_HAZARD,
    LOCK_DISCIPLINE,
    LIFECYCLE_DISCIPLINE,
    SECRET_HYGIENE,
    SSE_PROTOCOL,
    TIMEOUT_DISCIPLINE,
    METRIC_DISCIPLINE,
    EXCEPTION_HYGIENE,
    DEVICE_SYNC,
)

RULES_BY_NAME: dict[str, Rule] = {r.name: r for r in ALL_RULES}

__all__ = ["ALL_RULES", "RULES_BY_NAME"]
