"""timeout-discipline: every httpx request call in ``providers/`` must pass
an explicit ``timeout=``.

The reliability layer (ISSUE 3) caps each upstream attempt with the
request's remaining deadline budget via :func:`deadline_timeout`. That cap
only reaches the wire if the call site actually passes ``timeout=`` —
httpx's silent fallback is the client's construction-time default, and a
client built without one waits **5 s connect / 5 s read** per httpx's own
default, or forever under a misconfigured transport. One forgotten
``timeout=`` reintroduces exactly the unbounded-wait class of bug this PR
removes, so the lint pins it:

* ``<...client...>.get/post/put/patch/delete/request/stream/build_request``
  — flagged when no ``timeout=`` keyword is present. Receivers qualify when
  their terminal name contains ``client`` (``self._client``, ``client``,
  ``models_client``), which is the project convention for httpx handles —
  dict ``.get()`` and list ``.pop()`` never match.
* ``httpx.AsyncClient(...)`` / ``httpx.Client(...)`` — the pooled client's
  default timeout is the last line of defense; constructing one without
  ``timeout=`` (or ``transport=``-only test shims without it) is flagged.

``.send()`` is exempt: its timeout rides on the request object that
``build_request(..., timeout=...)`` (itself checked) produced.
"""
from __future__ import annotations

import ast

from ..core import Finding, Rule
from ._util import call_name

_HTTP_METHODS = frozenset({"get", "post", "put", "patch", "delete",
                           "request", "stream", "build_request"})
_CLIENT_CONSTRUCTORS = frozenset({"httpx.AsyncClient", "httpx.Client"})


def _terminal_receiver_name(func: ast.Attribute) -> str | None:
    """The name the method is called on: ``client`` for ``client.post``,
    ``_client`` for ``self._client.post``; None for dynamic receivers."""
    recv = func.value
    if isinstance(recv, ast.Attribute):
        return recv.attr
    if isinstance(recv, ast.Name):
        return recv.id
    return None


def _has_timeout_kw(node: ast.Call) -> bool:
    return any(kw.arg == "timeout" for kw in node.keywords)


class TimeoutDisciplineRule(Rule):
    name = "timeout-discipline"
    description = ("httpx request calls (and client constructors) in "
                   "providers/ must pass an explicit timeout= so deadline "
                   "caps reach the wire")
    dirs = ("providers",)

    def check(self, tree: ast.Module, source: str,
              relpath: str) -> list[Finding]:
        findings: list[Finding] = []
        for node in ast.walk(tree):
            if not isinstance(node, ast.Call):
                continue
            name = call_name(node)
            if name in _CLIENT_CONSTRUCTORS:
                if not _has_timeout_kw(node):
                    findings.append(self.finding(
                        relpath, node,
                        f"{name}(...) without timeout=: the pooled "
                        "client's default timeout is the last line of "
                        "defense against unbounded upstream waits"))
                continue
            func = node.func
            if not isinstance(func, ast.Attribute):
                continue
            if func.attr not in _HTTP_METHODS:
                continue
            recv = _terminal_receiver_name(func)
            if recv is None or "client" not in recv.lower():
                continue               # dict.get(), payload.get(), etc.
            if not _has_timeout_kw(node):
                findings.append(self.finding(
                    relpath, node,
                    f"httpx {func.attr}() without explicit timeout=: pass "
                    "deadline_timeout(request.deadline) (or a module "
                    "timeout constant) so the request's budget caps the "
                    "wire wait"))
        return findings


RULE = TimeoutDisciplineRule()
