"""tracer-hazard: host round-trips and Python control flow in traced code.

Inside a ``jax.jit``/``shard_map``/``lax.scan`` body every array is a
tracer: ``np.asarray``/``jax.device_get``/``.item()`` force a host sync
(or fail outright), and Python ``if``/``while``/``for`` over traced values
either raises a ConcretizationError or — worse — silently bakes one
branch into the compiled program and recompiles per shape. A hidden host
round-trip in the decode scan body is exactly the class of regression
that costs a benchmark round (DistServe-style decode loops only pay off
host-free, PAPERS.md), so this rule gates ``engine/`` and ``ops/``.

Detection is lexical: a function is considered traced when it is
decorated with jit/shard_map (directly or via ``functools.partial``), or
its name is passed to a ``jax.jit(...)`` / ``lax.scan(...)`` /
``shard_map(...)`` call in the same module. Branch/iteration hazards are
flagged only when the condition/iterable contains a ``jnp.``/``jax.``
*call* — branching on static Python config stays legal.
"""
from __future__ import annotations

import ast

from ..core import Finding, Rule
from ._util import call_name, contains_call_rooted_at

_JAX_ROOTS = frozenset({"jnp", "jax", "lax"})

# Call suffixes that mark the *wrapped function* as traced.
_TRACING_WRAPPERS = ("jit", "shard_map", "scan", "pmap", "vmap",
                     "while_loop", "fori_loop", "checkpoint", "remat")

_HOST_SYNC_CALLS = {
    "np.asarray": "np.asarray() inside a traced body forces a host sync "
                  "(or fails on a tracer); use jnp",
    "np.array": "np.array() inside a traced body forces a host sync "
                "(or fails on a tracer); use jnp",
    "onp.asarray": "host numpy call inside a traced body",
    "jax.device_get": "jax.device_get() inside a traced body is a host sync",
    "jax.block_until_ready":
        "jax.block_until_ready() inside a traced body is a host sync",
}


def _wrapper_suffix(name: str | None) -> bool:
    return bool(name) and name.split(".")[-1] in _TRACING_WRAPPERS


def _decorator_traces(dec: ast.AST) -> bool:
    """``@jax.jit``, ``@jit``, ``@partial(jax.jit, ...)``,
    ``@jax.jit(...)``, ``@shard_map(...)`` — all mark the def as traced."""
    if _wrapper_suffix(call_name(dec) if isinstance(dec, ast.Call)
                       else _dotted(dec)):
        return True
    if isinstance(dec, ast.Call):
        name = call_name(dec)
        if name and name.split(".")[-1] == "partial":
            return any(_wrapper_suffix(_dotted(a)) for a in dec.args)
    return False


def _dotted(node: ast.AST) -> str | None:
    from ._util import dotted_name
    return dotted_name(node)


class TracerHazardRule(Rule):
    name = "tracer-hazard"
    description = ("host syncs (np.asarray, device_get, .item()) and Python "
                   "branching/iteration on traced values inside "
                   "jit/shard_map/scan bodies in engine/ and ops/")
    dirs = ("engine", "ops")

    def check(self, tree: ast.Module, source: str,
              relpath: str) -> list[Finding]:
        traced_names = self._collect_traced_names(tree)
        findings: list[Finding] = []
        for node in ast.walk(tree):
            if not isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                continue
            if (node.name in traced_names
                    or any(_decorator_traces(d) for d in node.decorator_list)):
                self._check_traced_body(node, relpath, findings)
        return findings

    @staticmethod
    def _collect_traced_names(tree: ast.Module) -> set[str]:
        """Function names passed (as bare names) to jit/scan/shard_map
        calls anywhere in the module."""
        names: set[str] = set()
        for node in ast.walk(tree):
            if isinstance(node, ast.Call) and _wrapper_suffix(call_name(node)):
                for arg in list(node.args) + [k.value for k in node.keywords]:
                    if isinstance(arg, ast.Name):
                        names.add(arg.id)
        return names

    def _check_traced_body(self, fn: ast.AST, relpath: str,
                           findings: list[Finding]) -> None:
        for node in ast.walk(fn):
            if isinstance(node, ast.Call):
                name = call_name(node)
                if name in _HOST_SYNC_CALLS:
                    findings.append(self.finding(
                        relpath, node, _HOST_SYNC_CALLS[name]))
                elif (isinstance(node.func, ast.Attribute)
                        and node.func.attr == "item"
                        and not node.args and not node.keywords):
                    findings.append(self.finding(
                        relpath, node,
                        ".item() inside a traced body is a host sync"))
                elif (isinstance(node.func, ast.Name)
                        and node.func.id in ("float", "int", "bool")
                        and node.args
                        and contains_call_rooted_at(node.args[0], _JAX_ROOTS)):
                    findings.append(self.finding(
                        relpath, node,
                        f"{node.func.id}() of a traced value concretizes the "
                        "tracer (host sync / ConcretizationError)"))
            elif isinstance(node, (ast.If, ast.While)):
                if contains_call_rooted_at(node.test, _JAX_ROOTS):
                    kind = "if" if isinstance(node, ast.If) else "while"
                    findings.append(self.finding(
                        relpath, node,
                        f"Python `{kind}` on a traced value bakes one branch "
                        "into the compiled program; use jnp.where/lax.cond"))
            elif isinstance(node, ast.For):
                if contains_call_rooted_at(node.iter, _JAX_ROOTS):
                    findings.append(self.finding(
                        relpath, node,
                        "Python iteration over a traced value unrolls or "
                        "concretizes; use lax.scan/fori_loop"))


RULE = TracerHazardRule()
