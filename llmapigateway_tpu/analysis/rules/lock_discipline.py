"""lock-discipline: awaits under threading locks, and guarded-state checks.

Three checks, one rule:

1. **await under a threading lock** — ``await`` inside ``with
   self._lock:`` where ``_lock`` is a ``threading.Lock``/``RLock`` parks
   the *whole event loop* on a lock no coroutine can release; only
   ``asyncio.Lock`` may be held across awaits.

2. **guarded attribute mutated outside its lock** — attributes documented
   with a trailing ``# guarded-by: <lockname>`` comment on their
   initialization line must only be mutated (assignment, ``del``,
   subscript store, or a mutating method call — ``.append``/``.pop``/
   ``.update``/``.execute``/…) inside a ``with self.<lockname>`` /
   ``async with self.<lockname>`` block. ``__init__`` is exempt (the
   object hasn't escaped). Module-level globals guarded by module-level
   locks are checked the same way.

3. **loop-guarded attribute mutated on a worker thread** — ``# guarded-by:
   loop`` marks attributes that are event-loop-thread-only (asyncio
   queues/dicts are not thread-safe). The rule computes the set of
   methods reachable from ``asyncio.to_thread(self.X, ...)`` /
   ``threading.Thread(target=self.X)`` dispatch sites via the class's
   self-call graph and flags mutations of loop-guarded attributes there
   (engine.py's "worker-thread calls only touch device programs and host
   numpy state" invariant, made checkable).
"""
from __future__ import annotations

import ast
import re
from dataclasses import dataclass, field

from ..core import Finding, Rule
from ._util import call_name, self_attr

_GUARDED_RE = re.compile(r"#\s*guarded-by:\s*([\w\-]+)")

_MUTATORS = frozenset({
    "append", "extend", "insert", "add", "update", "setdefault", "pop",
    "popitem", "remove", "discard", "clear", "sort", "reverse",
    "put_nowait", "get_nowait", "set", "execute", "executemany",
    "executescript", "commit", "rollback", "close", "write",
})

_THREADING_LOCK_CTORS = {"threading.Lock", "threading.RLock",
                         "threading.Condition", "threading.Semaphore"}
_ASYNC_LOCK_CTORS = {"asyncio.Lock", "asyncio.Condition", "asyncio.Semaphore"}


@dataclass
class _ClassInfo:
    node: ast.ClassDef
    threading_locks: set[str] = field(default_factory=set)
    async_locks: set[str] = field(default_factory=set)
    guards: dict[str, str] = field(default_factory=dict)   # attr -> lock
    worker_entries: set[str] = field(default_factory=set)
    self_calls: dict[str, set[str]] = field(default_factory=dict)


class LockDisciplineRule(Rule):
    name = "lock-discipline"
    description = ("await while holding a threading.Lock; mutation of "
                   "`# guarded-by: <lock>` attributes outside their lock; "
                   "mutation of `# guarded-by: loop` attributes in "
                   "worker-thread-reachable methods")

    def check(self, tree: ast.Module, source: str,
              relpath: str) -> list[Finding]:
        lines = source.splitlines()
        findings: list[Finding] = []
        mod_locks, mod_guards = self._module_level(tree, lines)
        for node in ast.walk(tree):
            if isinstance(node, ast.ClassDef):
                info = self._scan_class(node, lines)
                self._check_class(info, mod_locks, relpath, findings)
        self._check_module_guards(tree, mod_locks, mod_guards, relpath,
                                  findings)
        # Check 1 also applies outside classes (module-level locks used in
        # free async functions).
        self._check_awaits_under_lock(tree, mod_locks, set(), relpath,
                                      findings)
        return findings

    # -- collection ----------------------------------------------------------
    @staticmethod
    def _guard_comment(lines: list[str], node: ast.AST) -> str | None:
        for ln in range(node.lineno, getattr(node, "end_lineno",
                                             node.lineno) + 1):
            if ln <= len(lines):
                m = _GUARDED_RE.search(lines[ln - 1])
                if m:
                    return m.group(1)
        return None

    def _module_level(self, tree: ast.Module,
                      lines: list[str]) -> tuple[set[str], dict[str, str]]:
        locks: set[str] = set()
        guards: dict[str, str] = {}
        for stmt in tree.body:
            targets: list[ast.AST] = []
            value = None
            if isinstance(stmt, ast.Assign):
                targets, value = stmt.targets, stmt.value
            elif isinstance(stmt, ast.AnnAssign) and stmt.value is not None:
                targets, value = [stmt.target], stmt.value
            for t in targets:
                if not isinstance(t, ast.Name):
                    continue
                ctor = call_name(value) if isinstance(value, ast.Call) else None
                if ctor in _THREADING_LOCK_CTORS:
                    locks.add(t.id)
                guard = self._guard_comment(lines, stmt)
                if guard:
                    guards[t.id] = guard
        return locks, guards

    def _scan_class(self, cls: ast.ClassDef, lines: list[str]) -> _ClassInfo:
        info = _ClassInfo(node=cls)
        for node in ast.walk(cls):
            if isinstance(node, (ast.Assign, ast.AnnAssign)):
                targets = (node.targets if isinstance(node, ast.Assign)
                           else [node.target])
                value = node.value
                for t in targets:
                    attr = self_attr(t)
                    if attr is None:
                        continue
                    ctor = (call_name(value)
                            if isinstance(value, ast.Call) else None)
                    if ctor in _THREADING_LOCK_CTORS:
                        info.threading_locks.add(attr)
                    elif ctor in _ASYNC_LOCK_CTORS:
                        info.async_locks.add(attr)
                    guard = self._guard_comment(lines, node)
                    if guard:
                        info.guards[attr] = guard
            elif isinstance(node, ast.Call):
                self._collect_worker_entry(node, info)
        # Self-call graph per method (for the `loop` guard closure).
        for stmt in cls.body:
            if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef)):
                calls = {self_attr(n.func)
                         for n in ast.walk(stmt)
                         if isinstance(n, ast.Call)
                         and self_attr(n.func) is not None}
                info.self_calls[stmt.name] = {c for c in calls if c}
        return info

    @staticmethod
    def _collect_worker_entry(node: ast.Call, info: _ClassInfo) -> None:
        name = call_name(node)
        if name and name.split(".")[-1] == "to_thread" and node.args:
            attr = self_attr(node.args[0])
            if attr:
                info.worker_entries.add(attr)
        if name and name.split(".")[-1] == "Thread":
            for kw in node.keywords:
                if kw.arg == "target":
                    attr = self_attr(kw.value)
                    if attr:
                        info.worker_entries.add(attr)

    # -- checks --------------------------------------------------------------
    def _check_class(self, info: _ClassInfo, mod_locks: set[str],
                     relpath: str, findings: list[Finding]) -> None:
        # Module-level locks are covered by the module-wide pass; here only
        # the class's own `self.<lock>` attributes (no double reports).
        self._check_awaits_under_lock(
            info.node, set(), info.threading_locks, relpath, findings)

        lock_guards = {a: l for a, l in info.guards.items() if l != "loop"}
        loop_guards = {a for a, l in info.guards.items() if l == "loop"}

        for stmt in info.node.body:
            if not isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef)):
                continue
            if stmt.name == "__init__":
                continue        # object hasn't escaped; no lock needed yet
            self._check_guarded_mutations(
                stmt, lock_guards, is_self=True, relpath=relpath,
                findings=findings)

        if loop_guards:
            reachable = self._worker_reachable(info)
            for stmt in info.node.body:
                if (isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef))
                        and stmt.name in reachable):
                    for node, attr in self._mutations(stmt, is_self=True):
                        if attr in loop_guards:
                            findings.append(self.finding(
                                relpath, node,
                                f"self.{attr} is `guarded-by: loop` "
                                f"(event-loop thread only) but is mutated in "
                                f"worker-thread-reachable method "
                                f"{stmt.name}()"))

    @staticmethod
    def _worker_reachable(info: _ClassInfo) -> set[str]:
        seen: set[str] = set()
        frontier = [m for m in info.worker_entries if m in info.self_calls]
        while frontier:
            m = frontier.pop()
            if m in seen:
                continue
            seen.add(m)
            frontier.extend(c for c in info.self_calls.get(m, ())
                            if c in info.self_calls and c not in seen)
        return seen

    def _check_awaits_under_lock(self, root: ast.AST, mod_locks: set[str],
                                 self_locks: set[str], relpath: str,
                                 findings: list[Finding]) -> None:
        for node in ast.walk(root):
            if not isinstance(node, ast.With):
                continue
            if not any(self._is_threading_lock(item.context_expr, mod_locks,
                                               self_locks)
                       for item in node.items):
                continue
            for inner in ast.walk(node):
                if isinstance(inner, ast.Await):
                    findings.append(self.finding(
                        relpath, inner,
                        "await while holding a threading.Lock parks the "
                        "event loop on a lock no coroutine can release; "
                        "use asyncio.Lock or release before awaiting"))

    @staticmethod
    def _is_threading_lock(expr: ast.AST, mod_locks: set[str],
                           self_locks: set[str]) -> bool:
        attr = self_attr(expr)
        if attr is not None:
            return attr in self_locks
        return isinstance(expr, ast.Name) and expr.id in mod_locks

    def _check_module_guards(self, tree: ast.Module, mod_locks: set[str],
                             mod_guards: dict[str, str], relpath: str,
                             findings: list[Finding]) -> None:
        if not mod_guards:
            return
        for node in ast.walk(tree):
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                self._check_guarded_mutations(
                    node, mod_guards, is_self=False, relpath=relpath,
                    findings=findings)

    def _check_guarded_mutations(self, fn: ast.AST, guards: dict[str, str],
                                 *, is_self: bool, relpath: str,
                                 findings: list[Finding]) -> None:
        """Flag mutations of guarded targets in ``fn`` that have no
        enclosing ``with <lock>`` block naming the documented lock."""
        if not guards:
            return
        held_stack: list[set[str]] = [set()]

        def locks_of(with_node) -> set[str]:
            out = set()
            for item in with_node.items:
                name = (self_attr(item.context_expr) if is_self
                        else (item.context_expr.id
                              if isinstance(item.context_expr, ast.Name)
                              else None))
                if name:
                    out.add(name)
            return out

        def visit(node: ast.AST) -> None:
            if isinstance(node, (ast.With, ast.AsyncWith)):
                held_stack.append(held_stack[-1] | locks_of(node))
                for child in ast.iter_child_nodes(node):
                    visit(child)
                held_stack.pop()
                return
            for mnode, attr in self._direct_mutations(node, is_self=is_self):
                lock = guards.get(attr)
                if lock and lock != "loop" and lock not in held_stack[-1]:
                    target = f"self.{attr}" if is_self else attr
                    findings.append(self.finding(
                        relpath, mnode,
                        f"{target} is `guarded-by: {lock}` but is mutated "
                        f"outside a `with {'self.' if is_self else ''}{lock}` "
                        f"block"))
            for child in ast.iter_child_nodes(node):
                visit(child)

        for child in ast.iter_child_nodes(fn):
            visit(child)

    def _mutations(self, fn: ast.AST, *, is_self: bool):
        for node in ast.walk(fn):
            yield from self._direct_mutations(node, is_self=is_self)

    @staticmethod
    def _direct_mutations(node: ast.AST, *, is_self: bool):
        """(node, attr) pairs for mutations performed *by this node itself*
        (not its subtree): assignment/del of the target or a subscript of
        it, augmented assignment, or a mutating method call on it."""
        def target_name(expr: ast.AST) -> str | None:
            if isinstance(expr, ast.Subscript):
                expr = expr.value
            if is_self:
                return self_attr(expr)
            return expr.id if isinstance(expr, ast.Name) else None

        if isinstance(node, (ast.Assign, ast.AnnAssign, ast.AugAssign)):
            targets = (node.targets if isinstance(node, ast.Assign)
                       else [node.target])
            for t in targets:
                name = target_name(t)
                if name:
                    yield node, name
        elif isinstance(node, ast.Delete):
            for t in node.targets:
                name = target_name(t)
                if name:
                    yield node, name
        elif (isinstance(node, ast.Call)
                and isinstance(node.func, ast.Attribute)
                and node.func.attr in _MUTATORS):
            name = target_name(node.func.value)
            if name:
                yield node, name


RULE = LockDisciplineRule()
