"""lifecycle-discipline: engine lifecycle state changes only through the
state-machine API (ISSUE 14).

The engine supervisor's ``_lc_state`` attribute is the single source of
truth for "what state is this engine in" — serving, draining,
restarting, failed. Every consumer (admission gate, watchdog, breaker
failover, /metrics, flight records) keys off it, and the transition
table in ``reliability/supervisor.py`` is what makes illegal edges
(``failed → serving`` without a stop) impossible.

A direct write — ``engine.supervisor._lc_state = "serving"`` in a
recovery path, or ``setattr(sup, "_lc_state", ...)`` in a test helper —
bypasses the table, the transition history, the flight-ring echo, and
the drain bookkeeping at once: the engine would *be* in a state it
never *entered*. This rule pins all ``_lc_state`` stores to the
supervisor module itself (where ``__init__`` seeds it and
``transition()`` validates every edge); everyone else must call
``transition()``.
"""
from __future__ import annotations

import ast

from ..core import Finding, Rule
from ._util import call_name

# The one attribute the supervisor state machine owns, and the one
# module allowed to store to it.
_STATE_ATTR = "_lc_state"
_OWNER_MODULE = "reliability/supervisor.py"


class LifecycleDisciplineRule(Rule):
    name = "lifecycle-discipline"
    description = ("engine lifecycle state (`_lc_state`) may only be "
                   "written inside reliability/supervisor.py — every "
                   "other module must go through "
                   "EngineSupervisor.transition()")

    def check(self, tree: ast.Module, source: str,
              relpath: str) -> list[Finding]:
        if relpath.endswith(_OWNER_MODULE):
            return []
        findings: list[Finding] = []
        for node in ast.walk(tree):
            targets: list[ast.expr] = []
            if isinstance(node, ast.Assign):
                targets = node.targets
            elif isinstance(node, (ast.AnnAssign, ast.AugAssign)):
                targets = [node.target]
            elif isinstance(node, ast.Call):
                # setattr(sup, "_lc_state", ...) is the same store in a
                # trench coat.
                if (call_name(node) == "setattr" and len(node.args) >= 2
                        and isinstance(node.args[1], ast.Constant)
                        and node.args[1].value == _STATE_ATTR):
                    findings.append(self.finding(
                        relpath, node,
                        "setattr on '_lc_state' bypasses the lifecycle "
                        "state machine — use "
                        "EngineSupervisor.transition()"))
                continue
            else:
                continue
            for tgt in targets:
                if (isinstance(tgt, ast.Attribute)
                        and tgt.attr == _STATE_ATTR):
                    findings.append(self.finding(
                        relpath, node,
                        "direct write to '_lc_state' bypasses the "
                        "lifecycle state machine (transition table, "
                        "history, flight-ring echo) — use "
                        "EngineSupervisor.transition()"))
        return findings


RULE = LifecycleDisciplineRule()
