"""sse-protocol: malformed Server-Sent-Events frames at yield sites.

Every byte the streaming path yields must be a complete SSE event:
``data: ``-framed lines terminated by a blank line (``\\n\\n``). A frame
missing its terminator silently concatenates with the next event in the
client's parser; a bare payload line (no ``data: `` prefix) is dropped by
conforming clients — both are protocol corruptions that no test notices
until a real OpenAI-client consumer hangs. The SSE spec also allows
``:`` comment lines (keep-alives) and ``event:``/``id:``/``retry:``
fields, so those pass.

Checked statically where it's checkable: yields of string/bytes
*literals*, f-strings, and ``"...".encode()`` in the streaming modules
(``utils/sse.py``, ``server/chat.py``, ``providers/local.py``,
``providers/remote_http.py``). Yields of names and non-literal calls pass
— ``format_sse(...)`` is the one sanctioned frame constructor and
dynamic values can't be verified lexically.
"""
from __future__ import annotations

import ast

from ..core import Finding, Rule

_FIELD_PREFIXES = ("data:", "event:", "id:", "retry:", ":")


def _frame_problem(text: str) -> str | None:
    """None if ``text`` is a well-formed complete SSE event, else why not."""
    if not text.endswith("\n\n"):
        return ("SSE event is not terminated by a blank line (must end "
                "with \\n\\n)")
    body = text[:-2]
    for line in body.split("\n"):
        if line and not line.startswith(_FIELD_PREFIXES):
            return (f"SSE line {line.split(chr(10))[0][:40]!r} has no "
                    f"'data: ' (or other field) framing; conforming "
                    f"clients drop it")
    if not any(line.startswith(("data:", ":")) for line in body.split("\n")):
        return "SSE event carries no 'data:' line"
    return None


def _literal_text(node: ast.AST) -> str | None:
    """The static text of a yield value, where one exists: a str/bytes
    constant, a ``"...".encode()`` call, or an f-string with literal
    framing (interpolated spans count as opaque payload)."""
    if isinstance(node, ast.Constant):
        if isinstance(node.value, bytes):
            try:
                return node.value.decode("utf-8")
            except UnicodeDecodeError:
                return None
        if isinstance(node.value, str):
            return node.value
        return None
    if (isinstance(node, ast.Call) and isinstance(node.func, ast.Attribute)
            and node.func.attr == "encode"):
        return _literal_text(node.func.value)
    if isinstance(node, ast.JoinedStr):
        parts = []
        for v in node.values:
            if isinstance(v, ast.Constant) and isinstance(v.value, str):
                parts.append(v.value)
            else:
                parts.append("x")       # opaque interpolation, payload-safe
        return "".join(parts)
    return None


class SSEProtocolRule(Rule):
    name = "sse-protocol"
    description = ("yield sites in the streaming path emitting events "
                   "without 'data: ' framing or the blank-line terminator")
    files = ("utils/sse.py", "server/chat.py", "providers/local.py",
             "providers/remote_http.py")

    def check(self, tree: ast.Module, source: str,
              relpath: str) -> list[Finding]:
        findings: list[Finding] = []
        for node in ast.walk(tree):
            if not isinstance(node, ast.Yield) or node.value is None:
                continue
            text = _literal_text(node.value)
            if text is None:
                continue        # names/calls: format_sse et al., unverifiable
            problem = _frame_problem(text)
            if problem:
                findings.append(self.finding(relpath, node, problem))
        return findings


RULE = SSEProtocolRule()
