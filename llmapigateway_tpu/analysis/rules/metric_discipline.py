"""metric-discipline: metric naming and span-lifecycle invariants for the
observability plane (ISSUE 4).

Two checks:

* **Metric names.** Every instrument registered via
  ``<registry>.counter("name", ...)`` / ``.gauge(`` / ``.histogram(`` (first
  argument a string literal) must be snake_case and end with a unit suffix
  — ``_seconds``, ``_bytes``, ``_total``, or ``_ratio``. Unit-suffixed
  names are what make the one-scrape exposition legible (a bare
  ``gateway_latency`` tells an operator nothing about ms vs s) and keep
  PromQL aggregations dimensionally sane.

* **Span lifecycle.** Spans may only be opened through the context-manager
  API (``with span(...)``) — a bare ``begin_span(`` call outside
  ``obs/trace.py`` has no paired close on the exception path, and a leaked
  open span turns every downstream trace read into a lie. The tracer's own
  module is exempt: it is where the context manager (and the post-hoc
  ``record_span``) are built from the primitive.
"""
from __future__ import annotations

import ast
import re

from ..core import Finding, Rule
from ._util import call_name

_FACTORY_METHODS = frozenset({"counter", "gauge", "histogram"})
_UNIT_SUFFIXES = ("_seconds", "_bytes", "_total", "_ratio")
_SNAKE_RE = re.compile(r"[a-z][a-z0-9_]*\Z")

# The one module allowed to touch the span primitive.
_TRACE_MODULE = "obs/trace.py"


class MetricDisciplineRule(Rule):
    name = "metric-discipline"
    description = ("metric names must be snake_case with a unit suffix "
                   "(_seconds/_bytes/_total/_ratio); spans open only via "
                   "the context-manager API (no bare begin_span() calls)")

    def check(self, tree: ast.Module, source: str,
              relpath: str) -> list[Finding]:
        findings: list[Finding] = []
        for node in ast.walk(tree):
            if not isinstance(node, ast.Call):
                continue
            func = node.func
            # -- bare begin_span( anywhere outside the tracer module ------
            called = func.attr if isinstance(func, ast.Attribute) else (
                func.id if isinstance(func, ast.Name) else None)
            if called == "begin_span" and relpath != _TRACE_MODULE:
                findings.append(self.finding(
                    relpath, node,
                    "bare begin_span() call: open spans via the context "
                    "manager (`with span(...):`) so they cannot leak "
                    "unclosed past an exception"))
                continue
            # -- instrument registration naming ---------------------------
            if (isinstance(func, ast.Attribute)
                    and func.attr in _FACTORY_METHODS
                    and node.args
                    and isinstance(node.args[0], ast.Constant)
                    and isinstance(node.args[0].value, str)):
                metric_name = node.args[0].value
                if not _SNAKE_RE.fullmatch(metric_name):
                    findings.append(self.finding(
                        relpath, node,
                        f"metric name {metric_name!r} is not snake_case "
                        "([a-z][a-z0-9_]*)"))
                elif not metric_name.endswith(_UNIT_SUFFIXES):
                    findings.append(self.finding(
                        relpath, node,
                        f"metric name {metric_name!r} lacks a unit suffix "
                        f"({', '.join(_UNIT_SUFFIXES)}) — name the unit so "
                        "the exposition and PromQL stay dimensionally "
                        "sane"))
        return findings


RULE = MetricDisciplineRule()
