"""Incremental analysis cache: mtime + content-hash keyed per file.

The tier-1 gate runs graftlint over the whole package on every test
session, and the ``--changed`` pre-commit mode re-lints on every commit;
both would otherwise re-parse ~120 files to re-derive results that almost
never change. The cache stores, per file, the two things that are
expensive to recompute: the *lexical findings* (per-file rules) and the
*module summary* (the whole-program pass's input, analysis/program.py) —
so a warm run parses only files whose content actually changed and the
interprocedural pass runs over cached summaries.

Validation is two-tier: a matching ``mtime_ns`` is a hit without even
reading the file; a changed mtime falls back to the sha256 of the content
(rebuilds, ``git checkout`` round-trips, and touch(1) don't invalidate).
The cache key folds in the rule names and a schema version, so adding a
rule or changing the summary format invalidates everything at once
instead of serving stale results.
"""
from __future__ import annotations

import hashlib
import json
from pathlib import Path
from typing import Any

from .core import ChainHop, Finding
from .program import SUMMARY_VERSION

CACHE_SCHEMA = 1


def _finding_from_dict(d: dict[str, Any]) -> Finding:
    return Finding(rule=d["rule"], path=d["path"], line=d["line"],
                   col=d["col"], message=d["message"],
                   chain=tuple(ChainHop(h["path"], h["line"], h["note"])
                               for h in d.get("chain", ())))


class LintCache:
    """One JSON file mapping relpath → {mtime_ns, sha256, findings,
    summary}. Load once, :meth:`save` once at the end of a run."""

    def __init__(self, path: str | Path, rule_names: tuple[str, ...] = ()):
        self.path = Path(path)
        self.key = f"{CACHE_SCHEMA}/{SUMMARY_VERSION}/" + ",".join(sorted(rule_names))
        self._files: dict[str, dict[str, Any]] = {}
        self.hits = 0
        self.misses = 0
        self._dirty = False
        try:
            doc = json.loads(self.path.read_text())
            if doc.get("key") == self.key:
                self._files = doc.get("files", {})
        except (OSError, ValueError):
            pass

    # -- lookup -------------------------------------------------------------
    def lookup(self, file_path: Path, relpath: str
               ) -> tuple[list[Finding], dict[str, Any] | None, str | None] | None:
        """(findings, summary, source_or_None) on a hit, else None. Source
        is returned only when the hash fallback had to read the file — the
        caller reuses it instead of reading twice."""
        entry = self._files.get(relpath)
        if entry is None:
            self.misses += 1
            return None
        try:
            mtime_ns = file_path.stat().st_mtime_ns
        except OSError:
            self.misses += 1
            return None
        source: str | None = None
        if entry["mtime_ns"] != mtime_ns:
            try:
                source = file_path.read_text()
            except OSError:
                self.misses += 1
                return None
            if hashlib.sha256(source.encode()).hexdigest() != entry["sha256"]:
                self.misses += 1
                return None
            entry["mtime_ns"] = mtime_ns      # content same: refresh mtime
            self._dirty = True
        self.hits += 1
        findings = [_finding_from_dict(d) for d in entry["findings"]]
        return findings, entry.get("summary"), source

    # -- store --------------------------------------------------------------
    def store(self, file_path: Path, relpath: str, source: str,
              findings: list[Finding], summary: dict[str, Any] | None) -> None:
        try:
            mtime_ns = file_path.stat().st_mtime_ns
        except OSError:
            return
        self._files[relpath] = {
            "mtime_ns": mtime_ns,
            "sha256": hashlib.sha256(source.encode()).hexdigest(),
            "findings": [f.to_dict() for f in findings],
            "summary": summary,
        }
        self._dirty = True

    def save(self) -> None:
        if not self._dirty:
            return
        doc = {"key": self.key, "files": self._files}
        try:
            self.path.parent.mkdir(parents=True, exist_ok=True)
            tmp = self.path.with_suffix(self.path.suffix + ".tmp")
            tmp.write_text(json.dumps(doc))
            tmp.replace(self.path)
        except OSError:
            pass                    # cache is an optimization only

    def __len__(self) -> int:
        return len(self._files)
