"""graftlint reporters: human text, machine JSON, and SARIF 2.1.0.

SARIF is the interchange format code-review tooling actually ingests
(GitHub code scanning, VS Code SARIF viewer, tools/lint_report.py):
interprocedural findings ship their call chains both as
``relatedLocations`` (every file:line hop, clickable) and as a
``codeFlows`` thread flow (the ordered path a viewer can step through).
"""
from __future__ import annotations

import json
from collections import Counter
from typing import Iterable

from .core import Finding

SARIF_VERSION = "2.1.0"
SARIF_SCHEMA = ("https://raw.githubusercontent.com/oasis-tcs/sarif-spec/"
                "master/Schemata/sarif-schema-2.1.0.json")


def render_text(findings: list[Finding], *, checked_files: int) -> str:
    lines = [f.render() for f in findings]
    by_rule = Counter(f.rule for f in findings)
    if findings:
        summary = ", ".join(f"{rule}: {n}" for rule, n in sorted(by_rule.items()))
        lines.append(f"graftlint: {len(findings)} finding(s) in "
                     f"{checked_files} file(s) ({summary})")
    else:
        lines.append(f"graftlint: clean ({checked_files} file(s) checked)")
    return "\n".join(lines)


def render_json(findings: list[Finding], *, checked_files: int) -> str:
    return json.dumps({
        "findings": [f.to_dict() for f in findings],
        "count": len(findings),
        "checked_files": checked_files,
    }, indent=2)


def _sarif_location(path: str, line: int, col: int = 0,
                    message: str | None = None) -> dict:
    loc: dict = {"physicalLocation": {
        "artifactLocation": {"uri": path},
        "region": {"startLine": max(1, line),
                   "startColumn": max(1, col + 1)}}}
    if message:
        loc["message"] = {"text": message}
    return loc


def render_sarif(findings: list[Finding], *, checked_files: int,
                 rules: Iterable = ()) -> str:
    rule_meta = [{"id": r.name,
                  "shortDescription": {"text": r.description}}
                 for r in rules]
    known_ids = {r["id"] for r in rule_meta}
    for f in findings:                      # meta-rules (parse-error etc.)
        if f.rule not in known_ids:
            known_ids.add(f.rule)
            rule_meta.append({"id": f.rule,
                              "shortDescription": {"text": f.rule}})
    results = []
    for f in findings:
        res: dict = {
            "ruleId": f.rule,
            "level": "error",
            "message": {"text": f.message},
            "locations": [_sarif_location(f.path, f.line, f.col)],
        }
        if f.chain:
            res["relatedLocations"] = [
                _sarif_location(h.path, h.line, message=h.note)
                for h in f.chain]
            res["codeFlows"] = [{"threadFlows": [{"locations": [
                {"location": _sarif_location(h.path, h.line, message=h.note)}
                for h in f.chain]}]}]
        results.append(res)
    doc = {
        "$schema": SARIF_SCHEMA,
        "version": SARIF_VERSION,
        "runs": [{
            "tool": {"driver": {
                "name": "graftlint",
                "informationUri":
                    "https://llmapigateway-tpu.local/tools/README.md",
                "rules": rule_meta,
            }},
            "properties": {"checkedFiles": checked_files},
            "results": results,
        }],
    }
    return json.dumps(doc, indent=2)


def render_rules(rules: Iterable) -> str:
    out = []
    for r in rules:
        scope = (", ".join(r.dirs + r.files) or "whole package")
        out.append(f"{r.name}  [{scope}]\n    {r.description}")
    return "\n".join(out)
