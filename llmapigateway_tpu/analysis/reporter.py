"""graftlint reporters: human text and machine JSON."""
from __future__ import annotations

import json
from collections import Counter
from typing import Iterable

from .core import Finding


def render_text(findings: list[Finding], *, checked_files: int) -> str:
    lines = [f.render() for f in findings]
    by_rule = Counter(f.rule for f in findings)
    if findings:
        summary = ", ".join(f"{rule}: {n}" for rule, n in sorted(by_rule.items()))
        lines.append(f"graftlint: {len(findings)} finding(s) in "
                     f"{checked_files} file(s) ({summary})")
    else:
        lines.append(f"graftlint: clean ({checked_files} file(s) checked)")
    return "\n".join(lines)


def render_json(findings: list[Finding], *, checked_files: int) -> str:
    return json.dumps({
        "findings": [f.to_dict() for f in findings],
        "count": len(findings),
        "checked_files": checked_files,
    }, indent=2)


def render_rules(rules: Iterable) -> str:
    out = []
    for r in rules:
        scope = (", ".join(r.dirs + r.files) or "whole package")
        out.append(f"{r.name}  [{scope}]\n    {r.description}")
    return "\n".join(out)
