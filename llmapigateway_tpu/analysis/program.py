"""graftlint v2 whole-program core: symbol table, call graph, dataflow.

The per-file rules (rules/) see one module at a time, so one transitive
call through a sync helper in another module defeats every one of them: an
``async def`` handler that calls ``ConfigLoader.read_raw`` blocks the
event loop on ``Path.read_text`` two files away, a free function that
mutates a ``# guarded-by:``-annotated attribute it received as a parameter
escapes the lock check, and an httpx client handed to a helper loses its
``timeout=`` discipline at the project boundary. This module closes that
gap with a project-wide pass:

* **Symbol table + call graph.** Every module is summarized once
  (:func:`summarize_module`) into a JSON-serializable record of its
  functions (incl. nested defs and methods), the calls each makes, direct
  blocking primitives, guarded-attribute accesses, thread-dispatch sites,
  and httpx usage. Summaries are what the incremental cache
  (analysis/cache.py) stores — an unchanged file is never re-parsed.
  :class:`Program` links summaries into a cross-module call graph: bare
  names resolve through lexical scope then imports (relative and
  absolute), ``self.X`` through the enclosing class, ``Cls.method``
  through imported classes, and otherwise-unresolvable method calls
  devirtualize by *project-unique method name* (a method name defined by
  exactly one class in the tree, excluding ubiquitous container/stdlib
  names) — the cheap trick that makes ``gw.loader.read_raw(...)`` resolve
  without a type system.

* **async-blocking, transitive.** From every ``async def`` in the serving
  layers (server/, routing/, providers/), a BFS over *call* edges (a
  function passed by reference to ``asyncio.to_thread`` /
  ``run_in_executor`` / ``Thread(target=...)`` creates no edge — that is
  the sanctioned offload) finds the shortest chain to a function that
  performs a blocking primitive. The finding carries every file:line hop.
  Depth-0 (the primitive lexically inside the entry) is the per-file
  rule's business and is not re-reported.

* **lock-discipline, inferred.** ``# guarded-by:`` annotations are
  collected across the whole tree into a class→attr→guard index. Two
  whole-program checks: (1) *external access* — code outside the owning
  class that reads or mutates a guarded attribute through a parameter
  annotated with the class must hold the declared lock; (2) *thread
  reachability* — any access to a ``guarded-by: loop`` attribute in a
  function reachable (through the whole-program call graph) from a
  thread-dispatch site is flagged with the dispatch chain.

* **timeout-discipline, dataflow.** httpx clients (``httpx.AsyncClient``
  constructions and ``*client*``-named handles) passed as arguments from
  providers/ are tracked through function parameters to a fixpoint; an
  HTTP-method call on a tainted parameter without ``timeout=`` is flagged
  wherever it lives, chain attached.

Findings reuse the per-file rule names (``async-blocking``,
``lock-discipline``, ``timeout-discipline``) so one suppression syntax
covers both layers; ``# graftlint: disable=`` comments in the flagged file
apply exactly as they do for lexical findings.
"""
from __future__ import annotations

import ast
import re
from collections import deque
from pathlib import Path
from typing import Any, Iterable

from .core import ChainHop, Finding, Suppressions, iter_python_files, package_relpath
from .rules._util import dotted_name
from .rules.async_blocking import classify_blocking_call
from .rules.device_sync import classify_device_sync, sync_ok_marked
from .rules.lock_discipline import _GUARDED_RE, _MUTATORS

SUMMARY_VERSION = 4

# Entry scope for the transitive async-blocking pass (matches the lexical
# rule's dirs) and for the timeout dataflow seed.
SERVING_DIRS = ("server", "routing", "providers")
PROVIDER_DIRS = ("providers",)

# Method names never devirtualized by uniqueness: they collide with
# builtin container/stdlib methods, so an attribute call with this name is
# far more likely a dict/list/Path/logger/re/np operation than the one
# project method that happens to share it.
_DEVIRT_DENY = frozenset({
    "get", "put", "pop", "close", "open", "read", "write", "send", "recv",
    "update", "items", "keys", "values", "append", "extend", "insert",
    "remove", "clear", "copy", "sort", "reverse", "index", "count",
    "encode", "decode", "join", "split", "strip", "format", "add",
    "discard", "setdefault", "popitem", "run", "start", "stop", "wait",
    "set", "release", "acquire", "cancel", "done", "result", "exception",
    "flush", "seek", "tell", "readline", "readlines", "writelines",
    "submit", "apply", "mkdir", "exists", "unlink", "glob", "resolve",
    "info", "debug", "warning", "error", "critical", "log", "observe",
    "inc", "dec", "labels", "feed", "match", "search", "sub", "findall",
    "group", "loads", "dumps", "load", "dump", "sleep", "connect",
    "execute", "commit", "rollback", "fetchone", "fetchall", "item",
    "tolist", "astype", "reshape", "mean", "sum", "any", "all", "min",
    "max", "next", "name", "total", "render", "check", "empty", "qsize",
})

_HTTP_METHODS = frozenset({"get", "post", "put", "patch", "delete",
                           "request", "stream", "build_request"})
_THREAD_DISPATCH = frozenset({"to_thread", "run_in_executor"})

_LOCK_CTORS = frozenset({
    "threading.Lock", "threading.RLock", "threading.Condition",
    "threading.Semaphore", "asyncio.Lock", "asyncio.Condition",
    "asyncio.Semaphore"})

PACKAGE_NAME = "llmapigateway_tpu"


def _module_name(relpath: str) -> str:
    """Dotted module name for a package-relative path: ``server/app.py`` →
    ``server.app``; ``__init__.py`` files name their package."""
    parts = relpath[:-3].split("/") if relpath.endswith(".py") else relpath.split("/")
    if parts and parts[-1] == "__init__":
        parts = parts[:-1]
    return ".".join(parts)


def _is_client_expr(node: ast.AST) -> bool:
    """True for expressions that are httpx clients by project convention:
    a ``httpx.AsyncClient(...)``/``httpx.Client(...)`` construction or a
    name/attribute whose terminal name contains ``client``."""
    if isinstance(node, ast.Call):
        name = dotted_name(node.func)
        return name in ("httpx.AsyncClient", "httpx.Client")
    name = dotted_name(node)
    if name is None:
        return False
    return "client" in name.split(".")[-1].lower()


class _FnCollector(ast.NodeVisitor):
    """Summarizes one function body (NOT descending into nested defs —
    each nested def is its own function record)."""

    def __init__(self, summ: "_FnSummary", class_name: str | None,
                 param_types: dict[str, str], lines: list[str]):
        self.s = summ
        self.class_name = class_name
        self.param_types = dict(param_types)    # name -> annotated class
        self.lines = lines
        self.lock_stack: list[list[str]] = [[]]
        self._local_ctor: dict[str, str] = {}   # local -> ClassName(...)

    # -- nested defs are separate records -------------------------------
    def visit_FunctionDef(self, node: ast.FunctionDef) -> None:
        pass

    def visit_AsyncFunctionDef(self, node: ast.AsyncFunctionDef) -> None:
        pass

    def visit_Lambda(self, node: ast.Lambda) -> None:
        pass

    # -- with blocks track held locks ------------------------------------
    def _with_locks(self, node: ast.With | ast.AsyncWith) -> list[str]:
        held = []
        for item in node.items:
            name = dotted_name(item.context_expr)
            if name:
                held.append(name)       # "self._lock", "loader._lock", "_lock"
        return held

    def visit_With(self, node: ast.With) -> None:
        self.lock_stack.append(self.lock_stack[-1] + self._with_locks(node))
        self.generic_visit(node)
        self.lock_stack.pop()

    visit_AsyncWith = visit_With

    # -- receivers --------------------------------------------------------
    def _recv_class(self, node: ast.AST) -> tuple[str, str] | None:
        """(receiver_name, class) when the expression is a name/``self``
        with an inferable project class."""
        if isinstance(node, ast.Name):
            cls = self.param_types.get(node.id) or self._local_ctor.get(node.id)
            if cls:
                return node.id, cls
        return None

    def _record_access(self, attr_node: ast.Attribute, mutate: bool) -> None:
        if isinstance(attr_node.value, ast.Name) and attr_node.value.id == "self":
            if self.class_name:
                self.s.accesses.append({
                    "recv": "self", "cls": self.class_name,
                    "attr": attr_node.attr, "line": attr_node.lineno,
                    "mut": mutate, "locks": list(self.lock_stack[-1])})
            return
        rc = self._recv_class(attr_node.value)
        if rc is not None:
            self.s.accesses.append({
                "recv": rc[0], "cls": rc[1], "attr": attr_node.attr,
                "line": attr_node.lineno, "mut": mutate,
                "locks": list(self.lock_stack[-1])})

    # -- statements --------------------------------------------------------
    def visit_Assign(self, node: ast.Assign) -> None:
        # Local constructed from a known class: x = ClassName(...)
        if (isinstance(node.value, ast.Call)
                and isinstance(node.value.func, ast.Name)
                and len(node.targets) == 1
                and isinstance(node.targets[0], ast.Name)):
            self._local_ctor[node.targets[0].id] = node.value.func.id
        for t in node.targets:
            self._mark_target(t)
        self.generic_visit(node)

    def visit_AnnAssign(self, node: ast.AnnAssign) -> None:
        self._mark_target(node.target)
        self.generic_visit(node)

    def visit_AugAssign(self, node: ast.AugAssign) -> None:
        self._mark_target(node.target)
        self.generic_visit(node)

    def visit_Delete(self, node: ast.Delete) -> None:
        for t in node.targets:
            self._mark_target(t)
        self.generic_visit(node)

    def _mark_target(self, target: ast.AST) -> None:
        if isinstance(target, ast.Subscript):
            target = target.value
        if isinstance(target, ast.Attribute):
            self._record_access(target, mutate=True)

    def visit_Attribute(self, node: ast.Attribute) -> None:
        # Reads (mutation sites were recorded at their statement; a second
        # read record for the same node is harmless — checks dedupe).
        if isinstance(node.ctx, ast.Load):
            self._record_access(node, mutate=False)
        self.generic_visit(node)

    # -- calls -------------------------------------------------------------
    def visit_Call(self, node: ast.Call) -> None:
        msg = classify_blocking_call(node)
        dmsg = classify_device_sync(node)
        if self.s.sync_ok and dmsg is not None:
            # Documented `# device-sync: ok` helper: its vetted syncs are
            # exempt from BOTH transitive passes (non-sync blocking
            # primitives — sleep, requests, file I/O — still flag).
            msg = dmsg = None
        if msg is not None:
            self.s.blocking.append([node.lineno, msg])
        if dmsg is not None:
            self.s.device_syncs.append([node.lineno, dmsg])

        name = dotted_name(node.func)
        if name is not None:
            self._record_call(node, name)
            self._record_dispatch(node, name)
        elif isinstance(node.func, ast.Attribute):
            # Dynamic root (call result, subscript): record the terminal
            # method name so unique-name devirtualization still applies.
            self._record_call(node, "?." + node.func.attr)

        # Mutator method call on a receiver attribute: self._table.update()
        if isinstance(node.func, ast.Attribute) and node.func.attr in _MUTATORS:
            recv = node.func.value
            if isinstance(recv, ast.Subscript):
                recv = recv.value
            if isinstance(recv, ast.Attribute):
                self._record_access(recv, mutate=True)

        # httpx discipline: HTTP-method call on a bare name without timeout=.
        if (isinstance(node.func, ast.Attribute)
                and node.func.attr in _HTTP_METHODS
                and isinstance(node.func.value, ast.Name)
                and not any(kw.arg == "timeout" for kw in node.keywords)):
            self.s.httpx_bare.append([node.func.value.id, node.func.attr,
                                      node.lineno])
        self.generic_visit(node)

    def _record_call(self, node: ast.Call, name: str) -> None:
        client_args: list[Any] = []
        param_args: dict[str, str] = {}
        for i, arg in enumerate(node.args):
            if _is_client_expr(arg):
                client_args.append(i)
            if isinstance(arg, ast.Name) and arg.id in self.s.params:
                param_args[str(i)] = arg.id
        for kw in node.keywords:
            if kw.arg is None:
                continue
            if _is_client_expr(kw.value):
                client_args.append(kw.arg)
            if isinstance(kw.value, ast.Name) and kw.value.id in self.s.params:
                param_args[kw.arg] = kw.value.id
        rec: dict[str, Any] = {"name": name, "line": node.lineno}
        if client_args:
            rec["client_args"] = client_args
        if param_args:
            rec["param_args"] = param_args
        self.s.calls.append(rec)

    def _record_dispatch(self, node: ast.Call, name: str) -> None:
        """Functions handed BY REFERENCE to a worker thread."""
        tail = name.split(".")[-1]
        ref: ast.AST | None = None
        if tail in _THREAD_DISPATCH and node.args:
            # to_thread(fn, ...) / run_in_executor(None, fn, ...)
            ref = node.args[1] if tail == "run_in_executor" and len(node.args) > 1 \
                else node.args[0]
        elif tail == "Thread":
            for kw in node.keywords:
                if kw.arg == "target":
                    ref = kw.value
        if ref is None:
            return
        ref_name = dotted_name(ref)
        if ref_name:
            self.s.thread_refs.append([ref_name, node.lineno])


class _FnSummary:
    """Mutable builder for one function's summary dict."""

    def __init__(self, qlocal: str, node: ast.AST, class_name: str | None):
        self.qlocal = qlocal
        self.line = node.lineno
        self.is_async = isinstance(node, ast.AsyncFunctionDef)
        self.class_name = class_name
        args = node.args
        self.params = [a.arg for a in
                       args.posonlyargs + args.args + args.kwonlyargs]
        self.calls: list[dict[str, Any]] = []
        self.blocking: list[list[Any]] = []
        self.device_syncs: list[list[Any]] = []
        self.sync_ok = False
        self.accesses: list[dict[str, Any]] = []
        self.httpx_bare: list[list[Any]] = []
        self.thread_refs: list[list[Any]] = []

    def to_dict(self) -> dict[str, Any]:
        return {"line": self.line, "is_async": self.is_async,
                "class": self.class_name, "params": self.params,
                "calls": self.calls, "blocking": self.blocking,
                "device_syncs": self.device_syncs, "sync_ok": self.sync_ok,
                "accesses": self.accesses, "httpx_bare": self.httpx_bare,
                "thread_refs": self.thread_refs}


def _annotation_class(ann: ast.AST | None) -> str | None:
    """Terminal class name of a simple annotation (``ConfigLoader``,
    ``loader.ConfigLoader``, ``"InferenceEngine"`` string forms)."""
    if ann is None:
        return None
    if isinstance(ann, ast.Constant) and isinstance(ann.value, str):
        return ann.value.split(".")[-1].strip() or None
    name = dotted_name(ann)
    if name:
        return name.split(".")[-1]
    return None


def summarize_module(tree: ast.Module, source: str, relpath: str) -> dict[str, Any]:
    """One module's whole-program summary (JSON-serializable; cacheable)."""
    lines = source.splitlines()
    functions: dict[str, dict[str, Any]] = {}
    classes: dict[str, dict[str, Any]] = {}

    def guard_comment(node: ast.AST) -> str | None:
        for ln in range(node.lineno, getattr(node, "end_lineno", node.lineno) + 1):
            if ln <= len(lines):
                m = _GUARDED_RE.search(lines[ln - 1])
                if m:
                    return m.group(1)
        return None

    def direct_nested_defs(node) -> list:
        """Defs whose nearest enclosing def is ``node`` (no deeper)."""
        found = []
        stack = list(node.body)
        while stack:
            child = stack.pop()
            if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef)):
                found.append(child)
                continue                # its own nested defs belong to it
            stack.extend(ast.iter_child_nodes(child))
        return found

    def collect_fn(node, qlocal: str, class_name: str | None,
                   param_types: dict[str, str]) -> None:
        summ = _FnSummary(qlocal, node, class_name)
        # `# device-sync: ok` on the def line / signature: a documented
        # sync helper — the device-sync pass neither reports it nor
        # chases through it (rules/device_sync.py).
        summ.sync_ok = sync_ok_marked(node, lines)
        # Parameter annotations naming project classes.
        args = node.args
        ptypes = dict(param_types)
        for a in args.posonlyargs + args.args + args.kwonlyargs:
            cls = _annotation_class(a.annotation)
            if cls:
                ptypes[a.arg] = cls
        col = _FnCollector(summ, class_name, ptypes, lines)
        for child in node.body:
            col.visit(child)
        functions[qlocal] = summ.to_dict()
        # Nested defs: separate records, scoped names.
        for child in direct_nested_defs(node):
            collect_fn(child, f"{qlocal}.{child.name}", class_name, ptypes)

    # -- classes + their guards -------------------------------------------
    for node in tree.body:
        if isinstance(node, ast.ClassDef):
            guards: dict[str, str] = {}
            lock_kinds: dict[str, str] = {}
            methods: list[str] = []
            for sub in ast.walk(node):
                if isinstance(sub, (ast.Assign, ast.AnnAssign)):
                    targets = (sub.targets if isinstance(sub, ast.Assign)
                               else [sub.target])
                    for t in targets:
                        if (isinstance(t, ast.Attribute)
                                and isinstance(t.value, ast.Name)
                                and t.value.id == "self"):
                            g = guard_comment(sub)
                            if g:
                                guards[t.attr] = g
                            if isinstance(sub.value, ast.Call):
                                ctor = dotted_name(sub.value.func)
                                if ctor in _LOCK_CTORS:
                                    lock_kinds[t.attr] = (
                                        "asyncio" if ctor.startswith("asyncio")
                                        else "threading")
            for stmt in node.body:
                if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef)):
                    methods.append(stmt.name)
                    collect_fn(stmt, f"{node.name}.{stmt.name}", node.name, {})
            classes[node.name] = {"line": node.lineno, "guards": guards,
                                  "locks": lock_kinds, "methods": methods,
                                  "bases": [b for b in
                                            (dotted_name(x) for x in node.bases)
                                            if b]}
        elif isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            collect_fn(node, node.name, None, {})

    # -- imports -----------------------------------------------------------
    module = _module_name(relpath)
    pkg_parts = module.split(".")[:-1] if module else []
    imports: dict[str, list[str | None]] = {}
    for node in ast.walk(tree):
        if isinstance(node, ast.Import):
            for alias in node.names:
                target = alias.name
                if target.startswith(PACKAGE_NAME + "."):
                    target = target[len(PACKAGE_NAME) + 1:]
                elif target == PACKAGE_NAME:
                    target = ""
                imports[alias.asname or alias.name.split(".")[0]] = [target, None]
        elif isinstance(node, ast.ImportFrom):
            base: list[str]
            if node.level:
                base = pkg_parts[:len(pkg_parts) - (node.level - 1)] \
                    if node.level <= len(pkg_parts) + 1 else []
                if node.module:
                    base = base + node.module.split(".")
            else:
                mod = node.module or ""
                if mod == PACKAGE_NAME:
                    base = []
                elif mod.startswith(PACKAGE_NAME + "."):
                    base = mod[len(PACKAGE_NAME) + 1:].split(".")
                else:
                    base = ["\x00ext", mod]    # external marker
            for alias in node.names:
                imports[alias.asname or alias.name] = [".".join(base), alias.name]

    return {"version": SUMMARY_VERSION, "module": module, "relpath": relpath,
            "functions": functions, "classes": classes, "imports": imports}


class Program:
    """Linked whole-program view over module summaries."""

    def __init__(self, summaries: dict[str, dict[str, Any]]):
        # relpath -> summary
        self.summaries = summaries
        self.by_module: dict[str, dict[str, Any]] = {
            s["module"]: s for s in summaries.values()}
        # Global class index: name -> (module, class record). First wins;
        # duplicate class names across modules disable unique lookups.
        self.classes: dict[str, tuple[str, dict[str, Any]] | None] = {}
        # method name -> {"Class.method" qualified ids by (module, qlocal)}
        method_owners: dict[str, list[tuple[str, str]]] = {}
        for s in summaries.values():
            for cname, crec in s["classes"].items():
                if cname in self.classes:
                    self.classes[cname] = None          # ambiguous
                else:
                    self.classes[cname] = (s["module"], crec)
                for m in crec["methods"]:
                    method_owners.setdefault(m, []).append(
                        (s["module"], f"{cname}.{m}"))
        self.unique_methods: dict[str, tuple[str, str]] = {
            m: owners[0] for m, owners in method_owners.items()
            if len(owners) == 1 and m not in _DEVIRT_DENY}

    # -- lookups -----------------------------------------------------------
    def fn(self, module: str, qlocal: str) -> dict[str, Any] | None:
        s = self.by_module.get(module)
        if s is None:
            return None
        return s["functions"].get(qlocal)

    def relpath(self, module: str) -> str:
        return self.by_module[module]["relpath"]

    def resolve_call(self, module: str, caller_qlocal: str,
                     name: str) -> tuple[str, str] | None:
        """(module, qlocal) of the project function a call by ``name`` from
        ``caller_qlocal`` refers to, or None (external / dynamic)."""
        s = self.by_module.get(module)
        if s is None:
            return None
        caller = s["functions"].get(caller_qlocal, {})
        cls = caller.get("class")
        parts = name.split(".")

        # self.X(...) → method of the enclosing class (here or a base).
        if parts[0] == "self":
            if len(parts) != 2 or cls is None:
                return None
            return self._resolve_method(module, cls, parts[1])

        if parts[0] == "?":                      # dynamic receiver
            return self._devirt(parts[-1])

        # Bare name: nested def in an enclosing scope, module function,
        # class in this module (constructor), or import.
        if len(parts) == 1:
            scope = caller_qlocal.split(".")
            for depth in range(len(scope), 0, -1):
                cand = ".".join(scope[:depth] + [name])
                if cand in s["functions"]:
                    return module, cand
            if name in s["functions"]:
                return module, name
            if name in s["classes"]:
                return self._ctor(module, name)
            imp = s["imports"].get(name)
            if imp is not None:
                return self._resolve_import(imp, None)
            return None

        # Dotted: resolve the root, then descend one level.
        root, rest = parts[0], parts[1:]
        if root in s["classes"] and len(rest) == 1:
            return self._resolve_method(module, root, rest[0])
        imp = s["imports"].get(root)
        if imp is not None:
            return self._resolve_import(imp, rest)
        # obj.method(...) with an unresolvable receiver → devirtualize by
        # project-unique method name.
        return self._devirt(parts[-1])

    def _ctor(self, module: str, cls: str) -> tuple[str, str] | None:
        rec = self.by_module[module]["classes"].get(cls)
        if rec and "__init__" in rec["methods"]:
            return module, f"{cls}.__init__"
        return None

    def _resolve_method(self, module: str, cls: str,
                        meth: str) -> tuple[str, str] | None:
        seen = set()
        queue = [(module, cls)]
        while queue:
            mod, cname = queue.pop()
            if (mod, cname) in seen:
                continue
            seen.add((mod, cname))
            s = self.by_module.get(mod)
            rec = s["classes"].get(cname) if s else None
            if rec is None:
                entry = self.classes.get(cname)
                if entry is None:
                    continue
                mod, rec = entry[0], entry[1]
            if meth in rec["methods"]:
                return mod, f"{cname}.{meth}"
            for base in rec.get("bases", []):
                queue.append((mod, base.split(".")[-1]))
        return None

    def _devirt(self, meth: str) -> tuple[str, str] | None:
        return self.unique_methods.get(meth)

    def _resolve_import(self, imp: list[str | None],
                        rest: list[str] | None) -> tuple[str, str] | None:
        mod, attr = imp[0], imp[1]
        if mod is not None and mod.startswith("\x00ext"):
            return None
        rest = list(rest or [])
        if attr is not None:
            # from M import A: A may be a submodule, class, or function.
            sub = f"{mod}.{attr}" if mod else attr
            if sub in self.by_module:
                mod = sub
            elif mod in self.by_module:
                s = self.by_module[mod]
                if attr in s["classes"]:
                    if not rest:
                        return self._ctor(mod, attr)
                    if len(rest) == 1:
                        return self._resolve_method(mod, attr, rest[0])
                    return None
                if not rest and attr in s["functions"]:
                    return mod, attr
                return None
            else:
                return None
        if mod not in self.by_module:
            return None
        s = self.by_module[mod]
        if not rest:
            return None
        if len(rest) == 1:
            if rest[0] in s["functions"]:
                return mod, rest[0]
            if rest[0] in s["classes"]:
                return self._ctor(mod, rest[0])
            return None
        if len(rest) == 2 and rest[0] in s["classes"]:
            return self._resolve_method(mod, rest[0], rest[1])
        return None

    # -- pass 1: transitive async-blocking --------------------------------
    def _blocking_findings(self) -> list[Finding]:
        findings: list[Finding] = []
        for s in self.summaries.values():
            rel = s["relpath"]
            if not rel.startswith(SERVING_DIRS):
                continue
            for qlocal, fn in s["functions"].items():
                if fn["is_async"]:
                    findings.extend(
                        self._chase_blocking(s["module"], qlocal, fn))
        return findings

    def _chase_blocking(self, module: str, qlocal: str,
                        fn: dict[str, Any]) -> list[Finding]:
        """BFS over call edges from one async entry; shortest chain per
        terminal blocking site, depth ≥ 1 (depth 0 is the lexical rule)."""
        entry_rel = self.relpath(module)
        findings: list[Finding] = []
        reported: set[tuple[str, int]] = set()
        # queue entries: (module, qlocal, chain) where chain is hops so far.
        seen = {(module, qlocal)}
        queue: deque = deque()
        for call in fn["calls"]:
            tgt = self.resolve_call(module, qlocal, call["name"])
            if tgt is None or tgt in seen:
                continue
            seen.add(tgt)
            hop = ChainHop(entry_rel, call["line"],
                           f"{_pretty(qlocal)} calls {_pretty(tgt[1])} "
                           f"({self.relpath(tgt[0])}:{self._line(tgt)})")
            queue.append((tgt, (hop,)))
        while queue:
            (mod, ql), chain = queue.popleft()
            callee = self.fn(mod, ql)
            if callee is None or len(chain) > 8:
                continue
            rel = self.relpath(mod)
            if callee["is_async"] and rel.startswith(SERVING_DIRS) \
                    and callee["blocking"]:
                continue        # lexically flagged at its own site already
            for line, msg in callee["blocking"]:
                key = (rel, line)
                if key in reported:
                    continue
                reported.add(key)
                full = chain + (ChainHop(rel, line, msg),)
                entry_fn = _pretty(qlocal)
                findings.append(Finding(
                    rule="async-blocking", path=entry_rel,
                    line=chain[0].line, col=0,
                    message=(f"async {entry_fn}() reaches blocking call "
                             f"through {len(chain)} call hop(s): {msg} "
                             f"[{rel}:{line}] — offload the helper via "
                             f"asyncio.to_thread or make the chain async"),
                    chain=full))
            for call in callee["calls"]:
                tgt = self.resolve_call(mod, ql, call["name"])
                if tgt is None or tgt in seen:
                    continue
                seen.add(tgt)
                hop = ChainHop(rel, call["line"],
                               f"{_pretty(ql)} calls {_pretty(tgt[1])} "
                               f"({self.relpath(tgt[0])}:{self._line(tgt)})")
                queue.append((tgt, chain + (hop,)))
        return findings

    def _line(self, ref: tuple[str, str]) -> int:
        fn = self.fn(*ref)
        return fn["line"] if fn else 0

    # -- pass 1b: transitive device-sync discipline -------------------------
    def _device_sync_findings(self) -> list[Finding]:
        """From every serving-layer ``async def``, chase call edges into
        ANY module (the engine/obs helpers the blocking pass's serving-
        scope misses are exactly where device syncs hide) and flag
        device→host syncs — except inside functions documented with
        ``# device-sync: ok``, which are neither reported nor descended
        through (their callees are the helper's implementation detail).
        Thread-dispatch references create no call edge (PR 5), so
        worker-thread fetch code is structurally exempt."""
        findings: list[Finding] = []
        for s in self.summaries.values():
            rel = s["relpath"]
            if not rel.startswith(SERVING_DIRS):
                continue
            for qlocal, fn in s["functions"].items():
                if fn["is_async"] and not fn.get("sync_ok"):
                    findings.extend(
                        self._chase_device_sync(s["module"], qlocal, fn))
        return findings

    def _chase_device_sync(self, module: str, qlocal: str,
                           fn: dict[str, Any]) -> list[Finding]:
        entry_rel = self.relpath(module)
        findings: list[Finding] = []
        reported: set[tuple[str, int]] = set()
        entry_fn = _pretty(qlocal)
        # Depth 0: syncs in the coroutine's own body (the lexical rule
        # also sees these in serving dirs; findings dedupe by location
        # downstream of suppression handling, and the chain here names
        # the entry explicitly).
        for line, msg in fn.get("device_syncs", ()):
            key = (entry_rel, line)
            if key in reported:
                continue
            reported.add(key)
            findings.append(Finding(
                rule="device-sync-discipline", path=entry_rel, line=line,
                col=0,
                message=(f"async {entry_fn}() performs a device sync on "
                         f"the event loop: {msg} — offload via "
                         f"asyncio.to_thread or document the helper with "
                         f"`# device-sync: ok`"),
                chain=(ChainHop(entry_rel, line, msg),)))
        seen = {(module, qlocal)}
        queue: deque = deque()
        for call in fn["calls"]:
            tgt = self.resolve_call(module, qlocal, call["name"])
            if tgt is None or tgt in seen:
                continue
            seen.add(tgt)
            hop = ChainHop(entry_rel, call["line"],
                           f"{entry_fn} calls {_pretty(tgt[1])} "
                           f"({self.relpath(tgt[0])}:{self._line(tgt)})")
            queue.append((tgt, (hop,)))
        while queue:
            (mod, ql), chain = queue.popleft()
            callee = self.fn(mod, ql)
            if callee is None or len(chain) > 8:
                continue
            if callee.get("sync_ok"):
                continue            # documented helper: stop the chase
            rel = self.relpath(mod)
            for line, msg in callee.get("device_syncs", ()):
                key = (rel, line)
                if key in reported:
                    continue
                reported.add(key)
                full = chain + (ChainHop(rel, line, msg),)
                findings.append(Finding(
                    rule="device-sync-discipline", path=entry_rel,
                    line=chain[0].line, col=0,
                    message=(f"async {entry_fn}() reaches a device sync "
                             f"through {len(chain)} call hop(s): {msg} "
                             f"[{rel}:{line}] — offload the helper via "
                             f"asyncio.to_thread or document it with "
                             f"`# device-sync: ok`"),
                    chain=full))
            for call in callee["calls"]:
                tgt = self.resolve_call(mod, ql, call["name"])
                if tgt is None or tgt in seen:
                    continue
                seen.add(tgt)
                hop = ChainHop(rel, call["line"],
                               f"{_pretty(ql)} calls {_pretty(tgt[1])} "
                               f"({self.relpath(tgt[0])}:{self._line(tgt)})")
                queue.append((tgt, chain + (hop,)))
        return findings

    # -- pass 2: guarded-by inference --------------------------------------
    def _guard_index(self) -> dict[str, dict[str, str]]:
        """class name -> {attr: guard} across the whole tree (ambiguous
        class names keep their first-seen guards — same-name classes with
        different guard sets would be a design smell the per-file rule
        still covers)."""
        idx: dict[str, dict[str, str]] = {}
        for s in self.summaries.values():
            for cname, crec in s["classes"].items():
                if crec["guards"]:
                    idx.setdefault(cname, {}).update(crec["guards"])
        return idx

    def _thread_reachable(self) -> dict[tuple[str, str], tuple[ChainHop, ...]]:
        """(module, qlocal) -> dispatch chain for every function reachable
        from a thread-dispatch site, whole-program."""
        reach: dict[tuple[str, str], tuple[ChainHop, ...]] = {}
        queue: deque = deque()
        for s in self.summaries.values():
            rel = s["relpath"]
            for qlocal, fn in s["functions"].items():
                for ref_name, line in fn["thread_refs"]:
                    tgt = self.resolve_call(s["module"], qlocal, ref_name)
                    if tgt is None:
                        continue
                    hop = ChainHop(rel, line,
                                   f"{_pretty(qlocal)} dispatches "
                                   f"{_pretty(tgt[1])} to a worker thread")
                    if tgt not in reach:
                        reach[tgt] = (hop,)
                        queue.append(tgt)
        while queue:
            mod, ql = queue.popleft()
            fn = self.fn(mod, ql)
            if fn is None:
                continue
            base_chain = reach[(mod, ql)]
            if len(base_chain) > 8:
                continue
            rel = self.relpath(mod)
            for call in fn["calls"]:
                tgt = self.resolve_call(mod, ql, call["name"])
                if tgt is None or tgt in reach:
                    continue
                hop = ChainHop(rel, call["line"],
                               f"{_pretty(ql)} calls {_pretty(tgt[1])}")
                reach[tgt] = base_chain + (hop,)
                queue.append(tgt)
        return reach

    def _guard_findings(self) -> list[Finding]:
        guards = self._guard_index()
        if not guards:
            return []
        reach = self._thread_reachable()
        findings: list[Finding] = []
        seen: set[tuple[str, int, str]] = set()
        for s in self.summaries.values():
            rel = s["relpath"]
            for qlocal, fn in s["functions"].items():
                in_init = qlocal.endswith("__init__")
                for acc in fn["accesses"]:
                    cls_guards = guards.get(acc["cls"])
                    if not cls_guards:
                        continue
                    guard = cls_guards.get(acc["attr"])
                    if guard is None:
                        continue
                    key = (rel, acc["line"], acc["attr"])
                    if key in seen:
                        continue
                    target = f"{acc['recv']}.{acc['attr']}"
                    if guard == "loop":
                        chain = reach.get((s["module"], qlocal))
                        if chain is None:
                            continue
                        seen.add(key)
                        full = chain + (ChainHop(
                            rel, acc["line"],
                            f"{_pretty(qlocal)} touches {target} "
                            f"(guarded-by: loop) off the event loop"),)
                        findings.append(Finding(
                            rule="lock-discipline", path=rel,
                            line=acc["line"], col=0,
                            message=(f"{target} of class {acc['cls']} is "
                                     f"`guarded-by: loop` (event-loop thread "
                                     f"only) but {_pretty(qlocal)}() is "
                                     f"reachable from a worker-thread "
                                     f"dispatch ({len(chain)} hop(s))"),
                            chain=full))
                        continue
                    # Lock guard. Same-class sites are the per-file rule's
                    # (already enforced); the program pass adds EXTERNAL
                    # accesses through typed parameters/locals.
                    if acc["recv"] == "self" or in_init:
                        continue
                    held = {l.split(".")[-1] for l in acc["locks"]
                            if l.split(".")[0] == acc["recv"] or "." not in l}
                    if guard in held:
                        continue
                    seen.add(key)
                    kind = "mutates" if acc["mut"] else "reads"
                    findings.append(Finding(
                        rule="lock-discipline", path=rel,
                        line=acc["line"], col=0,
                        message=(f"{_pretty(qlocal)}() {kind} {target} of "
                                 f"class {acc['cls']} which is `guarded-by: "
                                 f"{guard}` — external access must hold "
                                 f"`with {acc['recv']}.{guard}` (or go "
                                 f"through the class's own accessors)"),
                        chain=(ChainHop(rel, acc["line"],
                                        f"unguarded external {kind[:-1]} of "
                                        f"{acc['cls']}.{acc['attr']}"),)))
        return findings

    # -- pass 3: httpx timeout dataflow ------------------------------------
    def _timeout_findings(self) -> list[Finding]:
        # Seed taint: client-like args passed at call sites in providers/.
        tainted: dict[tuple[str, str, str], tuple[ChainHop, ...]] = {}
        queue: deque = deque()

        def taint(tgt: tuple[str, str], param: str,
                  chain: tuple[ChainHop, ...]) -> None:
            key = (tgt[0], tgt[1], param)
            if key in tainted:
                return
            tainted[key] = chain
            queue.append(key)

        for s in self.summaries.values():
            if not s["relpath"].startswith(PROVIDER_DIRS):
                continue
            rel = s["relpath"]
            for qlocal, fn in s["functions"].items():
                for call in fn["calls"]:
                    if not call.get("client_args"):
                        continue
                    tgt = self.resolve_call(s["module"], qlocal, call["name"])
                    if tgt is None:
                        continue
                    callee = self.fn(*tgt)
                    if callee is None:
                        continue
                    for pos in call["client_args"]:
                        pname = _param_at(callee, pos)
                        if pname is None:
                            continue
                        hop = ChainHop(
                            rel, call["line"],
                            f"{_pretty(qlocal)} passes an httpx client to "
                            f"{_pretty(tgt[1])}({pname}=…) "
                            f"[{self.relpath(tgt[0])}:{callee['line']}]")
                        taint(tgt, pname, (hop,))

        findings: list[Finding] = []
        reported: set[tuple[str, int]] = set()
        while queue:
            mod, ql, param = queue.popleft()
            chain = tainted[(mod, ql, param)]
            fn = self.fn(mod, ql)
            if fn is None or len(chain) > 8:
                continue
            rel = self.relpath(mod)
            # Direct unsafe use of the tainted parameter.
            for recv, method, line in fn["httpx_bare"]:
                if recv != param or (rel, line) in reported:
                    continue
                if rel.startswith(PROVIDER_DIRS) and "client" in param.lower():
                    continue        # the lexical rule flags this receiver
                reported.add((rel, line))
                full = chain + (ChainHop(
                    rel, line, f"{_pretty(ql)} calls {param}.{method}() "
                               f"without timeout="),)
                findings.append(Finding(
                    rule="timeout-discipline", path=rel, line=line, col=0,
                    message=(f"httpx {method}() on client parameter "
                             f"{param!r} without explicit timeout= — the "
                             f"client flowed in from "
                             f"{chain[0].path}:{chain[0].line}; pass the "
                             f"deadline-capped timeout through"),
                    chain=full))
            # Propagate: tainted param passed onward.
            for call in fn["calls"]:
                pargs = call.get("param_args") or {}
                fwd = [(pos, p) for pos, p in pargs.items() if p == param]
                if not fwd:
                    continue
                tgt = self.resolve_call(mod, ql, call["name"])
                if tgt is None:
                    continue
                callee = self.fn(*tgt)
                if callee is None:
                    continue
                for pos, _ in fwd:
                    pname = _param_at(callee,
                                      int(pos) if pos.isdigit() else pos)
                    if pname is None:
                        continue
                    hop = ChainHop(rel, call["line"],
                                   f"{_pretty(ql)} forwards {param} to "
                                   f"{_pretty(tgt[1])}({pname}=…)")
                    key = (tgt[0], tgt[1], pname)
                    if key not in tainted:
                        tainted[key] = chain + (hop,)
                        queue.append(key)
        return findings

    # -- driver -------------------------------------------------------------
    def findings(self) -> list[Finding]:
        out = (self._blocking_findings() + self._device_sync_findings()
               + self._guard_findings() + self._timeout_findings())
        out.sort(key=lambda f: (f.path, f.line, f.col, f.rule))
        return out


def _pretty(qlocal: str) -> str:
    return qlocal


def _param_at(fn: dict[str, Any], pos: Any) -> str | None:
    params = [p for p in fn["params"] if p not in ("self", "cls")]
    if isinstance(pos, str) and not pos.isdigit():
        return pos if pos in fn["params"] or pos in params else None
    i = int(pos)
    if 0 <= i < len(params):
        return params[i]
    return None


def summarize_source(source: str, path: str | Path,
                     base: Path | None = None) -> dict[str, Any] | None:
    """Parse + summarize one file; None when it doesn't parse (the lexical
    pass reports the syntax error)."""
    relpath = package_relpath(path, base)
    try:
        tree = ast.parse(source, filename=str(path))
    except SyntaxError:
        return None
    return summarize_module(tree, source, relpath)


def analyze_program(paths: Iterable[str | Path],
                    summaries: dict[str, dict[str, Any]] | None = None,
                    report_only: set[str] | None = None) -> list[Finding]:
    """Whole-program findings over ``paths`` (files and/or directory
    roots). Pre-computed ``summaries`` (e.g. cache-loaded, keyed by
    relpath) are used as-is; missing files are parsed fresh. Per-file
    ``# graftlint: disable=`` suppressions apply to the findings exactly
    as they do for lexical rules. ``report_only`` (relpaths) filters the
    report without shrinking the analyzed world — the ``--changed`` mode."""
    summaries = dict(summaries or {})
    sources: dict[str, str] = {}
    for root in paths:
        rootp = Path(root)
        base = rootp if rootp.is_dir() else rootp.parent
        for f in iter_python_files(rootp):
            rel = package_relpath(f, base)
            try:
                src = f.read_text()
            except OSError:
                continue
            sources[rel] = src
            if rel not in summaries:
                summ = summarize_source(src, f, base)
                if summ is not None:
                    summaries[rel] = summ
    program = Program(summaries)
    findings = program.findings()
    out: list[Finding] = []
    known = {"async-blocking", "lock-discipline", "timeout-discipline",
             "device-sync-discipline"}
    supp_cache: dict[str, Suppressions] = {}
    for f in findings:
        if report_only is not None and f.path not in report_only:
            continue
        src = sources.get(f.path)
        if src is not None:
            supp = supp_cache.get(f.path)
            if supp is None:
                supp = Suppressions.parse(src, known)
                supp_cache[f.path] = supp
            if supp.is_suppressed(f):
                continue
        out.append(f)
    return out
