"""Pipeline parallelism: GPipe-style microbatch schedule over a ``pipe``
mesh axis, expressed with ``shard_map`` + ``jax.lax.ppermute``.

SURVEY.md §2b "Pipeline Parallelism (PP)" row: layer-blocked params +
collective-permute microbatching. The reference has no counterpart (it has
no parallelism of any kind — SURVEY.md §2b); this is the TPU-native
equivalent of the stage-to-stage p2p a GPU framework would run over
NCCL send/recv.

Design:

* Params stay in the stacked-layer layout ``[L, ...]`` (models/llama.py)
  and shard the layer dim over ``pipe`` (parallel/sharding.py) — stage ``p``
  holds the contiguous block of layers ``[p·L/P, (p+1)·L/P)``. The KV cache
  shards the same way, so a stage only ever touches its own layers' cache.
* The batch is split into ``M`` microbatches. One forward = ``M + P - 1``
  ticks; at tick ``t`` stage ``p`` runs microbatch ``m = t - p`` through its
  layer block, then hands the activation to stage ``p+1`` via ``ppermute``
  (one hop per tick — rides whatever link the ``pipe`` axis is laid on,
  ideally DCN across hosts).
* Bubble ticks (``t - p`` outside ``[0, M)``) compute on a zero activation
  with ``active=False``, so their cache writes are routed to the
  never-visible row tail (models/llama.py ``insert_kv`` invariant) — no
  masking pass over the cache is ever needed.
* Embedding and the LM head are replicated on every stage: each stage
  embeds its own microbatch input (stage 0's is the only real one) and the
  last stage's logits are broadcast to all stages with a masked ``psum``,
  so the caller sees a fully-replicated ``[B, T, V]`` — the same contract
  as the non-pipelined forward.

Tested against the sequential forward on a virtual CPU mesh
(tests/test_pipeline.py) — same logits, same cache, bubbles and all.
"""
from __future__ import annotations

import functools
from typing import Any

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec as P

from ..utils.jax_compat import SHARD_MAP_PARTIAL_AUTO_OK, shard_map

from ..models import llama
from ..models.config import ModelConfig


def stage_size(n_layers: int, n_stages: int) -> int:
    if n_layers % n_stages != 0:
        raise ValueError(
            f"n_layers={n_layers} not divisible by pipe={n_stages} stages")
    return n_layers // n_stages


def _block_forward(lp_block: dict, c: ModelConfig, x: jax.Array,
                   lengths: jax.Array, k_block: jax.Array,
                   v_block: jax.Array, active: jax.Array,
                   cos: jax.Array, sin: jax.Array, mlp_fn=None,
                   attention_fn=None
                   ) -> tuple[jax.Array, jax.Array, jax.Array]:
    """Run one stage's layer block: scan over the local layers.
    x [Bm, T, D]; k/v_block [Lp, Bm, KV, S, Dh] — or the int8-quantized
    ``{"q", "s"}`` dict (the scan unstacks dim 0 of every leaf; the
    attention handles plain-or-quantized via llama._kv_dequant_views) —
    or, with ``attention_fn`` set, the stage's slice of a paged pool
    ([Lp, NP, KV, page, Dh]) routed by the table the attention closes
    over. ``mlp_fn(h, lp)`` replaces the SwiGLU MLP (the MoE hook — same
    contract as llama.forward's).

    Decode ticks (T == 1) run the DEFERRED-insert protocol exactly when
    the attention provider carries it — the SAME dispatch as llama.forward,
    including the dense default and the windowed (Mistral) default, both of
    which carry ``.decode``/``.insert_all`` (models/llama.py:493-494,:506).
    Per-layer functional cache updates inside the scan would serialize into
    2·L scatters per step; the deferred form attends the stale cache plus
    the self-column and lands ONE stacked insert after the scan, keeping
    the full cache out of the scan's ys. Because the SAME decode kernel
    runs pipelined and non-pipelined, greedy outputs bit-match the
    non-pipelined engine even on float rounding ties. Chunks (T > 1) stay
    insert-then-attend, as in llama.forward for providers without
    ``.verify``."""
    B, T, _ = x.shape
    if attention_fn is None and c.sliding_window:
        # Mistral-family: the default dense path carries the window.
        attend = llama.windowed_dense_attention(c.sliding_window)
    else:
        attend = attention_fn or llama.dense_cache_attention
    decode_attend = insert_all = None
    if T == 1:
        decode_attend = getattr(attend, "decode", None)
        insert_all = getattr(attend, "insert_all", None)
    deferred = decode_attend is not None and insert_all is not None

    def layer_step(x, scanned):
        lp, layer_k, layer_v = scanned
        h = llama.rms_norm(x, lp["attn_norm"], c.rms_eps, c.rms_offset)
        q, k, v = llama.qkv_proj(h, lp, c)
        q = llama.apply_rope(q, cos, sin)
        k = llama.apply_rope(k, cos, sin)
        if deferred:
            attn = decode_attend(q, k, v, layer_k, layer_v, lengths, active)
            ys = (k, v)                      # stacked for insert_all below
        else:
            attn, layer_k, layer_v = attend(
                q, k, v, layer_k, layer_v, lengths, active)
            ys = (layer_k, layer_v)
        x = x + llama.mm(attn, lp["wo"])
        h = llama.rms_norm(x, lp["mlp_norm"], c.rms_eps, c.rms_offset)
        if mlp_fn is not None:
            x = x + mlp_fn(h, lp)
        else:
            x = x + llama.swiglu_mlp(h, lp["wg"], lp["wu"], lp["wd"], c.act)
        return x, ys

    x, (ys_k, ys_v) = jax.lax.scan(layer_step, x, (lp_block, k_block, v_block))
    if deferred:
        new_k, new_v = insert_all(k_block, v_block, ys_k, ys_v, lengths,
                                  active)
    else:
        new_k, new_v = ys_k, ys_v
    return x, new_k, new_v


@functools.lru_cache(maxsize=32)
def _build_run(c: ModelConfig, mesh: Mesh, n_stages: int, M: int, Bm: int,
               T: int, has_lm_head: bool, has_head_q8: bool = False,
               make_attention=None):
    """Build (once per signature) the jitted shard_map pipeline program.
    jax.jit caches by function identity, so the closure must be memoized —
    a fresh closure per call would retrace/recompile every invocation.

    ``make_attention(table_rows) -> attention_fn`` switches the cache to
    PAGED mode: the run gains a trailing ``table [B, slots]`` argument,
    stages hold their slice of the page POOL (no batch dim — the
    microbatch tick slices TABLE rows instead of cache rows, and each
    microbatch's writes land in its own pages), and bubble-tick writes
    ride the pool's trash-page-0 redirect (active=False). The callable
    must be identity-stable (the engine builds one partial per engine)
    or this memo would retrace per call."""
    B = M * Bm
    # MoE (mixtral): the staged block runs the family MLP hook per layer
    # — the scanned lp slice carries router [D,E] + expert stacks, which
    # is exactly what moe_mlp_* consume. NB the dense/dispatch shape
    # switch sees the MICROBATCH's N = Bm·T, so a pipelined long prefill
    # may pick capacity dispatch at a different N than the sequential
    # forward would — capacity is an approximation knob either way;
    # decode (T=1) and small chunks always run the exact dense form.
    if c.is_moe:
        from ..models import mixtral
        mlp_fn = mixtral.make_mlp_fn(c)
    else:
        mlp_fn = None
    # Spec prefix-trees: P("pipe") applies to every leaf under "layers".
    param_spec = {"embed": P(), "final_norm": P(), "layers": P("pipe")}
    if has_lm_head:
        param_spec["lm_head"] = P()
    if has_head_q8:
        param_spec["lm_head_q8"] = P()     # prefix spec covers {q, s}
    paged = make_attention is not None
    in_specs = (
        P("pipe"),               # stage index [n_stages] -> local [1]
        param_spec,
        P(),                     # tokens (replicated; every stage embeds)
        P(),                     # lengths
        P("pipe"), P("pipe"),    # cache k, v (layer dim)
        P(),                     # active
    ) + ((P(),) if paged else ())   # page table (replicated)
    out_specs = (P(), P("pipe"), P("pipe"))

    @functools.partial(
        shard_map, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
        axis_names={"pipe"}, check_vma=False)
    def run(stage, params, tokens, lengths, cache_k, cache_v, active,
            *table):
        # The stage id arrives as this stage's shard of an iota input —
        # NOT jax.lax.axis_index: under a partially-manual shard_map
        # (auto `model` axis) axis_index lowers to a PartitionId
        # instruction SPMD partitioning rejects on older jax.
        p = stage[0]
        lp = params["layers"]                  # [Lp, ...] local block

        # Every stage embeds every microbatch (replicated compute, tiny):
        # [M, Bm, T, D].
        x_all = jnp.take(params["embed"], tokens, axis=0).reshape(M, Bm, T, -1)
        if c.scale_embed:
            x_all = x_all * jnp.asarray(c.d_model ** 0.5, x_all.dtype)
        positions = (lengths[:, None] + jnp.arange(T)[None, :])     # [B, T]
        cos_all, sin_all = llama.rope_tables(positions, c.head_dim,
                                             c.rope_theta, c.rope_scaling)
        cos_all = cos_all.reshape(M, Bm, T, -1)
        sin_all = sin_all.reshape(M, Bm, T, -1)
        len_all = lengths.reshape(M, Bm)
        act_all = active.reshape(M, Bm)

        n_ticks = M + n_stages - 1

        def tick(t, carry):
            inbuf, cache_k, cache_v, outs = carry
            m = t - p                               # this stage's microbatch
            valid = (m >= 0) & (m < M)
            mc = jnp.clip(m, 0, M - 1)
            # Stage 0 reads its own embedding; later stages read the
            # ppermuted activation from the previous stage.
            x_in = jnp.where(p == 0, x_all[mc], inbuf)
            mb_len = len_all[mc]
            mb_act = act_all[mc] & valid            # bubbles → tail writes
            if paged:
                # The pool has no batch dim: slice TABLE rows for this
                # microbatch instead of cache rows; writes land in the
                # microbatch's own pages (bubbles → trash page 0 via
                # active=False), so the updated stage pool carries whole.
                mb_table = jax.lax.dynamic_slice_in_dim(
                    table[0], mc * Bm, Bm, 0)
                y, cache_k, cache_v = _block_forward(
                    lp, c, x_in, mb_len, cache_k, cache_v, mb_act,
                    cos_all[mc], sin_all[mc], mlp_fn=mlp_fn,
                    attention_fn=make_attention(mb_table))
            else:
                # Tree-mapped batch slicing: an int8-quantized cache is a
                # {"q": [L,B,KV,S,Dh], "s": [L,B,KV,1,S]} dict — the
                # batch dim is axis 1 of EVERY leaf, so one per-leaf
                # slice covers both layouts (VERDICT r3 item 7:
                # kv_quant × PP).
                def rows(cache):
                    return jax.tree.map(
                        lambda a: jax.lax.dynamic_slice_in_dim(
                            a, mc * Bm, Bm, 1), cache)
                y, k_rows, v_rows = _block_forward(
                    lp, c, x_in, mb_len, rows(cache_k), rows(cache_v),
                    mb_act, cos_all[mc], sin_all[mc], mlp_fn=mlp_fn)
                cache_k = jax.tree.map(
                    lambda full, r: jax.lax.dynamic_update_slice_in_dim(
                        full, r, mc * Bm, 1), cache_k, k_rows)
                cache_v = jax.tree.map(
                    lambda full, r: jax.lax.dynamic_update_slice_in_dim(
                        full, r, mc * Bm, 1), cache_v, v_rows)
            # Last stage collects its finished microbatch.
            take = valid & (p == n_stages - 1)
            outs = jax.lax.cond(
                take,
                lambda o: jax.lax.dynamic_update_slice_in_dim(
                    o, y[None], mc, 0),
                lambda o: o, outs)
            # Hand the activation to the next stage (ring permute; the
            # wrap-around hop P-1 → 0 carries a bubble, never real data).
            inbuf = jax.lax.ppermute(
                y, "pipe",
                [(i, (i + 1) % n_stages) for i in range(n_stages)])
            return inbuf, cache_k, cache_v, outs

        inbuf = jnp.zeros_like(x_all[0])
        outs = jnp.zeros_like(x_all)
        inbuf, cache_k, cache_v, outs = jax.lax.fori_loop(
            0, n_ticks, tick, (inbuf, cache_k, cache_v, outs))

        # Final norm + head on the last stage's collected activations;
        # masked psum broadcasts the logits to every stage.
        x = outs.reshape(B, T, -1)
        x = llama.rms_norm(x, params["final_norm"], c.rms_eps, c.rms_offset)
        head = llama._select_head(params, c)
        logits = llama.head_matmul(x, head)   # plain bf16 or int8 {q,s} head
        logits = jnp.where(p == n_stages - 1, logits, 0.0)
        logits = jax.lax.psum(logits, "pipe")
        return logits, cache_k, cache_v

    # Partially-manual shard_map (axis_names ⊂ mesh axes, so GSPMD keeps
    # managing e.g. the `model` axis inside each stage) only traces under
    # jit in current JAX.
    return jax.jit(run)


def pipelined_forward(params: dict, config: ModelConfig, tokens: jax.Array,
                      lengths: jax.Array, cache, mesh: Mesh,
                      n_microbatches: int,
                      active: jax.Array | None = None,
                      make_attention=None, table: jax.Array | None = None):
    """Pipelined equivalent of ``llama.forward`` over the mesh's ``pipe``
    axis. Same signature contract: tokens [B, T] → (logits [B, T, V] fp32
    replicated, updated cache). B must divide into ``n_microbatches``.

    PAGED mode: pass ``make_attention(table_rows) -> attention_fn`` (an
    identity-stable builder — one partial per engine) plus the page
    ``table [B, slots]``; ``cache`` is then the PagedKVCache pool with
    its layer dim staged over ``pipe``. The cache pytree type is
    preserved in the return.
    """
    B, T = tokens.shape
    n_stages = mesh.shape.get("pipe", 1)
    if (not SHARD_MAP_PARTIAL_AUTO_OK and n_stages > 1
            and any(n > 1 for ax, n in mesh.shape.items() if ax != "pipe")):
        # Refuse BEFORE compile: the legacy partial-auto shard_map
        # miscompiles this schedule combined with a real second mesh axis
        # (XLA abort, which would take the whole process down).
        raise NotImplementedError(
            "pipeline parallelism combined with another sharded mesh axis "
            "needs jax.shard_map's partial-auto mode (jax >= 0.5); this "
            "jax build only supports a pure-pipe mesh")
    stage_size(config.n_layers, n_stages)     # validate divisibility
    M = n_microbatches
    if B % M != 0:
        raise ValueError(f"batch {B} not divisible by {M} microbatches")
    if active is None:
        active = jnp.ones((B,), bool)
    run = _build_run(config, mesh, n_stages, M, B // M, T,
                     "lm_head" in params, "lm_head_q8" in params,
                     make_attention)
    extra = () if make_attention is None else (table,)
    stage = jnp.arange(n_stages, dtype=jnp.int32)
    logits, new_k, new_v = run(stage, params, tokens, lengths, cache.k,
                               cache.v, active, *extra)
    return logits, type(cache)(k=new_k, v=new_v)
