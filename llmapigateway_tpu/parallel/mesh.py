"""Device mesh construction.

The communication backend of this framework is XLA collectives over ICI
(intra-slice) and DCN (inter-host) — the TPU-native equivalent of the
NCCL/MPI tier a GPU framework would carry (SURVEY.md §2b, §5 "Distributed
communication backend"). A :class:`jax.sharding.Mesh` with named axes is the
single abstraction everything shards over:

  axes: ``data`` (DP, batch dim) · ``model`` (TP, weight columns/rows)
        · ``expert`` (EP, MoE experts) · ``seq`` (SP, ring attention)

Multi-host: call :func:`init_distributed` first (wraps
``jax.distributed.initialize``); mesh axes spanning hosts ride DCN, axes
within a slice ride ICI. Keep ``model``/``seq`` inside a slice, put
``data`` across slices — collectives then match link bandwidth.
"""
from __future__ import annotations

import logging
import os
from dataclasses import dataclass, field

import jax
import numpy as np
from jax.sharding import Mesh

logger = logging.getLogger(__name__)

AXIS_ORDER = ("pipe", "data", "expert", "seq", "model")   # slowest → fastest
# `pipe` (PP stages) is outermost: stage-to-stage traffic is one activation
# hand-off per microbatch tick — the least-frequent collective — so it is
# the axis to lay across hosts/DCN; `model` stays innermost on adjacent ICI
# neighbors.


@dataclass
class MeshSpec:
    """Named axis sizes; unspecified axes default to 1. ``model`` absorbs
    remaining devices when sizes don't cover the device count and
    ``auto_model`` is set."""
    sizes: dict[str, int] = field(default_factory=dict)
    auto_model: bool = True

    def resolve(self, n_devices: int) -> dict[str, int]:
        sizes = {ax: int(self.sizes.get(ax, 1)) for ax in AXIS_ORDER}
        known = 1
        for ax, s in sizes.items():
            if s <= 0:
                raise ValueError(f"mesh axis {ax} must be positive, got {s}")
            known *= s
        if known == n_devices:
            return sizes
        if self.auto_model and "model" not in self.sizes and \
                n_devices % (known // sizes["model"]) == 0:
            rest = known // sizes["model"]
            if n_devices % rest == 0:
                sizes["model"] = n_devices // rest
                return sizes
        raise ValueError(
            f"mesh sizes {self.sizes} (product {known}) do not match "
            f"{n_devices} devices")


def build_mesh(spec: MeshSpec | dict[str, int] | None = None,
               devices: list | None = None) -> Mesh:
    """Build a mesh over the given (default: all) devices.

    Device order: JAX returns devices in row-major ICI order; reshaping to
    (data, expert, seq, model) keeps the fastest-varying axis (`model` — the
    axis with the most collective traffic) on adjacent ICI neighbors.
    """
    if isinstance(spec, dict):
        spec = MeshSpec(sizes=spec)
    spec = spec or MeshSpec()
    devices = devices if devices is not None else jax.devices()
    sizes = spec.resolve(len(devices))
    shape = tuple(sizes[ax] for ax in AXIS_ORDER)
    arr = np.array(devices).reshape(shape)
    mesh = Mesh(arr, AXIS_ORDER)
    logger.info("mesh: %s over %d %s devices",
                {ax: s for ax, s in sizes.items() if s > 1} or {"single": 1},
                len(devices), devices[0].platform)
    return mesh


def init_distributed() -> None:
    """Initialize multi-host JAX (DCN) when launched under a multi-host
    runtime. Safe no-op for single-process runs."""
    if os.environ.get("JAX_COORDINATOR_ADDRESS"):
        jax.distributed.initialize()
        logger.info("jax.distributed initialized: process %d/%d",
                    jax.process_index(), jax.process_count())
