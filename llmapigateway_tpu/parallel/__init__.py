from .mesh import build_mesh, MeshSpec
from .sharding import param_shardings, cache_sharding, batch_sharding

__all__ = ["build_mesh", "MeshSpec", "param_shardings", "cache_sharding",
           "batch_sharding"]
