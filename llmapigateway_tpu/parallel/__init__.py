from .mesh import build_mesh, MeshSpec
from .pipeline import pipelined_forward
from .ring_attention import ring_attention
from .sharding import (
    batch_sharding,
    cache_sharding,
    paged_cache_sharding,
    param_shardings,
)
from .ulysses import ulysses_attention

__all__ = ["build_mesh", "MeshSpec", "param_shardings", "cache_sharding",
           "paged_cache_sharding", "batch_sharding", "pipelined_forward",
           "ring_attention", "ulysses_attention"]
