"""Ulysses-style sequence parallelism: all-to-all head↔sequence resharding.

The alternative to ring attention when heads ≥ chips (SURVEY.md §2b
"Ulysses-style attention" row): instead of rotating K/V blocks n-1 hops,
ONE ``all_to_all`` converts the sharding from sequence-split (each chip has
``T/n`` tokens of every head) to head-split (each chip has every token of
``H/n`` heads), plain full-sequence attention runs locally per head group,
and a second ``all_to_all`` restores sequence sharding. Two collectives
total — cheaper than a ring when the sequence is long but heads divide
evenly; not applicable when KV heads < chips (ring handles that case).

No reference counterpart (the reference has no parallelism of any kind —
SURVEY.md §2b); pattern follows the public DeepSpeed-Ulysses idea,
expressed TPU-natively with ``shard_map`` + ``jax.lax.all_to_all``.
"""
from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec as P

from ..utils.jax_compat import shard_map

NEG_INF = -1e30


def _dense_causal(q, k, v, *, causal: bool):
    """Plain attention, local shapes [B, T, h, Dh] / [B, T, kv, Dh]."""
    B, T, H, Dh = q.shape
    KV = k.shape[2]
    group = H // KV
    kh = jnp.repeat(k, group, axis=2)
    vh = jnp.repeat(v, group, axis=2)
    qf = q.astype(jnp.float32)
    scores = jnp.einsum("bqhd,bkhd->bhqk", qf, kh.astype(jnp.float32))
    scores *= Dh ** -0.5
    if causal:
        q_pos = jnp.arange(T)[:, None]
        k_pos = jnp.arange(T)[None, :]
        scores = jnp.where((k_pos <= q_pos)[None, None], scores, NEG_INF)
    probs = jax.nn.softmax(scores, axis=-1)
    out = jnp.einsum("bhqk,bkhd->bqhd", probs, vh.astype(jnp.float32))
    return out.astype(q.dtype)


def _ulysses_body(q, k, v, *, axis: str, causal: bool):
    """Inside shard_map: local q [B, T/n, H, Dh] → attention → same shape."""
    # seq-sharded → head-sharded: split heads (axis 2) across the group,
    # gather sequence (axis 1).
    qh = jax.lax.all_to_all(q, axis, split_axis=2, concat_axis=1, tiled=True)
    kh = jax.lax.all_to_all(k, axis, split_axis=2, concat_axis=1, tiled=True)
    vh = jax.lax.all_to_all(v, axis, split_axis=2, concat_axis=1, tiled=True)
    out = _dense_causal(qh, kh, vh, causal=causal)     # [B, T, H/n, Dh]
    # head-sharded → seq-sharded.
    return jax.lax.all_to_all(out, axis, split_axis=1, concat_axis=2,
                              tiled=True)


def ulysses_attention(q: jax.Array, k: jax.Array, v: jax.Array, mesh: Mesh,
                      axis: str = "seq", causal: bool = True) -> jax.Array:
    """Exact attention with sequence sharded on ``axis`` via all-to-all.

    q: [B, T, H, Dh]; k/v: [B, T, KV, Dh], T sharded over ``axis``.
    Requires H % n == 0 and KV % n == 0 (n = mesh axis size) — use
    :func:`..parallel.ring_attention.ring_attention` otherwise.
    """
    n = mesh.shape[axis]
    H, KV = q.shape[2], k.shape[2]
    if q.shape[1] % n:
        raise ValueError(f"sequence {q.shape[1]} not divisible by {axis}={n}")
    if H % n or KV % n:
        raise ValueError(
            f"Ulysses needs heads divisible by the mesh axis (H={H}, "
            f"KV={KV}, {axis}={n}); use ring_attention for KV < chips")
    body = functools.partial(_ulysses_body, axis=axis, causal=causal)
    f = shard_map(
        body, mesh=mesh,
        in_specs=(P(None, axis, None, None),) * 3,
        out_specs=P(None, axis, None, None),
        axis_names={axis}, check_vma=False)
    return f(q, k, v)
