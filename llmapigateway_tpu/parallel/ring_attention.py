"""Ring attention: causal self-attention with the sequence axis sharded
over the mesh, exchanging K/V blocks around the ring via ``ppermute``.

Long-context prefill support (SURVEY.md §2b "Sequence/Context Parallelism"
row, §5 "long-context"): a prompt longer than one chip's HBM/FLOP budget is
sharded ``[B, T/n, ...]`` per chip; each chip keeps its query block resident
and sees every K/V block exactly once as blocks rotate n-1 hops around the
ring (neighbor exchange — on TPU this rides ICI, overlapping each hop with
the current block's compute; cf. the blockwise-attention papers in
PAPERS.md, re-derived). Online softmax (m/l/acc running triple) makes the
result exact, not approximate.

The reference has no counterpart — sequence length is the upstream
vendor's problem there (SURVEY.md §5). Here it is a first-class op usable
standalone (tested against dense attention on a virtual CPU mesh) and as
the prefill attention for a sequence-sharded engine.

No reference-repo code involved; collective structure is textbook ring
parallelism expressed with ``shard_map`` + ``jax.lax.ppermute``.
"""
from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec as P

from ..utils.jax_compat import axis_size, shard_map

NEG_INF = -1e30


def _block_attn_accum(q, k, v, q_off, k_off, m, l, acc, *, causal: bool):
    """One K/V block's contribution under online softmax.

    q: [B, Tq, H, Dh]; k/v: [B, Tk, KV, Dh]; q_off/k_off: scalar global
    offsets of the blocks; m/l: [B, H, Tq, 1]; acc: [B, H, Tq, Dh].
    Fully-masked entries contribute exactly zero (explicit mask multiply —
    the classic exp(0)=1 hazard when a block is entirely invisible).
    """
    B, Tq, H, Dh = q.shape
    KV = k.shape[2]
    group = H // KV
    kh = jnp.repeat(k, group, axis=2)          # [B, Tk, H, Dh]
    vh = jnp.repeat(v, group, axis=2)

    qf = q.astype(jnp.float32)
    scores = jnp.einsum("bqhd,bkhd->bhqk", qf, kh.astype(jnp.float32))
    scores *= Dh ** -0.5                        # [B, H, Tq, Tk]

    if causal:
        q_pos = q_off + jnp.arange(Tq)[:, None]         # [Tq, 1]
        k_pos = k_off + jnp.arange(k.shape[1])[None, :]  # [1, Tk]
        mask = (k_pos <= q_pos)[None, None]              # [1, 1, Tq, Tk]
        scores = jnp.where(mask, scores, NEG_INF)
    else:
        mask = jnp.ones((1, 1, Tq, k.shape[1]), bool)

    m_new = jnp.maximum(m, jnp.max(scores, axis=-1, keepdims=True))
    alpha = jnp.exp(m - m_new)
    p = jnp.exp(scores - m_new) * mask          # zero where invisible
    l_new = alpha * l + jnp.sum(p, axis=-1, keepdims=True)
    acc_new = alpha * acc + jnp.einsum(
        "bhqk,bkhd->bhqd", p, vh.astype(jnp.float32))
    return m_new, l_new, acc_new


def _ring_body(q, k, v, *, axis: str, causal: bool):
    """Per-shard ring loop (runs inside shard_map, manual over `axis`)."""
    B, Tl, H, Dh = q.shape
    n = axis_size(axis)
    idx = jax.lax.axis_index(axis)
    q_off = idx * Tl

    m = jnp.full((B, H, Tl, 1), NEG_INF, jnp.float32)
    l = jnp.zeros((B, H, Tl, 1), jnp.float32)
    acc = jnp.zeros((B, H, Tl, Dh), jnp.float32)

    perm = [(i, (i + 1) % n) for i in range(n)]

    def step(s, carry):
        k_blk, v_blk, m, l, acc = carry
        # At step s this shard holds the block that started on shard idx-s.
        owner = (idx - s) % n
        m, l, acc = _block_attn_accum(
            q, k_blk, v_blk, q_off, owner * Tl, m, l, acc, causal=causal)
        # Rotate for the next step (skipped result on the last iteration is
        # harmless; keeping the permute inside the loop lets XLA overlap it
        # with this step's compute).
        k_nxt = jax.lax.ppermute(k_blk, axis, perm)
        v_nxt = jax.lax.ppermute(v_blk, axis, perm)
        return k_nxt, v_nxt, m, l, acc

    _, _, m, l, acc = jax.lax.fori_loop(0, n, step, (k, v, m, l, acc))
    out = acc / jnp.where(l == 0.0, 1.0, l)
    return out.transpose(0, 2, 1, 3).astype(q.dtype)   # [B, Tl, H, Dh]


def ring_attention(q: jax.Array, k: jax.Array, v: jax.Array, mesh: Mesh,
                   axis: str = "seq", causal: bool = True) -> jax.Array:
    """Exact causal attention with sequence sharded on ``axis``.

    q: [B, T, H, Dh]; k/v: [B, T, KV, Dh] (GQA OK) — T sharded over
    ``axis``; every other dim replicated or GSPMD-managed. Returns
    [B, T, H, Dh] with the same sequence sharding.
    """
    n = mesh.shape[axis]
    if q.shape[1] % n:
        raise ValueError(f"sequence {q.shape[1]} not divisible by "
                         f"{axis}={n}")
    body = functools.partial(_ring_body, axis=axis, causal=causal)
    f = shard_map(
        body, mesh=mesh,
        in_specs=(P(None, axis, None, None),) * 3,
        out_specs=P(None, axis, None, None),
        axis_names={axis}, check_vma=False)
    return f(q, k, v)
