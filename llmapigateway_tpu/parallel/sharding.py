"""GSPMD sharding rules: param-tree → NamedSharding.

Megatron-style tensor parallelism expressed purely as shardings — XLA
inserts the collectives (all-reduce after row-parallel matmuls rides ICI on
the ``model`` axis):

* attention/MLP input projections (wq/wk/wv/wg/wu): column-parallel —
  output dim sharded on ``model``;
* output projections (wo/wd): row-parallel — input dim sharded on ``model``;
* lm_head: vocab-sharded (logit all-gather at the end);
* norms: replicated; embed: vocab-sharded when divisible;
* MoE expert weights: expert dim on ``expert``, then column/row on ``model``;
* KV cache: batch on ``data``, KV heads on ``model`` when divisible
  (GQA with fewer KV heads than chips → heads replicated, which matches the
  usual TPU serving layout).

Every rule degrades to replication when the dim isn't divisible by the axis
size — correctness never depends on a particular mesh shape.
"""
from __future__ import annotations

import logging
from typing import Any

import jax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

logger = logging.getLogger(__name__)


def _axis(mesh: Mesh, name: str, dim_size: int) -> str | None:
    """Use `name` for a dim only if the axis exists and divides the dim."""
    size = mesh.shape.get(name, 1)
    if size > 1 and dim_size % size == 0:
        return name
    return None


# param path (dot key) → function(shape, mesh) -> PartitionSpec
def _spec_for(path: str, shape: tuple[int, ...], mesh: Mesh) -> P:
    # The tied-embedding int8 head copy (models/quant.py quantize_tree)
    # shards exactly like a real lm_head.
    if path.startswith("lm_head_q8"):
        path = "lm_head" + path[len("lm_head_q8"):]
    # Int8-quantized weights (models/quant.py) add ".q"/".s" sub-leaves:
    # the int8 tensor shards exactly like the bf16 weight it replaces; the
    # per-output-channel scale shards like the weight's output dim (so a
    # column-parallel matmul keeps scale shards co-resident with their
    # channels, and a row-parallel one keeps the scale replicated — the
    # fp32 rescale commutes with the int32 partial-sum all-reduce).
    if path.endswith(".q"):
        return _spec_for(path[:-2], shape, mesh)
    if path.endswith(".s"):
        base = path[:-2]
        if base == "lm_head":                       # [V]
            return P(_axis(mesh, "model", shape[0]))
        key = base.split(".", 1)[1] if base.startswith("layers.") else base
        lp = _axis(mesh, "pipe", shape[0])
        if key in ("wq", "wk", "wv", "wg", "wu"):   # column-parallel [L, out]
            if len(shape) == 3:                     # MoE expert [L, E, F]
                return P(lp, _axis(mesh, "expert", shape[1]),
                         _axis(mesh, "model", shape[2]))
            return P(lp, _axis(mesh, "model", shape[1]))
        if key in ("wo", "wd"):                     # row-parallel: out replicated
            if len(shape) == 3:                     # MoE expert [L, E, D]
                return P(lp, _axis(mesh, "expert", shape[1]), None)
            return P(lp, None)
        return P()
    if path == "embed" or path == "lm_head":
        return P(_axis(mesh, "model", shape[0]), None)
    if path in ("final_norm",):
        return P(None)
    if path.startswith("layers."):
        key = path.split(".", 1)[1]
        # Stacked layer dim (dim 0) shards over `pipe` when PP is on: each
        # stage holds a contiguous block of layers (parallel/pipeline.py).
        lp = _axis(mesh, "pipe", shape[0])
        if key in ("attn_norm", "mlp_norm"):
            return P(lp, None)
        if key == "router":                       # [L, D, E]
            return P(lp, None, None)
        if key in ("bq", "bk", "bv"):             # [L, out] column bias
            return P(lp, _axis(mesh, "model", shape[1]))
        n = len(shape)
        if key in ("wq", "wk", "wv", "wg", "wu"):
            if n == 4:                            # MoE expert: [L, E, D, F]
                return P(lp, _axis(mesh, "expert", shape[1]), None,
                         _axis(mesh, "model", shape[3]))
            return P(lp, None, _axis(mesh, "model", shape[2]))
        if key in ("wo", "wd"):
            if n == 4:                            # [L, E, F, D]
                return P(lp, _axis(mesh, "expert", shape[1]),
                         _axis(mesh, "model", shape[2]), None)
            return P(lp, _axis(mesh, "model", shape[1]), None)
    logger.debug("no sharding rule for %s %s; replicating", path, shape)
    return P()


def _tree_paths(tree: Any, prefix: str = "") -> dict[str, Any]:
    out = {}
    for key, val in tree.items():
        path = f"{prefix}{key}"
        if isinstance(val, dict):
            out.update(_tree_paths(val, path + "."))
        else:
            out[path] = val
    return out


def param_shardings(params_or_shapes: Any, mesh: Mesh) -> Any:
    """Mirror the params pytree with NamedShardings."""
    def build(tree, prefix=""):
        out = {}
        for key, val in tree.items():
            path = f"{prefix}{key}"
            if isinstance(val, dict):
                out[key] = build(val, path + ".")
            else:
                out[key] = NamedSharding(mesh, _spec_for(path, tuple(val.shape), mesh))
        return out
    return build(params_or_shapes)


def spec_for_param(path: str, shape: tuple[int, ...], mesh: Mesh) -> NamedSharding:
    return NamedSharding(mesh, _spec_for(path, shape, mesh))


def cache_sharding(mesh: Mesh, n_kv_heads: int, batch: int,
                   max_seq: int | None = None,
                   n_layers: int | None = None) -> NamedSharding:
    """KV cache [L, B, KV, S, Dh] (head-major): batch on data, KV heads on
    model; in a sequence-parallel engine S shards on ``seq`` (ring prefill
    writes each shard locally, decode reductions are GSPMD-partitioned);
    in a pipelined engine L shards on ``pipe`` so each stage holds only its
    own layers' cache (matching parallel/pipeline.py's stage specs)."""
    return NamedSharding(mesh, P(
        _axis(mesh, "pipe", n_layers) if n_layers else None,
        _axis(mesh, "data", batch),
        _axis(mesh, "model", n_kv_heads),
        _axis(mesh, "seq", max_seq) if max_seq else None, None))


def paged_cache_sharding(mesh: Mesh, n_kv_heads: int,
                         n_layers: int | None = None,
                         num_pages: int | None = None) -> NamedSharding:
    """Paged pool [L, P, KV, page, Dh]: KV heads on model. The page dim is
    a global pool indexed by the (replicated) page table — unsharded,
    EXCEPT in a seq-sharded engine, where it rides ``seq`` with
    position-banded allocation (engine/paged.py: every chip's S-shard
    reads only local pages). In a pipelined engine the layer dim stages
    over ``pipe`` (each stage holds its own layers' pages), mirroring the
    dense cache_sharding."""
    return NamedSharding(mesh, P(
        _axis(mesh, "pipe", n_layers) if n_layers else None,
        _axis(mesh, "seq", num_pages) if num_pages else None,
        _axis(mesh, "model", n_kv_heads), None, None))


def batch_sharding(mesh: Mesh, batch: int) -> NamedSharding:
    """[B, ...] host batch arrays: batch dim on data axis."""
    return NamedSharding(mesh, P(_axis(mesh, "data", batch)))


def replicated(mesh: Mesh) -> NamedSharding:
    return NamedSharding(mesh, P())
