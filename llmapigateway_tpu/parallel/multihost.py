"""Multi-host serving: process-0 HTTP frontend, engine-op fan-out over DCN.

SURVEY.md §7 hard part (4) and §5 "Distributed communication backend": in a
multi-host JAX deployment every process must execute the same XLA programs
in lockstep on its shard of the global mesh. The gateway therefore runs the
HTTP server and the scheduler **only on process 0**; every compiled-program
invocation the scheduler decides on (one prefill chunk, one decode burst)
is first broadcast as a fixed-shape command word so follower processes can
replay the identical call on their shards. The broadcast rides
``multihost_utils.broadcast_one_to_all`` — an XLA collective over DCN, the
TPU-native counterpart of the NCCL/MPI control plane a GPU serving stack
would carry (the reference's only transport is outbound HTTPS —
``services/request_handler.py:15`` — it has no distributed plane at all).

Wire format: ONE int32 vector per command, shape ``[HEADER + payload]``
(fixed at bridge construction so the collective's shape never changes):

  ``[opcode, a, b, has_table, n_payload, _, _, _, payload ..., table tail]``

  * SHUTDOWN:       opcode 0
  * PREFILL_CHUNK:  opcode 1, a=slot, b=pos, payload=token ids (the
    compile bucket is derived per-process from pos+len+config)
  * PREFILL_PART:   opcode 3, same operands — one segment of a chunk
    longer than a frame's token capacity (TOKEN_FRAME_CAP); the follower
    concatenates parts, in order, onto the final PREFILL_CHUNK frame
  * DECODE_BURST:   opcode 2, a=n_steps, payload = packed slot state —
    lengths[B], active[B], last_token[B], top_k[B] (int32) then
    temperature[B], top_p[B], presence_penalty[B],
    frequency_penalty[B] (float32 bit-cast) then rng key (uint32
    bit-cast) — everything a follower needs to build bit-identical
    decode inputs. (Penalty COUNTS are never on the wire: both sides'
    device counts evolve through the same broadcast-input programs,
    so they stay bit-identical by construction.)
  * SPEC_BURST:     opcode 4, a=n_steps, b=reupload flag, payload = the
    same packed state. The token HISTORY is never on the wire: every
    process maintains a bit-identical host hist mirror (prefill chunks
    write it; each spec burst's fetched emitted matrix advances it via
    the same walk), so on a reupload both sides rebuild the device hist
    from their own mirrors.
  * ``cmd[3]`` is RESERVED as the has-table flag: when 1, the LAST
    ``B * table_slots`` ints of the frame carry the paged-KV page table
    (followers have no allocator; table changes ride the same stream
    that orders every compiled call). The tail region is reserved on
    top of the payload capacity, so payload and table never overlap.

Array placement: in multi-process mode ``jax.device_put`` cannot target a
sharding spanning non-addressable devices; :func:`put_global` switches to
``jax.make_array_from_callback`` (each process materializes its own
shards), and engine state uploads go through :func:`replicate_global`.
"""
from __future__ import annotations

import logging
from typing import Any, Callable

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import NamedSharding, PartitionSpec as P

logger = logging.getLogger(__name__)

HEADER = 8
OP_SHUTDOWN = 0
OP_PREFILL = 1
OP_DECODE = 2
OP_PREFILL_PART = 3
OP_SPEC = 4

# Token capacity cap per frame: keeps the FIXED frame width small even when
# the prefill bucket is the whole max_seq_len (seq-parallel engines) — a
# long prompt is shipped as OP_PREFILL_PART segments followed by the final
# OP_PREFILL, instead of sizing every frame (decode bursts included) to S.
TOKEN_FRAME_CAP = 2048


def is_multihost() -> bool:
    return jax.process_count() > 1


def is_coordinator() -> bool:
    return jax.process_index() == 0


def put_global(arr: np.ndarray, sharding: NamedSharding) -> jax.Array:
    """device_put that also works when `sharding` spans processes: every
    process must hold the SAME full `arr` (replicated host state) and
    contributes its addressable shards."""
    if jax.process_count() == 1:
        return jax.device_put(arr, sharding)
    return jax.make_array_from_callback(
        np.shape(arr), sharding, lambda idx: np.asarray(arr)[idx])


def replicate_global(arr: np.ndarray, mesh) -> Any:
    """Fully-replicated global array from identical per-process host data
    (engine slot state: tokens/lengths/active/sampling)."""
    return put_global(np.asarray(arr), NamedSharding(mesh, P()))


def zeros_global(shape: tuple, dtype, sharding: NamedSharding) -> jax.Array:
    """Sharded zeros without materializing the full array on any host
    (KV-cache init: each process only builds its own shards)."""
    if jax.process_count() == 1:
        return jax.device_put(jnp.zeros(shape, dtype), sharding)

    def shard(idx):
        size = tuple((sl.stop if sl.stop is not None else dim) -
                     (sl.start or 0)
                     for sl, dim in zip(idx, shape))
        return np.zeros(size, dtype)
    return jax.make_array_from_callback(shape, sharding, shard)


class HostBridge:
    """Publishes engine ops from the coordinator; replays them on followers.

    Single-process mode: ``enabled`` is False and every publish_* is a
    no-op, so the engine's hot path carries no conditional cost beyond one
    attribute check.
    """

    def __init__(self, batch_size: int, prefill_bucket_max: int,
                 table_slots: int = 0):
        self.enabled = is_multihost()
        self._shutdown_sent = False
        self.B = batch_size
        # Paged KV: the [B, table_slots] page table rides at the TAIL of any
        # command whose frame sets the has-table flag (cmd[3]) — followers
        # have no allocator, so table changes reach them in the same stream
        # that orders every compiled call (VERDICT r1 item 5).
        self.table_size = batch_size * table_slots
        self.table_slots = table_slots
        # Payload must fit the larger of: one prefill token segment (capped
        # — longer chunks ship as multiple frames), or the packed decode
        # state (4 int + 4 float vectors of B, + 2 key), plus the page
        # table tail.
        self.token_capacity = max(min(prefill_bucket_max, TOKEN_FRAME_CAP),
                                  8 * batch_size + 2)
        self.payload = self.token_capacity + self.table_size
        self.width = HEADER + self.payload
        if self.enabled:
            logger.info(
                "multihost bridge: %d processes, command width %d",
                jax.process_count(), self.width)

    # -- wire helpers ---------------------------------------------------------
    def _broadcast(self, cmd: np.ndarray | None) -> np.ndarray:
        from jax.experimental import multihost_utils
        if cmd is None:
            cmd = np.zeros((self.width,), np.int32)
        assert cmd.shape == (self.width,)
        return np.asarray(multihost_utils.broadcast_one_to_all(cmd))

    def _frame(self, opcode: int, a: int = 0, b: int = 0,
               payload: np.ndarray | None = None,
               table: np.ndarray | None = None) -> np.ndarray:
        cmd = np.zeros((self.width,), np.int32)
        cmd[0], cmd[1], cmd[2] = opcode, a, b
        if payload is not None:
            cmd[4] = len(payload)
            cmd[HEADER:HEADER + len(payload)] = payload
        if table is not None:
            assert table.size == self.table_size
            cmd[3] = 1                               # has-table flag
            cmd[self.width - self.table_size:] = table.ravel()
        return cmd

    def _parse_table(self, cmd: np.ndarray) -> np.ndarray | None:
        if not cmd[3]:
            return None
        return (cmd[self.width - self.table_size:]
                .reshape(self.B, self.table_slots).copy())

    # -- coordinator side -----------------------------------------------------
    def _check_live(self) -> None:
        """After SHUTDOWN the followers have exited their replay loop: any
        further broadcast would block forever inside the collective (1 of N
        participants), hanging the worker thread with no error. The bridge
        is therefore TERMINAL once shut down — fail loudly instead."""
        if self._shutdown_sent:
            raise RuntimeError(
                "multihost bridge is shut down; the engine cannot be "
                "restarted in multihost mode (followers already exited)")

    def publish_prefill(self, slot: int, pos: int, tokens: np.ndarray,
                        table: np.ndarray | None = None) -> None:
        """The compile bucket is NOT on the wire: every process derives it
        from (pos, len(tokens)) + engine config, so it cannot diverge.
        Chunks longer than one frame's token capacity ship as PART frames
        the follower reassembles in order."""
        if not self.enabled:
            return
        self._check_live()
        t = tokens.astype(np.int32)
        cap = self.token_capacity
        while len(t) > cap:
            self._broadcast(self._frame(OP_PREFILL_PART, slot, pos,
                                        payload=t[:cap]))
            t = t[cap:]
        self._broadcast(self._frame(OP_PREFILL, slot, pos, payload=t,
                                    table=table))

    def pack_decode_state(self, lengths, active, last_token, top_k,
                          temperature, top_p, presence, frequency,
                          key) -> np.ndarray:
        B = self.B
        out = np.empty((8 * B + 2,), np.int32)
        out[0 * B:1 * B] = lengths
        out[1 * B:2 * B] = np.asarray(active, np.int32)
        out[2 * B:3 * B] = last_token
        out[3 * B:4 * B] = top_k
        out[4 * B:5 * B] = np.asarray(temperature, np.float32).view(np.int32)
        out[5 * B:6 * B] = np.asarray(top_p, np.float32).view(np.int32)
        out[6 * B:7 * B] = np.asarray(presence, np.float32).view(np.int32)
        out[7 * B:8 * B] = np.asarray(frequency, np.float32).view(np.int32)
        out[8 * B:] = np.asarray(key, np.uint32).view(np.int32)
        return out

    def unpack_decode_state(self, payload: np.ndarray):
        B = self.B
        return dict(
            lengths=payload[0 * B:1 * B].copy(),
            active=payload[1 * B:2 * B].astype(bool),
            last_token=payload[2 * B:3 * B].copy(),
            top_k=payload[3 * B:4 * B].copy(),
            temperature=payload[4 * B:5 * B].view(np.float32).copy(),
            top_p=payload[5 * B:6 * B].view(np.float32).copy(),
            presence=payload[6 * B:7 * B].view(np.float32).copy(),
            frequency=payload[7 * B:8 * B].view(np.float32).copy(),
            key=payload[8 * B:8 * B + 2].view(np.uint32).copy(),
        )

    def publish_decode(self, n_steps: int, state: np.ndarray,
                       table: np.ndarray | None = None) -> None:
        if not self.enabled:
            return
        self._check_live()
        self._broadcast(self._frame(OP_DECODE, n_steps, payload=state,
                                    table=table))

    def publish_spec(self, n_steps: int, reupload: bool, state: np.ndarray,
                     table: np.ndarray | None = None,
                     probe: bool = False) -> None:
        # Flags int: bit 0 = reupload, bit 1 = probe (per-slot adaptive
        # drafting re-measure — the suspension mirror itself never rides
        # the wire, it evolves identically on every process).
        if not self.enabled:
            return
        self._check_live()
        flags = int(reupload) | (int(probe) << 1)
        self._broadcast(self._frame(OP_SPEC, n_steps, flags,
                                    payload=state, table=table))

    def publish_shutdown(self) -> None:
        """Idempotent: a second broadcast after followers have exited their
        replay loop would block forever in the collective."""
        if not self.enabled or self._shutdown_sent:
            return
        self._shutdown_sent = True
        self._broadcast(self._frame(OP_SHUTDOWN))

    # -- follower side --------------------------------------------------------
    def follow(self, on_prefill: Callable[..., None],
               on_decode: Callable[..., None],
               on_spec: Callable[..., None] | None = None) -> None:
        """Blocking replay loop for follower processes (process_index > 0):
        receive one command, execute the same compiled call, repeat until
        SHUTDOWN. Callbacks receive the attached page table (or None) as
        their last argument."""
        assert self.enabled and not is_coordinator()
        logger.info("follower %d: entering replay loop", jax.process_index())
        parts: list[np.ndarray] = []
        while True:
            cmd = self._broadcast(None)
            op = int(cmd[0])
            if op == OP_SHUTDOWN:
                logger.info("follower %d: shutdown", jax.process_index())
                return
            n = int(cmd[4])
            payload = cmd[HEADER:HEADER + n]
            table = self._parse_table(cmd)
            if op == OP_PREFILL_PART:
                parts.append(payload.copy())
            elif op == OP_PREFILL:
                if parts:
                    payload = np.concatenate(parts + [payload])
                    parts = []
                on_prefill(int(cmd[1]), int(cmd[2]), payload, table)
            elif op == OP_DECODE:
                on_decode(int(cmd[1]), self.unpack_decode_state(payload),
                          table)
            elif op == OP_SPEC:
                if on_spec is None:
                    raise RuntimeError(
                        "SPEC command on a non-speculative follower "
                        "(spec_draft_len mismatch across processes?)")
                on_spec(int(cmd[1]), int(cmd[2]),
                        self.unpack_decode_state(payload), table)
            else:
                raise RuntimeError(f"unknown multihost opcode {op}")
